"""The background coordination loop — heart of the runtime.

Python re-architecture of the reference's ``BackgroundThreadLoop`` /
``RunLoopOnce`` / ``PerformOperation``
(reference: horovod/common/operations.cc:662-955, 986-1338, 450-539):
one daemon thread per process paces a negotiation cycle every
``HOROVOD_CYCLE_TIME`` ms; each cycle drains this rank's request queue,
gathers all ranks' requests at the coordinator, fuses ready tensors
under the fusion threshold, broadcasts the agreed ResponseList, and
executes it through the backend priority list. Enqueue APIs return
immediately; completion flows back through per-entry callbacks
(reference: common.h:162 StatusCallback).

Hot-loop notes for TPU: the data plane executed here is an XLA
computation per fused response (see ops/xla_ops.py); this thread only
*issues* it, so the Python cycle overhead rides in the shadow of device
execution, like the reference's detached CUDA finalizer threads
(reference: ops/cuda_operations.cc:148-179).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from horovod_tpu.common import arena as harena
from horovod_tpu.common import elastic as helastic
from horovod_tpu.common import faults
from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import metrics as hmetrics
from horovod_tpu.common import overlap as hoverlap
from horovod_tpu.common import selfop
from horovod_tpu.common import steady as hsteady
from horovod_tpu.common import trace as htrace
from horovod_tpu.common import wire
from horovod_tpu.common import wire_dtype as _wd
from horovod_tpu.common.config import Config
from horovod_tpu.common.controller import Controller
from horovod_tpu.common.coordinator import (
    CACHEABLE_REQUESTS, CACHEABLE_RESPONSES, MessageTable, ResponseCache,
    StallInspector, construct_response, fuse_responses, iter_set_bits,
)
from horovod_tpu.common.invariants import world_coherent
from horovod_tpu.common.message import (
    CacheCycleRequest, CacheCycleResponse, DataType, Request, RequestList,
    RequestType, Response, ResponseList, ResponseType, datatype_size,
    datatype_to_numpy_dtype, numpy_dtype_to_datatype,
)
from horovod_tpu.common.status import (
    DUPLICATE_NAME_ERROR_FMT, SHUT_DOWN_ERROR, Status, WorldAbortedError,
    world_abort_message,
)
from horovod_tpu.common.tensor_table import (
    HandleManager, TensorTable, TensorTableEntry,
)
from horovod_tpu.common.timeline import (
    ACT_COLLECTIVE, ACT_QUEUE, NOOP_TIMELINE, create_timeline,
)
from horovod_tpu.ops.operation_manager import OperationManager


def _merge_tenant_worlds(world: Dict) -> Dict:
    """Fold the world views of every tenant whose coordinator lives
    in THIS process into a copy of the default world's view. Tenant
    series carry their tenant label, so the merge never collides;
    docs/multitenancy.md describes which surface shows which tenant."""
    from horovod_tpu.common import tenancy as _tenancy
    merged = dict(world)
    for t in _tenancy.tenants().values():
        rt = t._runtime
        agg = getattr(rt, "_aggregator", None) if rt is not None \
            else None
        if agg is None:
            continue
        try:
            agg.update_local(rt.metrics.snapshot())
            hmetrics.merge_into(merged, agg.world())
        except Exception:
            pass  # a tenant mid-teardown must not break the scrape
    return merged


class Runtime:
    """Process-global state + background thread
    (reference: HorovodGlobalState, common/global_state.h:33-136)."""

    def __init__(self, config: Config, controller: Controller,
                 op_manager: OperationManager,
                 parameter_manager=None):
        self.config = config
        self.controller = controller
        self.op_manager = op_manager
        self.tensor_table = TensorTable()
        self.handle_manager = HandleManager()
        self.parameter_manager = parameter_manager
        self.timeline = NOOP_TIMELINE
        if controller.rank == 0 and config.timeline_path:
            self.timeline = create_timeline(config.timeline_path,
                                            config.timeline_mark_cycles)
        op_manager.attach_timeline(self.timeline)
        # Tenancy (common/tenancy.py): a tenant sub-world stamps every
        # cycle frame with its world id (wire.stamp_world) and paces
        # its coordinator-bound cycles through the process-local
        # tenant scheduler lane bound by bind_tenant_lane. world_id 0
        # (the default world) keeps the wire byte-identical to every
        # earlier build and every hook a no-op.
        self._world_id = int(getattr(config, "world_id", 0))
        self._tenant = getattr(config, "tenant_name", "")
        # Lane binding races teardown (bind arrives from the tenant
        # attach path while an abort is unwinding on the background
        # loop): the lock makes bind-vs-unregister atomic and the
        # closed flag keeps a late bind from resurrecting a lane on a
        # dead runtime — the scheduler would hold it forever.
        self._lane_lock = lockdep.lock("runtime.Runtime._lane_lock")
        self._lane_closed = False
        self._tenant_lane = None
        self._dtypes: Dict[str, DataType] = {}
        # name -> elements per dim-0 row, for allgather fusion byte
        # accounting (reference: TotalByteSizeOfAllgatherOutput).
        self._slice_numels: Dict[str, int] = {}
        self._stall = StallInspector(
            controller.size,
            warning_time=config.stall_check_time_seconds,
            shutdown_time=config.stall_shutdown_time_seconds,
            disabled=config.stall_check_disable)
        # A completed negotiation clears its stall-warning record so a
        # RECURRING tensor name that stalls again warns again.
        self._message_table = MessageTable(
            on_remove=self._stall.tensor_completed) \
            if controller.rank == 0 else None
        # Async completion: backends that return InProgress complete on
        # detached finalizer threads while this loop keeps negotiating
        # (reference: cuda_operations.cc:148-179).
        self.finalizer = None
        if config.async_completion:
            from horovod_tpu.common.finalizer import Finalizer
            self.finalizer = Finalizer()
            op_manager.attach_finalizer(self.finalizer)
        self._shutdown_requested = threading.Event()
        self._done = threading.Event()
        self._teardown_started = False
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[Exception] = None
        # (origin_rank, cause) once the world has aborted: handles that
        # were in flight or are enqueued afterwards fail with a
        # structured WorldAbortedError instead of a generic shutdown.
        self._abort_info: Optional[tuple] = None
        # Lifetime count of executed responses (fault-injection op
        # triggers key off it to land failures squarely mid-collective).
        self._op_count = 0
        faults.load_env()
        # Autotune plumbing: bytes reduced this cycle.
        self._cycle_bytes = 0
        # Monotone id for async-nestable timeline batches.
        self._batch_seq = 0
        # Idle backoff: after _IDLE_GRACE empty cycles the loop ramps
        # its sleep toward config.idle_backoff_ms instead of spinning
        # the negotiation at full cycle rate forever (the reference
        # wakes every cycle_time_ms regardless, operations.cc:987-995 —
        # needless wakeups on a TPU host whose hot path is in-jit).
        # ``_wake`` snaps the loop awake the moment work arrives or
        # shutdown is requested, so pickup latency IMPROVES over a
        # fixed cycle; each rank's sleep is local, and a straggling
        # rank only delays the blocking gather, never deadlocks it.
        self._idle_cycles = 0
        self._cycle_count = 0  # lifetime cycles (observability/tests)
        self._wake = threading.Event()
        # Steady-state negotiation fast path: a world-coherent LRU of
        # negotiated responses; hit cycles exchange one bit per cache
        # slot instead of serialized Request lists (HOROVOD_CACHE_*,
        # docs/performance.md). All knobs must match across ranks —
        # the frame kinds and epochs fail fast on divergence.
        self._cache: Optional[ResponseCache] = None
        # The cache stays ON under autotune: cached replays would pin
        # every steady tensor to the (algorithm, wire dtype) verdict
        # of its FIRST negotiation, so whenever the tuner's active
        # combo changes the coordinator force-evicts every cached
        # allreduce verdict world-wide through the broadcast invalid
        # mask (_stale_plan_slots) — the tensors renegotiate under
        # the new plan and the tuner measures what it steers.
        if config.cache_enabled and config.cache_capacity > 0:
            # Elastic worlds seed the epoch from the world generation:
            # every post-resize rank starts at the SAME (bumped) epoch,
            # so the response cache, steady predictor, replay plans
            # and native steady plans of the old world all invalidate
            # through the existing epoch machinery.
            self._cache = ResponseCache(
                config.cache_capacity,
                epoch0=helastic.generation() << 32)
        # name -> (signature, dtype, slice_numel) recorded when a
        # cacheable request is sent the FULL way; consumed when its
        # negotiated response comes back and populates the cache.
        self._pending_sigs: Dict[str, tuple] = {}
        # (grant_mask, threshold) -> fused replay plan, valid for one
        # cache epoch: the steady state replays the SAME grant every
        # cycle, so the per-cycle fuse pass collapses to a dict hit.
        self._replay_plans: Dict[tuple, List[Response]] = {}
        self._replay_epoch = -1
        # (epoch, hit_mask) -> serialized cycle frame: steady-state
        # cycles send the SAME all-hit frame every time — skip
        # re-serializing it (epoch in the key invalidates on any
        # structural cache event).
        self._frame_memo: Dict[tuple, bytes] = {}
        # name -> monotonic time its cache hit first went un-granted;
        # after _BIT_DEMOTE_S the request falls back to the full path
        # so the coordinator's stall machinery (warnings, shutdown
        # blame) sees it exactly as it would without the cache.
        self._bit_pending_since: Dict[str, float] = {}
        self._cached_cycles = 0  # cycles negotiated purely via bitmask
        # Fused speculative cycle (HOROVOD_CACHE_SPECULATIVE): once a
        # pure-hit cycle is FULLY granted, its mask becomes a steady
        # prediction — the next identical cycle sends its pre-packed
        # fused allreduce buffers WITH the bitmask, and the coordinator
        # reduces inline and broadcasts grant + result in one frame:
        # negotiation + data plane in a single world round-trip. Any
        # deviation on any rank degrades that cycle to the classic
        # two-round cached path (the payload is simply ignored).
        # Under autotune, speculation is gated per-PHASE
        # (ParameterManager.spec_safe): live through the discrete
        # grid phase — so per-combo scores measure the DEPLOYMENT
        # regime, spec cycle included — and after convergence, but
        # off while the Bayesian phase steers fusion/cycle parameters
        # through full-response trailers that speculation would
        # starve. The gate is coordinator-side (a spec round needs
        # the coordinator's own bid), so a worker's view of the
        # phase never has to be synchronized.
        self._spec_ok = (self._cache is not None
                         and config.cache_speculative)
        # Recently fully-granted pure-hit masks -> their name sets
        # (insertion-ordered, capped): the steady-state predictions,
        # doubling as the burst-hold's (_absorb_burst) reference sets.
        # More than one set stays steady in real loops — double-
        # buffered training alternates two gradient buckets, periodic
        # metrics add an every-N-steps set — and each deserves the
        # fused round. Slot-based, so any structural cache event
        # (epoch move) invalidates them all. Epoch-coupled predictions
        # are world-replicated state: they may only move on broadcast
        # verdicts, which hvdlint's world-coherence analyzer enforces.
        self._steady: "OrderedDict[int, frozenset]" = \
            OrderedDict()  # hvdlint: world-replicated
        self._steady_epoch = -1  # hvdlint: world-replicated
        # The coordinator's effective fusion threshold, broadcast on
        # cached-cycle responses: replay and speculative packing must
        # fuse with the WORLD's value, not this rank's local config
        # (a divergent HOROVOD_FUSION_THRESHOLD would otherwise build
        # mismatched batches from the same grant). World-replicated:
        # only the broadcast verdict may move it.
        self._world_fusion_threshold = \
            config.fusion_threshold_bytes  # hvdlint: world-replicated
        # Wire-dtype compression (common/wire_dtype.py): this rank's
        # PROPOSAL, attached to every compressible allreduce Request;
        # the coordinator's resolved verdict rides each Response (and
        # the cache with it), so the applied dtype is world-coherent
        # by the same broadcast that makes the negotiation coherent.
        self._wire_propose = _wd.wire_code_of(config.compression)
        t = getattr(controller, "topology", None)
        self._multi_host = (t is not None
                            and t.local_size < t.size)
        # -- ICI-native data plane (HOROVOD_TPU_ICI, ops/xla_ops.py) ---
        # The fused-psum steady cycle: ALG_ICI-stamped buckets pack/
        # prescale/cast through ONE pre-compiled XLA executable over
        # the local device mesh, and the resulting wire buffer rides
        # the existing compressed socket/ring plane cross-slice. The
        # capability is world-AND-agreed HERE — a fixed init position
        # every rank reaches exactly once, right after the controller
        # handshake — so a single mesh-less rank degrades the verdict
        # to the socket plane everywhere, together. (HOROVOD_TPU_ICI
        # itself must be set world-wide, like HOROVOD_TWO_LEVEL.)
        self._ici_plane = None
        self._ici_cycles = 0
        if config.ici_enabled and controller.size > 1:
            from horovod_tpu.ops.xla_ops import IciPlane
            plane = IciPlane(config.ici_devices)
            local_ok = plane.probe()
            if controller.agree(local_ok):
                self._ici_plane = plane
            elif controller.rank == 0:
                hlog.warning(
                    "HOROVOD_TPU_ICI=1 degraded to the socket plane: "
                    "at least one rank has no local multi-device mesh "
                    "(needs >= 2 devices; set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=N for a "
                    "CPU-mesh run)")
        # Algorithm/dtype policy consulted when stamping fused
        # responses (coordinator only): the autotuner when armed
        # (ParameterManager.plan — per-size-bucket tuned table), the
        # static config policy otherwise.
        if parameter_manager is not None:
            self._wire_policy = parameter_manager
            parameter_manager.configure_wire(
                self._wire_propose, self._multi_host, controller.size,
                shm_enabled=config.shm_enabled,
                ring_allowed=config.ring_threshold_bytes >= 0,
                ici_allowed=self._ici_plane is not None)
            # Overlap bucket count joins the discrete grid (measured
            # between the wire sweep and the BO phase) only when the
            # overlap tier can actually engage on this rank.
            parameter_manager.configure_overlap(
                config.overlap_inflight > 0)
        else:
            self._wire_policy = _wd.StaticWirePolicy(
                config.two_level, config.two_level_threshold_bytes,
                self._multi_host, shm_enabled=config.shm_enabled,
                ici_allowed=self._ici_plane is not None,
                ici_threshold_bytes=config.ici_threshold_bytes)
            if config.two_level and controller.rank == 0 \
                    and not (self._multi_host and config.shm_enabled):
                hlog.warning(
                    "HOROVOD_TWO_LEVEL=1 has no effect: the two-level"
                    " plane needs a multi-host world with the shm"
                    " data plane enabled (HOROVOD_TPU_SHM=1)")
        # Last stamped/applied (algorithm, wire dtype) — rank-local
        # observability for the stall report.
        self._last_wire_verdict = None
        # Last wire-plan revision this coordinator stamped under: a
        # bump means the tuner moved the active combo, and every
        # cached allreduce verdict is stale — force-evicted world-wide
        # on the next cycle (see _coordinate_cycle).
        self._wire_plan_rev = 0
        # mask -> consecutive speculative bids the world answered with
        # a CLASSIC full grant: everything was granted, yet the fused
        # round was refused — the signature of a peer that will never
        # speculate (HOROVOD_CACHE_SPECULATIVE off, or a plane
        # mismatch). After _SPEC_DENY_LIMIT denials the mask stops
        # speculating, so a blessed heterogeneous-knob world does not
        # ship (and discard) the full fused payload every step
        # forever. A transient dead round (grant 0) does not count,
        # and a completed fused cycle resets the mask's slate.
        self._spec_denied: Dict[int, int] = {}
        # [(fused Response, entries, arrays)] per payload segment of
        # the spec frame in flight this cycle (build->apply, bg thread
        # only); None when the current cycle is not speculative.
        self._spec_inflight = None
        # Zero-copy native data plane (HOROVOD_TPU_ZERO_COPY,
        # common/steady.py): steady speculative cycles run as ONE
        # native call — pack into the persistent fusion arena, send
        # mask + fused payload via sendmsg, reduce in C, receive the
        # world result straight into a fresh per-step buffer. Only
        # engaged when the controller sits on a flat tier of the
        # control tree AND the native core is loaded; every deviation
        # falls back to the classic PR 3 path for that cycle, and the
        # wire format is byte-identical either way, so mixed
        # native/pure-Python worlds interoperate frame-for-frame.
        self._steady_native_ok = (config.zero_copy
                                  and self._spec_ok
                                  and controller.steady_native_ready())
        self._send_arena = harena.FusionArena()
        # -- overlap tier (HOROVOD_OVERLAP_*, common/overlap.py) -------
        # Bucketed ready-order dispatch + in-flight steady cycles: the
        # background loop SUBMITS packed zero-copy cycles to a
        # dedicated completion thread and immediately returns to
        # building the next bucket's frame, so collective wire time
        # hides under backward compute. Rank-local scheduling only —
        # the wire protocol is unchanged, heterogeneous knobs degrade
        # to the synchronous path. Cycles stay strictly FIFO on the
        # wire (one native call at a time on the runner thread), and
        # every world-replicated mutation still happens on THIS
        # thread, at drain, in submission order.
        self._overlap: Optional[hoverlap.OverlapRunner] = None
        self._overlap_chunk = max(0, config.overlap_chunk_bytes)
        if config.overlap_inflight > 0 and self._steady_native_ok:
            self._overlap = hoverlap.OverlapRunner(
                controller.steady_spec_cycle,
                config.overlap_inflight,
                on_complete=self._wake.set)
        self._overlap_seq = 0
        self._overlap_hold_deadline = None  # empty-queue hold expiry
        self._overlap_cycles = 0  # completed overlapped cycles
        self._overlap_buckets_submitted = 0
        # Submission-ordered masks of cycles in flight on the runner:
        # the world-coherent cycle ORDER — every rank submits the same
        # masks in the same (program) order, and verdicts apply in
        # that order at drain. Mutated only on broadcast-driven paths.
        self._inflight_masks: List[int] = []  # hvdlint: world-replicated
        # Steady predictor depth: each overlap bucket needs its own
        # steady mask to stay resident or speculation thrashes. Any
        # bucketing source counts — the static knob, a byte-derived
        # count, or the autotuner's choice (armed via overlap_inflight)
        # — and all of them are bounded by MAX_BUCKETS, so size for
        # that worst case whenever bucketing can engage at all.
        self._steady_cap = (2 * hoverlap.MAX_BUCKETS
                            if (self._overlap is not None
                                or config.overlap_buckets > 0
                                or config.overlap_bucket_bytes > 0
                                or config.overlap_inflight > 0)
                            else 8)
        # Intended bucket name-sets from bucketed grouped submissions
        # (rank-local scheduling hint; identical everywhere because
        # the split is a pure function of the identical submission):
        # _split_buckets peels pops at these boundaries from the very
        # first cycle, so each bucket negotiates — and learns its
        # steady mask — separately even when the training thread gets
        # ahead of the wire. Snapshot-swapped, never mutated in place
        # (enqueue threads write, the background thread reads).
        self._bucket_sets: frozenset = frozenset()
        # (mask, threshold) -> SteadyPlan, valid for one cache epoch.
        self._steady_plans: Dict[tuple, hsteady.SteadyPlan] = {}
        self._steady_plan_epoch = -1
        # (plan, packed buffers) for the native cycle in flight this
        # step (build->cycle, bg thread only).
        self._spec_steady = None
        self._native_steady_cycles = 0
        self._spec_cycles = 0  # cycles completed via the fused round
        self._spec_bids = 0    # speculative frames sent (observability)
        # Hits the last cycle bid but the world did not grant, now
        # requeued: their peers were already granted and will not be
        # re-enqueued, so they must never trigger a burst hold.
        self._requeued_names: frozenset = frozenset()
        # Monotonic count of speculative bids the world answered with
        # a classic full grant (per-mask slates in _spec_denied reset
        # on success; observability wants the lifetime total).
        self._spec_denials_total = 0

        # -- metrics plane (HOROVOD_TPU_METRICS, common/metrics.py) ----
        # Disabled (the default) hands every call site the shared
        # no-op metric — same zero-overhead contract as _NoOpTimeline;
        # _metrics_on additionally gates the extra clock reads so the
        # disabled hot path does not even pay a time.monotonic().
        self.metrics = hmetrics.create_registry(config.metrics_enabled,
                                                tenant=self._tenant)
        self._metrics_on = bool(config.metrics_enabled)
        reg = self.metrics
        self._m_cycle_s = reg.histogram(
            "hvd_cycle_seconds", "negotiation cycle wall time")
        self._m_negotiation_s = reg.histogram(
            "hvd_negotiation_seconds",
            "request gather -> response broadcast round trip")
        self._m_cycles = reg.counter("hvd_cycles_total")
        self._m_cached_cycles = reg.counter(
            "hvd_cached_cycles_total",
            "cycles negotiated purely via the cache bitmask")
        self._m_spec_cycles = reg.counter(
            "hvd_fused_spec_cycles_total",
            "single-round fused speculative cycles completed")
        self._m_spec_bids = reg.counter("hvd_spec_bids_total")
        self._m_spec_denials = reg.counter("hvd_spec_denials_total")
        self._m_native_steady = reg.counter(
            "hvd_native_steady_cycles_total",
            "steady steps completed by the one-call native data plane")
        self._m_arena_bytes = reg.gauge(
            "hvd_arena_bytes",
            "capacity of the persistent fusion arenas on this rank")
        self._m_data_copies = reg.counter(
            "hvd_data_copies_total",
            "payload byte-object copies on fallback data paths "
            "(0 while the zero-copy plane is engaged)")
        # Wire-compression plane (same counter objects as the socket
        # backend's module hooks — the registry memoizes by name).
        self._m_wire_saved = reg.counter(
            "hvd_wire_bytes_saved_total",
            "payload bytes kept OFF the wire by the negotiated "
            "wire dtype (uncompressed minus wire size, per send)")
        self._m_comp_ratio = reg.histogram(
            "hvd_compression_ratio",
            "wire bytes / uncompressed bytes per compressed payload",
            hmetrics.RATIO_BUCKETS)
        # Overlap-tier plane (docs/performance.md Layer 5).
        self._m_overlap_fraction = reg.histogram(
            "hvd_overlap_fraction",
            "per overlapped cycle: fraction of its wire time hidden "
            "under compute (1.0 = the loop never blocked on it)",
            hmetrics.RATIO_BUCKETS)
        self._m_inflight = reg.gauge(
            "hvd_inflight_cycles",
            "steady cycles outstanding on the overlap runner",
            agg=hmetrics.AGG_MAX)
        self._m_overlap_buckets = reg.counter(
            "hvd_overlap_buckets_total",
            "gradient buckets submitted by bucketed grouped dispatch")
        self._m_overlap_cycles = reg.counter(
            "hvd_overlap_cycles_total",
            "steady cycles completed through the overlap runner")
        self._m_cache_hits = reg.counter("hvd_cache_hits_total")
        self._m_cache_misses = reg.counter("hvd_cache_misses_total")
        self._m_cache_evictions = reg.counter(
            "hvd_cache_evictions_total")
        self._m_cache_entries = reg.gauge("hvd_cache_entries")
        self._m_queue_depth = reg.gauge(
            "hvd_tensor_queue_depth",
            "in-flight collectives tabled on this rank")
        self._m_burst_hold_s = reg.counter(
            "hvd_burst_hold_seconds_total",
            "time spent absorbing enqueue bursts")
        self._m_idle_hold_s = reg.counter(
            "hvd_idle_hold_seconds_total",
            "time spent in the steady-state idle hold")
        self._m_timeline_dropped = reg.counter(
            "hvd_timeline_dropped_events_total")
        self._m_lock_inversions = reg.counter(
            "hvd_lockcheck_inversions_total",
            "lock-order inversions observed by the runtime lockdep "
            "(HOROVOD_TPU_LOCKCHECK; 0 when unarmed)")
        self._m_affinity_violations = reg.counter(
            "hvd_threadcheck_violations_total",
            "thread-affinity violations observed by the runtime "
            "sanitizer (HOROVOD_TPU_THREADCHECK; 0 when unarmed)")
        # -- elastic worlds (HOROVOD_ELASTIC, common/elastic.py) -----
        # The context survives re-inits; each new Runtime generation
        # mirrors its counters so resize history rides the PR 4 plane.
        self._elastic = helastic.context()
        self._elastic_last_poll = 0.0
        self._m_world_size = reg.gauge(
            "hvd_world_size",
            "current world size (max-aggregated: the world view IS "
            "the size)", agg=hmetrics.AGG_MAX)
        self._m_world_resizes = reg.counter(
            "hvd_world_resizes_total",
            "elastic re-rendezvous barriers run by this rank as the "
            "(elected) coordinator")
        self._m_elastic_rejoins = reg.counter(
            "hvd_elastic_rejoins_total",
            "workers admitted into a resized world by this rank's "
            "rendezvous barriers")
        self._m_rdzv_s = reg.histogram(
            "hvd_elastic_rendezvous_seconds",
            "wall time from entering elastic recovery to holding a "
            "new world assignment")
        # -- self-operation (HOROVOD_SELFOP, common/selfop.py) -------
        # Policy is process-lifetime (decision counters and demotion
        # memory span generations); the runtime wires its telemetry
        # and wake event into it each re-init.
        self._selfop_policy = selfop.ensure_policy(controller.rank)
        self._selfop_last_tick = 0.0
        selfop.install_signal_handler(self._wake.set)
        self._selfop_decision_metrics: Dict[str, object] = {}
        self._scaling_eff_metrics: Dict[int, object] = {}
        self._m_sync_s = reg.histogram(
            "hvd_rejoin_sync_seconds",
            "wall time of each fast rejoin state sync "
            "(common/selfop.py chunked tree broadcast)")
        self._m_sync_bytes = reg.counter(
            "hvd_rejoin_sync_bytes_total",
            "payload bytes this rank moved through fast rejoin syncs")
        self._m_ckpt_age = reg.gauge(
            "hvd_checkpoint_age_seconds",
            "age of this rank's newest committed async checkpoint "
            "shard (-1 before the first write)")
        # The fused speculative cycle bypasses OperationManager, so the
        # runtime owns its share of the allreduce op/byte totals (the
        # registry memoizes by name — these are the SAME counters the
        # OperationManager increments on the classic path).
        self._m_bytes_allreduced = reg.counter(
            "hvd_bytes_allreduced_total")
        self._m_ops_allreduce = reg.counter(
            'hvd_ops_total{op="allreduce"}')
        self.timeline.attach_drop_counter(self._m_timeline_dropped)
        controller.attach_metrics(reg)
        op_manager.attach_metrics(
            reg, lambda: self._world_fusion_threshold)
        if self._ici_plane is not None:
            self._ici_plane.attach_metrics(reg)
        # Rank-0 world aggregation + read surfaces: control-tree
        # METRICS frames fold here, exposed over Prometheus HTTP
        # (HOROVOD_TPU_METRICS_PORT), a JSONL snapshot log
        # (HOROVOD_TPU_METRICS_LOG) and horovod_tpu.metrics().
        self._aggregator = None
        self._metrics_http = None
        self._metrics_log = None
        self._metrics_last_pub = 0.0
        if self._metrics_on:
            reg.add_collector(self._collect_runtime_metrics)
            if controller.rank == 0:
                self._aggregator = hmetrics.WorldAggregator(
                    controller.size)
                controller.metrics_sink = self._aggregator.ingest
                if config.metrics_port >= 0:
                    world_fn = self._aggregator.world
                    if not self._world_id:
                        # The fleet's /metrics also scrapes its
                        # co-located tenants (series are
                        # tenant-labelled; see metrics_view).
                        world_fn = (lambda base=self._aggregator.world:
                                    _merge_tenant_worlds(base()))
                    self._metrics_http = hmetrics.MetricsHTTPServer(
                        world_fn, config.metrics_port,
                        host=config.metrics_addr)
                if config.metrics_log:
                    self._metrics_log = hmetrics.JsonlMetricsLog(
                        config.metrics_log)
            # Info-style build identity (value always 1; the labels
            # ARE the payload): postmortems and dashboards can tell
            # WHICH build + knob set produced a dump or a regression.
            bi = htrace.build_info()
            reg.gauge(
                f'hvd_build_info{{version="{bi["version"]}",'
                f'native="{bi["native"]}",knobs="{bi["knobs"]}",'
                f'flags="{bi["flags"]}"}}',
                "build identity: package version, native .so build "
                "hash, armed-knobs digest, kernel-feature flags "
                "(io_uring/zerocopy; value is always 1)",
                agg=hmetrics.AGG_MAX).set(1)

        # -- world trace plane (HOROVOD_TPU_TRACE, common/trace.py) ----
        # Flight recorder first: ON BY DEFAULT (no-op writes when
        # HOROVOD_TPU_FLIGHT=0), process-lifetime singleton so a
        # postmortem spans elastic generations.
        self._flight = htrace.flight()
        if self._world_id:
            # Tenant sub-world: the process-lifetime recorder keeps
            # the default world's rank identity; tenants register in
            # the header's worlds map instead.
            self._flight.note_world(self._world_id, self._tenant,
                                    controller.rank)
        else:
            self._flight.set_identity(controller.rank)
        htrace.install_sigusr2()
        # Span collection + the world-identical cycle sequence number.
        self._trace = htrace.create_collector(bool(config.trace_path),
                                              tenant=self._tenant)
        self._trace_on = self._trace.enabled
        self._world_cycle = 0
        self._trace_last_pub = 0.0
        self._trace_spans_sent = 0
        self._m_trace_spans = reg.counter(
            "hvd_trace_spans_total",
            "trace spans this rank shipped (or wrote, on rank 0) "
            "into the world trace plane")
        self._trace_writer = None
        # Straggler attribution lives on rank 0 and arms whenever
        # EITHER observability plane is on (the metrics series are
        # no-ops without the registry, but the stall-report line and
        # the merged trace both want the arrival stamps).
        self._straggler = None
        if controller.rank == 0:
            if self._trace_on:
                # An elastic re-init constructs a fresh writer over
                # the same knob; suffix post-resize generations so the
                # just-finalized trace of the ABORTED world — the
                # artifact worth inspecting — is never truncated.
                trace_path = config.trace_path
                try:
                    from horovod_tpu.common import elastic as _elastic
                    gen = _elastic.generation()
                except Exception:
                    gen = 0
                if gen:
                    trace_path = f"{trace_path}.gen{gen}"
                if self._world_id:
                    # A tenant's rank-0 writer must never share (and
                    # truncate) the default world's file — same
                    # collision class the .genN suffix solves for
                    # elastic re-inits.
                    trace_path = (f"{trace_path}."
                                  f"{self._tenant or hex(self._world_id)}")
                self._trace_writer = htrace.WorldTraceWriter(trace_path)
                controller.trace_sink = self._trace_writer.ingest
            if self._metrics_on or self._trace_on:
                self._straggler = htrace.StragglerTracker(reg)
                controller.attach_trace(
                    on_arrivals=self._straggler.note_gather)
        elif self._trace_on:
            # Workers: arm the clock-echo half (PING noting).
            controller.attach_trace()

    @property
    def _spec_enabled(self) -> bool:
        pm = self.parameter_manager
        return self._spec_ok and (pm is None or pm.spec_safe)

    @property
    def _steady_native(self) -> bool:
        return self._steady_native_ok and self._spec_enabled

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._background_loop,
                                        name="hvd-background",
                                        daemon=True)
        self._thread.start()

    def request_shutdown(self) -> None:
        self._shutdown_requested.set()
        self._wake.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._done.is_set())

    def _terminal_status(self) -> Status:
        """Status for work that can no longer run: a structured abort
        (naming the failed rank) when the world was torn down by the
        fail-fast protocol, the plain shutdown error otherwise."""
        if self._abort_info is not None:
            origin, cause = self._abort_info
            return Status.WorldAborted(origin, cause)
        return Status.Aborted(SHUT_DOWN_ERROR)

    # -- enqueue APIs (reference: operations.cc:1430-1549) ---------------
    def enqueue(self, request_type: RequestType, entry: TensorTableEntry,
                dtype: DataType, shape, prescale: float = 1.0,
                postscale: float = 1.0) -> Status:
        if self._done.is_set() or self._shutdown_requested.is_set():
            return self._terminal_status()
        req = Request(request_rank=self.controller.rank,
                      request_type=request_type,
                      tensor_type=dtype,
                      tensor_name=entry.tensor_name,
                      root_rank=entry.root_rank,
                      device=entry.device,
                      tensor_shape=shape,
                      prescale_factor=prescale,
                      postscale_factor=postscale,
                      wire_dtype=self._propose_wire(request_type,
                                                    dtype))
        entry.request_type = request_type
        if not self.tensor_table.add(entry, req):
            return Status.InvalidArgument(
                DUPLICATE_NAME_ERROR_FMT
                % (request_type.name.lower(), entry.tensor_name))
        if self._done.is_set():
            # The loop exited between the liveness check and the add; the
            # shutdown fan-out may have missed this entry — reclaim it so
            # its handle cannot hang forever.
            if self.tensor_table.pop_entry_if_present(entry.tensor_name):
                return self._terminal_status()
        if self._tenant_lane is not None:
            # Backlog hint for the QoS scheduler: queued work makes
            # this tenant a contender NOW, not only once its cycle
            # loop reaches acquire (benign unlocked write — the
            # acquire path re-asserts it under the lock).
            self._tenant_lane.want = True
        if not self._wake.is_set():
            self._wake.set()  # snap an idle-backed-off loop awake
        return Status.OK()

    def enqueue_group(self, request_type: RequestType, items,
                      prescale: float = 1.0,
                      postscale: float = 1.0) -> Status:
        """Atomically enqueue several entries as one negotiation batch
        (the grouped-collective contract, later-Horovod
        ``grouped_allreduce``): every request enters the same
        RequestList on this rank, so a concurrent cycle tick cannot
        split the group, all members become ready in the same
        coordinator cycle, and compatible members fuse into ONE
        Response under the threshold. ``items`` is a list of
        (entry, dtype, shape)."""
        if self._done.is_set() or self._shutdown_requested.is_set():
            return self._terminal_status()
        pairs = []
        for entry, dtype, shape in items:
            req = Request(request_rank=self.controller.rank,
                          request_type=request_type,
                          tensor_type=dtype,
                          tensor_name=entry.tensor_name,
                          root_rank=entry.root_rank,
                          device=entry.device,
                          tensor_shape=shape,
                          prescale_factor=prescale,
                          postscale_factor=postscale,
                          wire_dtype=self._propose_wire(request_type,
                                                        dtype))
            entry.request_type = request_type
            pairs.append((entry, req))
        dup = self.tensor_table.add_all(pairs)
        if dup is not None:
            return Status.InvalidArgument(
                DUPLICATE_NAME_ERROR_FMT
                % (request_type.name.lower(), dup))
        if self._done.is_set():
            # Same liveness race as enqueue(): reclaim anything the
            # shutdown fan-out may have missed. Per-entry, because the
            # fan-out may already have completed some members — their
            # callbacks must not fire twice.
            for entry, _ in pairs:
                if self.tensor_table.pop_entry_if_present(
                        entry.tensor_name) and entry.callback:
                    entry.callback(self._terminal_status())
        if self._tenant_lane is not None:
            self._tenant_lane.want = True  # backlog hint (see enqueue)
        if not self._wake.is_set():
            self._wake.set()
        return Status.OK()

    def _propose_wire(self, request_type: RequestType,
                      dtype: DataType) -> int:
        """This rank's wire-dtype bid for one request: the configured
        compression for float32/float64 allreduces (the gradient
        path), allgathers and reducescatters — every payload-moving
        collective with a meaningful reduced-precision rendering —
        none for everything else. The coordinator min-resolves the
        world's bids per tensor (and degrades int8 allgathers to bf16,
        since a concatenated world blob cannot carry per-rank scales),
        so a divergent knob degrades the verdict instead of the
        world."""
        if self._wire_propose and dtype in _wd.COMPRESSIBLE \
                and request_type in (RequestType.ALLREDUCE,
                                     RequestType.ALLGATHER,
                                     RequestType.REDUCESCATTER):
            return self._wire_propose
        return _wd.WIRE_NONE

    def _resolve_abort(self, origin: int, cause: str) -> tuple:
        """A blame inferred from an anonymous transport error can race
        the AUTHORITATIVE notice from the rank that actually detected
        the failure — its teardown closes channels, which peers see as
        a second, misattributable failure (a ring survivor names its
        dead neighbor and collapses; this rank only sees the
        survivor's close). Sweep the control plane for a
        queued/just-arriving ABORT and defer to it — the whole world
        then converges on one origin. Failure path only; adds nothing
        to healthy cycles."""
        try:
            notice = self.controller.drain_abort_notice(0.25)
        except Exception:
            notice = None
        return notice if notice is not None else (origin, cause)

    def _data_plane_abort(self, entries, origin: int,
                          cause: str) -> WorldAbortedError:
        """Fail a mid-collective batch as a world abort: resolve the
        origin against the control plane FIRST (the callbacks complete
        user-visible handles — they must carry the converged origin),
        fire the callbacks, and return the error for the caller to
        raise into the loop-level handler."""
        origin, cause = self._resolve_abort(origin, cause)
        status = Status.WorldAborted(origin, cause)
        for en in entries:
            if en.callback:
                en.callback(status)
        err = WorldAbortedError(world_abort_message(origin, cause),
                                origin_rank=origin, cause=cause)
        err.resolved = True  # _fail_world: don't re-drain
        return err

    def _fail_world(self, origin: int, cause: str,
                    resolved: bool = False) -> None:
        """Record the world abort and fan the notice to every
        reachable peer (see _resolve_abort for why an unresolved blame
        is checked against the control plane before committing)."""
        if not resolved:
            origin, cause = self._resolve_abort(origin, cause)
        self._error = WorldAbortedError(
            world_abort_message(origin, cause), origin_rank=origin,
            cause=cause)
        self._abort_info = (origin, cause)
        hlog.error(f"horovod_tpu world aborted: {self._error}",
                   rank=self.controller.rank)
        self._flight.record(htrace.EV_ABORT, self._world_cycle,
                            arg=origin, note=cause[:200])
        if self._trace_on:
            self._trace.mark("ABORT", time.monotonic(),
                             self._world_cycle)
        try:
            self.controller.abort(origin, cause)
        except Exception:
            pass
        # Postmortem AFTER the abort fan-out: file I/O must not delay
        # the notice the survivors' deadlines are waiting on. The dump
        # ships the last N seconds of world history (final cycles,
        # the abort, any elastic/stall events) with nothing armed.
        self._flight.dump(cause=cause, origin=origin)

    # -- the loop --------------------------------------------------------
    def _background_loop(self) -> None:
        threadcheck.register_role("hvd-background")
        try:
            while self._run_loop_once():
                pass
        except WorldAbortedError as e:
            # Either received over the wire (a peer initiated the
            # abort) or raised locally (we detected the failure). Fan
            # the notice to every peer we can still reach — relays are
            # idempotent, so re-fanning a received abort is harmless —
            # then fail everything in flight with the structured error.
            # The BARE cause travels/persists, so each hop wraps the
            # origin banner exactly once.
            self._fail_world(e.origin_rank, getattr(e, "cause", str(e)),
                             resolved=getattr(e, "resolved", False))
        except (ConnectionError, OSError, TimeoutError) as e:
            # Transport failure nobody upstream could name: this rank
            # is the origin as far as the rest of the world knows.
            rank = self.controller.rank
            self._fail_world(rank,
                             f"transport failure on rank {rank}: {e}")
        except Exception as e:  # backend bug, ...
            self._error = e
            hlog.error(f"horovod_tpu background loop failed: {e!r}",
                       rank=self.controller.rank)
        finally:
            self._teardown()

    def _teardown(self) -> None:
        """Tear the runtime down — re-entrant AND stage-guarded.

        Re-entrant: the background loop's ``finally`` calls this, and
        elastic recovery (common/elastic.py) may call it again while
        draining a dead world; a SECOND abort raised during recovery
        (e.g. a WorldAbortedError surfacing from a native
        hvd_steady_worker/hvd_steady_coord teardown path) must find a
        no-op here, not a half-closed runtime whose finalizer drain
        wedges on re-entry. Stage-guarded: a raising finalizer drain
        or user completion callback must not skip the stages after it
        — in particular the timeline flush, or the trace of exactly
        the aborted runs you most want to inspect is left an
        unterminated JSON fragment."""
        if getattr(self, "_teardown_started", False):
            return
        self._teardown_started = True
        self._flight.record(htrace.EV_TEARDOWN, self._world_cycle)
        self._done.set()
        # Tenant lane first (stage-guarded): a dying tenant must stop
        # counting as a scheduling contender, or its co-tenants would
        # defer against a ghost until its user-level shutdown ran.
        with self._lane_lock:
            lane, self._tenant_lane = self._tenant_lane, None
            self._lane_closed = True
        # unregister OUTSIDE the lane lock: the scheduler takes its
        # own lock, and the attach path (scheduler -> bind_tenant_lane
        # -> lane lock) already fixes the opposite nesting order.
        if lane is not None:
            try:
                from horovod_tpu.common import tenancy as _tenancy
                _tenancy.scheduler().unregister(lane)
            except Exception:
                pass
        # Overlap runner first: its thread may sit inside a native
        # cycle against channels about to close — stop accepting work,
        # let the armed recv deadline return the call, and join. Any
        # undrained cycle's entries are still tabled (pops happen at
        # drain), so the pop_all below fails them with the terminal
        # status like everything else in flight.
        if self._overlap is not None:
            try:
                self._overlap.stop()
            except Exception:
                pass  # stage-guarded: plans must still drop
        # Native steady state next: the plans' cached ctypes bundles
        # bind file descriptors and arena generations of the world
        # that just died — drop them before anything that could raise,
        # so a resumed (elastic) process can never replay a stale
        # plan against rebuilt channels.
        try:
            self._spec_steady = None
            self._spec_inflight = None
            self._steady_plans.clear()
        except Exception:
            pass  # stage-guarded: the finalizer must still drain
        try:
            # Drain in-flight async completions first so every
            # issued collective fires its real status, then fail
            # what was never issued (reference:
            # operations.cc:898-913).
            if self.finalizer is not None:
                self.finalizer.drain()
        except Exception as e:
            hlog.warning(f"finalizer drain failed at shutdown: "
                         f"{e!r}", rank=self.controller.rank)
        terminal = self._terminal_status()
        for entry in self.tensor_table.pop_all():
            if entry.callback:
                try:
                    entry.callback(terminal)
                except Exception:
                    pass  # user callback; teardown must continue
        try:
            self.timeline.shutdown()
        except Exception:
            pass
        # Flush the trace tail: rank 0 writes its residue and closes
        # the merged file (the JSON array must terminate — the trace
        # of exactly the aborted run is the one worth inspecting);
        # workers best-effort ship theirs while the channel may still
        # be up. Stage-guarded like everything else here.
        if self._trace_on:
            try:
                spans, dropped = self._trace.drain()
                if self._trace_writer is not None:
                    self._trace_writer.add_section(0, spans, dropped)
                    self._trace_spans_sent += len(spans)
                elif (spans or dropped or
                      getattr(self.controller, "_child_trace", None)):
                    # a local root whose own buffer drained empty must
                    # still flush its children's parked frames — the
                    # tail of an aborted run is the part worth having
                    self.controller.send_trace(
                        wire.serialize_trace_frame(
                            [{"rank": self.controller.rank,
                              "dropped": dropped,
                              "echo": htrace.clock().take_echo(),
                              "spans": spans}]))
                    self._trace_spans_sent += len(spans)
            except Exception:
                pass
        if self._trace_writer is not None:
            try:
                self._trace_writer.close()
            except Exception:
                pass  # stage-guarded: metrics/backends must still close
        if self._aggregator is not None \
                and self._metrics_log is not None:
            # Final JSONL line with rank 0's own totals exact and
            # every owner's last-received frame folded in (workers
            # tear down concurrently, so their tail interval is
            # inherently best-effort — the log is a sampled view;
            # live exactness is the API/endpoint's job).
            try:
                self._aggregator.update_local(
                    self.metrics.snapshot())
                self._metrics_log.append(self._aggregator.world())
            except Exception:
                pass
        if self._metrics_http is not None:
            try:
                self._metrics_http.close()
            except Exception:
                pass  # stage-guarded: backends must still close
        try:
            self.op_manager.close()
        except Exception:
            pass  # stage-guarded: the controller must still close
        try:
            self.controller.close()
        except Exception:
            pass

    _IDLE_GRACE = 16  # empty cycles before the backoff ramp starts

    # How long a cache hit may stay un-granted (some rank has not
    # queued that tensor yet) before it falls back to the full
    # negotiation path. Bit-queued requests never enter the
    # coordinator's MessageTable, so without this demotion a tensor a
    # rank stops submitting would stall silently — invisible to the
    # stall inspector's warnings and shutdown blame. Healthy
    # steady-state hits are granted within a cycle or two; 5 s is
    # unreachable there and negligible next to the stall thresholds.
    _BIT_DEMOTE_S = 5.0

    # Consecutive classic-full-grant answers to speculative bids of
    # one mask before that mask stops speculating (see _spec_denied).
    _SPEC_DENY_LIMIT = 3

    # Empty-queue hold while steady state is established: how long an
    # idle rank waits for its producer before initiating an empty
    # (grant-nothing) round. Capped by heartbeat_timeout/4 so a
    # silently-holding rank can never be mistaken for a dead one.
    _STEADY_IDLE_S = 0.25

    # Floor for the burst hold's total budget (_absorb_burst): the
    # hold waits at most max(2 x cycle_time, this) for the rest of the
    # step's enqueue burst, woken by each enqueue rather than by
    # polling. Generous on purpose: while a rank holds, the world is
    # blocked in the request gather waiting for its frame anyway, so
    # the hold adds latency ONLY when the steady set genuinely shrank
    # — which pays this once and then re-learns the smaller set from
    # its next grant. A fragment negotiated instead would cost far
    # more: a mispredicted speculative cycle plus an extra
    # negotiation + data round for the remainder.
    _BURST_HOLD_S = 0.02

    def _bounded_hold_s(self, multiple: float, floor_s: float,
                        cycle_ms: Optional[float] = None) -> float:
        """A hold/wait budget derived from the cycle time, clamped as
        a WHOLE under heartbeat_timeout/4: a silently-holding rank
        sends no frames, and its only proof of life is its next one —
        every hold in this loop must stay far under the peer-death
        deadline, whatever HOROVOD_CYCLE_TIME is set to. THE one
        budget rule for the burst hold, the steady idle hold and the
        overlap empty-queue hold. ``cycle_ms`` overrides the config
        value where the autotuner's tuned cycle time governs."""
        if cycle_ms is None:
            cycle_ms = self.config.cycle_time_ms
        hold = max(multiple * cycle_ms / 1000.0, floor_s)
        hb = self.config.heartbeat_timeout_s
        if hb > 0:
            hold = min(hold, hb / 4.0)
        return hold

    # -- tenancy (common/tenancy.py) -------------------------------------
    def bind_tenant_lane(self, lane) -> None:
        """Attach this runtime's lane in the process-local tenant
        scheduler: cycles with local work acquire the lane (QoS-
        weighted interleave + quota deferral, bounded far under the
        heartbeat deadline) and report their negotiated bytes back."""
        with self._lane_lock:
            if self._lane_closed:
                # Teardown already unwound: binding now would leave the
                # scheduler holding a lane no cycle loop will ever pace.
                return
            self._tenant_lane = lane

    def _stamp(self, frame: bytes) -> bytes:
        return wire.stamp_world(frame, self._world_id) \
            if self._world_id else frame

    def _unstamp(self, frame: bytes) -> bytes:
        return wire.unstamp_world(frame, self._world_id) \
            if self._world_id else frame

    def _build_request_frame(self, requests: List[Request],
                             shutting_down: bool):
        """Partition this cycle's requests into cache-bitmask bits and
        full Requests; returns (payload, bit_requests) where
        ``bit_requests`` is [(slot, request)] for the hits the grant
        mask will adjudicate."""
        cache = self._cache
        self._spec_inflight = None
        if cache is None:
            return self._stamp(wire.serialize_cycle_request(
                RequestList(requests, shutdown=shutting_down))), []
        now = time.monotonic()
        hit_mask = 0
        invalid_mask = 0
        uncached: List[Request] = []
        bit_requests: List[tuple] = []
        for req in requests:
            state, slot = cache.lookup(req)
            if state == ResponseCache.HIT:
                pending = self._bit_pending_since.get(req.tensor_name)
                if pending is None or \
                        now - pending < self._BIT_DEMOTE_S:
                    hit_mask |= 1 << slot
                    bit_requests.append((slot, req))
                    continue
                # Un-granted for too long: demote to the full path so
                # the coordinator's stall machinery sees it.
                self._bit_pending_since.pop(req.tensor_name, None)
                hlog.warning(
                    f"tensor {req.tensor_name} waited "
                    f"{now - pending:.1f}s as a cached hit without "
                    f"world agreement; falling back to full "
                    f"negotiation", rank=self.controller.rank)
            elif state == ResponseCache.INVALID:
                invalid_mask |= 1 << slot
            self._record_signature(req)
            uncached.append(req)
        if not uncached and not invalid_mask and not shutting_down:
            if hit_mask and self._spec_enabled \
                    and self._steady_epoch == cache.epoch \
                    and hit_mask in self._steady \
                    and self._spec_denied.get(hit_mask, 0) \
                    < self._SPEC_DENY_LIMIT:
                payload = self._build_spec_frame(hit_mask)
                if payload is not None:
                    return payload, bit_requests
            # Pure-hit (or empty) frame: bit-identical every
            # steady-state cycle — serialize once per (epoch, mask).
            key = (cache.epoch, hit_mask)
            payload = self._frame_memo.get(key)
            if payload is None:
                payload = self._stamp(wire.serialize_cycle_request(
                    CacheCycleRequest(
                        epoch=cache.epoch, nslots=cache.nslots,
                        hit_mask=hit_mask)))
                if len(self._frame_memo) >= 64:
                    self._frame_memo.clear()
                self._frame_memo[key] = payload
            return payload, bit_requests
        payload = self._stamp(wire.serialize_cycle_request(
            CacheCycleRequest(
                epoch=cache.epoch, nslots=cache.nslots,
                hit_mask=hit_mask, invalid_mask=invalid_mask,
                requests=uncached, shutdown=shutting_down)))
        return payload, bit_requests

    def _absorb_burst(self, requests: List[Request]) -> List[Request]:
        """Hold a cycle that caught the FRONT of an enqueue burst: a
        training step submits the steady-state set back-to-back, and a
        loop that negotiates the first fraction gets a fragment grant —
        the step's one fused batch splits into several data-plane
        rounds, every cycle re-bids the remainder, and each fragment
        pays full round-trip cost. While the popped names are all
        cache hits forming a strict subset of the last granted cycle's
        set, wait (bounded by one cycle period) for the rest of the
        burst; any non-steady name or the deadline ends the hold — a
        transition cycle pays at most one cycle_time_ms of extra
        latency, the bound pacing already imposes."""
        steady_sets = self._steady.values()
        if not steady_sets:
            return requests
        seen = {r.tensor_name for r in requests}

        def fragment() -> bool:
            # A strict subset of SOME steady set — and not exactly any
            # of them (a complete bucket must negotiate now, even if
            # it happens to sit inside a larger steady set).
            return (not any(seen == s for s in steady_sets)
                    and any(seen < s for s in steady_sets))

        if not fragment() or seen <= self._requeued_names:
            return requests
        deadline = time.monotonic() + self._bounded_hold_s(
            2, self._BURST_HOLD_S)
        while True:
            # Event-driven, not polled: clear BEFORE draining so an
            # enqueue that lands between the drain and the wait still
            # sets the event (no missed wake, no busy spin — an
            # earlier 0.5 ms polling variant of this hold cost more
            # GIL contention than the fragmentation it prevented).
            self._wake.clear()
            more = self.tensor_table.pop_messages()
            if more:
                requests.extend(more)
                seen.update(r.tensor_name for r in more)
                if not fragment():
                    return requests
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._shutdown_requested.is_set():
                return requests
            self._wake.wait(remaining)

    def _build_spec_frame(self, hit_mask: int):
        """Build a fused speculative cycle frame: the pure-hit bitmask
        PLUS this rank's pre-packed fused allreduce buffers in
        replay-plan order, or None when the batch is not speculation-
        eligible (non-allreduce entries in the steady set, a data
        plane of its own — shm/ring/XLA — would carry it, or an entry
        vanished). Entries are only PEEKED: the world may still deny
        the grant, in which case the classic path pops them later.

        With the zero-copy plane engaged, the return value is a
        SteadyPlan (packed into the persistent fusion arena; the
        cycle then runs as one native call) instead of serialized
        bytes — _run_loop_once dispatches on the type."""
        from horovod_tpu.ops.socket_ops import _pack_fused, _to_numpy
        cache = self._cache
        pm = self.parameter_manager
        if pm is not None and self.controller.is_coordinator \
                and pm.plan_revision != self._wire_plan_rev:
            # The tuner just moved the active combo: the pending
            # world-wide eviction must run through _coordinate_cycle
            # this cycle — a native/spec grant would bypass it and
            # keep replaying verdicts of the superseded plan.
            return None
        plan = self._replay_plan(hit_mask, self._world_fusion_threshold)
        seg_arrays = []
        seg_wires = []
        prescales = []
        inflight = []
        for resp in plan:
            if resp.response_type != ResponseType.ALLREDUCE:
                return None
            if resp.algorithm not in (_wd.ALG_DEFAULT, _wd.ALG_STAR,
                                      _wd.ALG_ICI):
                # Ring/two-level batches own their data plane; the
                # speculative round must not steal them. ALG_ICI is
                # admitted on purpose: its intra-slice leg packs on
                # the mesh BEFORE this very cycle, and its cross-slice
                # leg IS the speculative star.
                return None
            if resp.wire_dtype == _wd.WIRE_INT8:
                # int8 payloads carry per-rank scales the inline
                # coordinator reduce cannot sum — the classic star
                # path (which dequantizes) keeps carrying them.
                return None
            entries = self.tensor_table.peek_entries(resp.tensor_names)
            if entries is None:
                return None
            arrays = [_to_numpy(e.tensor) for e in entries]
            try:
                backend = self.op_manager.pick(entries, resp)
            except RuntimeError:
                return None
            if not backend.fused_cycle_reducible(
                    sum(a.nbytes for a in arrays)):
                return None
            seg_arrays.append(arrays)
            seg_wires.append(resp.wire_dtype)
            prescales.append(resp.prescale_factor)
            inflight.append((resp, entries, arrays))
        if self._steady_native:
            splan = self._steady_plan_for(hit_mask, seg_arrays,
                                          seg_wires)
            if splan is not None:
                bufs = None
                if self._ici_plane is not None and any(
                        resp.algorithm == _wd.ALG_ICI
                        for resp, _, _ in inflight):
                    bufs = self._ici_pack(splan, hit_mask, seg_arrays,
                                          seg_wires, prescales,
                                          inflight)
                if bufs is None:
                    # Coordinator accumulators double as the broadcast
                    # result its outputs will alias — fresh, never
                    # arena.
                    bufs = splan.pack(
                        seg_arrays, prescales,
                        use_arena=not self.controller.is_coordinator)
                if any(seg_wires):
                    from horovod_tpu.ops.socket_ops import (
                        record_compression,
                    )
                    record_compression(
                        sum(sum(a.nbytes for a in arrays)
                            for arrays in seg_arrays),
                        sum(splan.seg_nbytes))
                self._spec_inflight = inflight
                self._spec_steady = (splan, bufs)
                self._spec_bids += 1
                return splan
        segments = []
        ici_segs = 0
        for j, (resp, _, arrays) in enumerate(inflight):
            w = resp.wire_dtype
            buf = None
            if self._ici_plane is not None \
                    and resp.algorithm == _wd.ALG_ICI:
                buf = self._ici_pack_segment(
                    cache.epoch, hit_mask, j, arrays,
                    resp.prescale_factor, w)
            if buf is not None:
                ici_segs += 1
                if w:
                    from horovod_tpu.ops.socket_ops import (
                        record_compression,
                    )
                    record_compression(
                        sum(a.nbytes for a in arrays), buf.nbytes)
                    segments.append((_wd.wire_datatype(w), buf))
                else:
                    segments.append(
                        (numpy_dtype_to_datatype(buf.dtype), buf))
                continue
            fused, _ = _pack_fused(arrays, resp)  # applies prescale
            if w:
                from horovod_tpu.ops.socket_ops import (
                    compress_send_payload,
                )
                wirearr = compress_send_payload(fused, w)
                segments.append((_wd.wire_datatype(w), wirearr))
            else:
                segments.append((numpy_dtype_to_datatype(fused.dtype),
                                 fused))
        if ici_segs:
            self._ici_cycles += 1
        self._spec_inflight = inflight
        self._spec_bids += 1
        return self._stamp(wire.serialize_cycle_request(
            CacheCycleRequest(
                epoch=cache.epoch, nslots=cache.nslots,
                hit_mask=hit_mask, spec_payload=segments)))

    def _ici_pack_segment(self, epoch: int, hit_mask: int, j: int,
                          arrays, prescale: float, wire_code: int):
        """One spec-frame segment through the ICI plane's pre-compiled
        fused-psum executable (concat + prescale + wire cast on the
        device mesh); None when the plane cannot carry it — the caller
        falls back to the host pack for bit-identical bytes."""
        import numpy as np

        plane = self._ici_plane
        plane.note_cache_epoch(epoch)
        flats = [a.reshape(-1) if a.flags["C_CONTIGUOUS"]
                 else np.ascontiguousarray(a).reshape(-1)
                 for a in arrays]
        flat = flats[0] if len(flats) == 1 else np.concatenate(flats)
        try:
            return plane.fused_pack((epoch, hit_mask, j), flat,
                                    prescale, wire_code)
        except Exception as e:
            # A mid-flight device failure must degrade, not abort: the
            # host pack produces byte-identical wire payloads.
            hlog.warning(f"ICI fused pack failed; falling back to the "
                         f"host pack for this cycle: {e!r}")
            return None

    def _ici_pack(self, splan, hit_mask: int, seg_arrays, seg_wires,
                  prescales, inflight):
        """Pack a whole native steady frame on the ICI plane: every
        segment must both be stamped ALG_ICI and survive the mesh leg,
        and the plan must adopt the buffers byte-compatibly; any
        deviation returns None and SteadyPlan.pack carries the cycle
        on the host, bit-identically."""
        epoch = self._cache.epoch
        bufs = []
        for j, (arrays, pre) in enumerate(zip(seg_arrays, prescales)):
            resp = inflight[j][0]
            if resp.algorithm != _wd.ALG_ICI:
                return None  # mixed-verdict frame: keep packs uniform
            buf = self._ici_pack_segment(epoch, hit_mask, j, arrays,
                                         pre, seg_wires[j])
            if buf is None:
                return None
            bufs.append(buf)
        adopted = splan.adopt_packed(bufs)
        if adopted is not None:
            self._ici_cycles += 1
        return adopted

    def _steady_plan_for(self, hit_mask: int, seg_arrays, seg_wires):
        """Memoized SteadyPlan for (mask, threshold) at the current
        cache epoch; None when a segment's dtype has no native reduce
        kernel (the classic path carries it). With a negotiated wire
        dtype the plan's segments are declared IN the wire dtype — the
        native coordinator reduces bf16/fp16 through the same
        hvd_sum_into codes, and pack compresses into the arena."""
        cache = self._cache
        if self._steady_plan_epoch != cache.epoch:
            self._steady_plans.clear()
            self._steady_plan_epoch = cache.epoch
        key = (hit_mask, self._world_fusion_threshold)
        splan = self._steady_plans.get(key)
        if splan is None:
            segments = []
            for arrays, wire in zip(seg_arrays, seg_wires):
                dtype = arrays[0].dtype
                if any(a.dtype != dtype for a in arrays):
                    return None
                src_nbytes = sum(a.nbytes for a in arrays)
                if wire:
                    np_wire = _wd.wire_np_dtype(wire)
                    count = src_nbytes // dtype.itemsize
                    segments.append((_wd.wire_datatype(wire), np_wire,
                                     count * np_wire.itemsize, dtype))
                else:
                    segments.append((numpy_dtype_to_datatype(dtype),
                                     dtype, src_nbytes, None))
            # In-flight overlap pipelines cycles of DIFFERENT plans:
            # each plan then owns a private arena so the packed send
            # bytes of a submitted cycle can never be overwritten by
            # the next bucket's pack (the runner additionally blocks
            # same-plan resubmission while its views are on the wire).
            arena = (harena.FusionArena() if self._overlap is not None
                     else self._send_arena)
            splan = hsteady.SteadyPlan(
                cache.epoch, cache.nslots, hit_mask, segments, arena,
                chunk_bytes=(0 if self.controller.is_coordinator
                             else self._overlap_chunk),
                world_id=self._world_id)
            if len(self._steady_plans) >= 64:
                self._steady_plans.clear()
            self._steady_plans[key] = splan
        return splan if splan.native_ok else None

    def _native_steady_cycle(self, splan) -> CacheCycleResponse:
        """Drive one zero-copy steady cycle and normalize every
        outcome to the CacheCycleResponse the classic apply path
        consumes. Deviations resume the classic protocol mid-flight:
        the request frame is already on the wire (byte-identical to
        the serialized classic frame), so only the response half
        replays."""
        ctl = self.controller
        _, bufs = self._spec_steady
        self._spec_steady = None
        outcome = ctl.steady_spec_cycle(splan, bufs)
        if outcome is None:
            # Support probe raced (e.g. library refused at call time):
            # run the cycle classically from the serialized frame.
            payload = splan.frame_bytes(bufs)
            gathered = ctl.gather_requests(payload)
            if ctl.is_coordinator:
                reply, meta = self._coordinate_cycle(gathered)
                ctl.broadcast_responses(reply)
            else:
                meta = wire.parse_cycle_response(self._unstamp(
                    ctl.broadcast_responses(None)))
            return meta
        kind, val = outcome
        if kind == "done":
            self._native_steady_cycles += 1
            if ctl.is_coordinator:
                self.timeline.negotiate_cached(fused=True)
                self._check_stall(self._message_table, ctl.size)
            return CacheCycleResponse(
                epoch=splan.epoch, nslots=splan.nslots,
                grant_mask=splan.mask, spec_payload=val)
        if kind == "frame":
            return wire.parse_cycle_response(self._unstamp(val))
        assert kind == "fallback"
        reply, meta = self._coordinate_cycle(val)
        ctl.broadcast_responses(reply)
        return meta

    # -- overlap tier (common/overlap.py) --------------------------------
    def overlap_bucket_plan(self, nbytes_list):
        """Bucket END indices for one grouped submission (ops layer),
        or None when bucketing is off. A pure function of the
        per-tensor sizes plus world-identical knobs/tuned values, so
        every rank splits the same submission the same way."""
        cfg = self.config
        k = cfg.overlap_buckets
        pm = self.parameter_manager
        if pm is not None:
            tuned = pm.overlap_buckets()
            if tuned is not None:
                k = tuned
                if k <= 0:
                    return None
        return hoverlap.plan_buckets(nbytes_list, k,
                                     cfg.overlap_bucket_bytes)

    def note_overlap_buckets(self, n: int) -> None:
        self._overlap_buckets_submitted += n

    def note_bucket_names(self, names) -> None:
        """Record one intended bucket's name set (called by the ops
        layer per bucketed enqueue_group, any thread): the background
        loop splits pops at these boundaries so each bucket
        negotiates as its own cycle. Bounded; snapshot-swapped.
        No-op unless the overlap runner is armed — without it, merged
        pops fusing into one batch is the cheaper outcome."""
        if self._overlap is None:
            return
        s = frozenset(names)
        cur = self._bucket_sets
        if s in cur:
            return
        if len(cur) >= 4 * hoverlap.MAX_BUCKETS:
            cur = frozenset()
        self._bucket_sets = cur | {s}

    def _split_buckets(self, requests: List[Request]) -> List[Request]:
        """Bucketed steady dispatch: when one pop caught SEVERAL
        complete steady buckets back-to-back (the training thread got
        ahead of the wire), peel off the FIRST bucket and requeue the
        rest — each bucket must ride its OWN fused cycle, or the
        union would negotiate as one unknown mask and the per-bucket
        speculation (and the overlap pipeline with it) would unlearn.
        Grouped enqueues are atomic, so pops only ever see whole
        buckets; the requeued remainder is re-popped next iteration
        (which immediately follows — submits count as activity)."""
        if self._overlap is None or len(requests) < 2:
            return requests
        # Only INTENDED bucket sets split pops — never learned steady
        # sets: a per-tensor submission flow (torch-style hooks) may
        # transiently grant a lone tensor, and splitting on that
        # learned singleton would fragment its future fused batches.
        split_sets = self._bucket_sets
        if not split_sets:
            return requests
        seen = set()
        for k, r in enumerate(requests):
            seen.add(r.tensor_name)
            if k + 1 < len(requests) \
                    and frozenset(seen) in split_sets:
                self.tensor_table.requeue(requests[k + 1:])
                if not self._wake.is_set():
                    self._wake.set()
                return requests[:k + 1]
        return requests

    @world_coherent
    def _submit_overlap_cycle(self, splan, bit_requests) -> bool:
        """Hand a packed steady cycle to the overlap runner. Returns
        False (leaving speculative state intact for the synchronous
        path) when the runner cannot accept — a deviation stalled it
        between the loop's drain and this submit, or teardown began.
        @world_coherent: the in-flight mask sequence only ever grows
        here, from a world-identically-built plan in program order."""
        spec = self._spec_steady
        inflight = self._spec_inflight
        self._spec_steady = None
        self._spec_inflight = None
        if spec is None or inflight is None:
            return False
        plan, bufs = spec
        self._overlap_seq += 1
        cyc = hoverlap.InflightCycle(plan, bufs, bit_requests,
                                     inflight, self._overlap_seq)
        try:
            self._overlap.submit(cyc)
        except RuntimeError:
            self._spec_steady = spec
            self._spec_inflight = inflight
            return False
        self._inflight_masks.append(plan.mask)
        if self.timeline.enabled:
            self.timeline.async_start("cycle", "OVERLAP", cyc.seq)
        return True

    def _drain_overlap(self, block: bool = False) -> None:
        """Apply finished overlapped cycles in submission order.
        ``block=True`` waits until NOTHING is outstanding — the wire
        is quiesced and every verdict applied (the precondition for
        any classic round). Runs only on the background thread."""
        runner = self._overlap
        if runner is None:
            return
        while True:
            cyc = runner.pop_completed()
            if cyc is None:
                if not block or not runner.outstanding:
                    return
                t0 = time.monotonic()
                cyc = runner.wait_completed(0.25)
                if cyc is None:
                    continue
                cyc.blocked_wait += time.monotonic() - t0
            self._finish_overlap_cycle(cyc)

    def _finish_overlap_cycle(self, cyc) -> None:
        """Apply one runner outcome — the bg-thread half of an
        overlapped cycle. \"done\" outcomes take the fused-grant fast
        path; anything else resolves through the classic machinery
        after cancelling (and requeueing) every never-sent cycle, so
        the wire order every rank observes stays identical."""
        kind, val = cyc.outcome
        if self.timeline.enabled:
            self.timeline.async_end("cycle", "OVERLAP", cyc.seq)
        if kind == "done":
            self._native_steady_cycles += 1
            self._overlap_cycles += 1
            # The drained cycle IS a completed world round — counted
            # here, at apply time, because verdicts apply in
            # submission order (the wire order every rank shares).
            wc = self._note_round()
            if self._trace_on:
                self._trace.slice(
                    "OVERLAP", cyc.t_start,
                    max(cyc.t_done - cyc.t_start, 0.0), wc)
            if self._metrics_on:
                dur = max(cyc.t_done - cyc.t_start, 1e-9)
                self._m_overlap_fraction.observe(
                    max(0.0, 1.0 - cyc.blocked_wait / dur))
            if self.controller.is_coordinator:
                self.timeline.negotiate_cached(fused=True)
                self._check_stall(self._message_table,
                                  self.controller.size)
            meta = CacheCycleResponse(
                epoch=cyc.plan.epoch, nslots=cyc.plan.nslots,
                grant_mask=cyc.plan.mask, spec_payload=val)
            self._apply_overlap_verdict(cyc, meta)
            return
        # Deviation / error: no later frame was sent (the runner
        # stalls), so cancel the queued cycles and put their requests
        # back — every rank that overlapped does the same at the same
        # verdict, and ranks that never overlapped have them queued
        # anyway; the next cycle re-bids them identically everywhere.
        cancelled = self._overlap.cancel_pending()
        for c in cancelled:
            self._unwind_cancelled_cycle(c)
        if kind == "error":
            err = val
            if isinstance(err, WorldAbortedError):
                entries = [e for (_r, es, _a) in cyc.inflight
                           for e in es]
                popped = self.tensor_table.pop_entries(
                    [e.tensor_name for e in entries]) or entries
                self._drop_inflight_mask(cyc.plan.mask)
                raise self._data_plane_abort(
                    popped, err.origin_rank,
                    getattr(err, "cause", str(err)))
            self._drop_inflight_mask(cyc.plan.mask)
            raise err
        ctl = self.controller
        if kind == "none":
            # Support probe raced: run the cycle classically from the
            # serialized frame (byte-identical to the native send).
            payload = cyc.plan.frame_bytes(cyc.bufs)
            gathered = ctl.gather_requests(payload)
            if ctl.is_coordinator:
                reply, meta = self._coordinate_cycle(gathered)
                ctl.broadcast_responses(reply)
            else:
                meta = wire.parse_cycle_response(self._unstamp(
                    ctl.broadcast_responses(None)))
        elif kind == "frame":
            meta = wire.parse_cycle_response(self._unstamp(val))
        else:
            assert kind == "fallback"
            reply, meta = self._coordinate_cycle(val)
            ctl.broadcast_responses(reply)
        wc = self._note_round()
        if self._trace_on:
            self._trace.slice("OVERLAP", cyc.t_start,
                              max(time.monotonic() - cyc.t_start, 0.0),
                              wc)
        self._apply_overlap_verdict(cyc, meta)

    @world_coherent
    def _apply_overlap_verdict(self, cyc, meta) -> None:
        """Apply a drained cycle's broadcast verdict exactly as the
        synchronous path would: restore ITS speculative in-flight
        state, run the shared cached-cycle apply, and execute whatever
        classic responses the verdict carried."""
        self._spec_inflight = cyc.inflight
        self._drop_inflight_mask(cyc.plan.mask)
        try:
            resp_list = self._apply_cached_cycle(meta,
                                                 cyc.bit_requests)
        finally:
            self._spec_inflight = None
        if self.parameter_manager is not None:
            self.parameter_manager.apply_synced(
                resp_list.tuned_fusion_threshold_bytes,
                resp_list.tuned_cycle_time_ms,
                resp_list.tuned_overlap_buckets)
        self._perform_operations(resp_list)

    @world_coherent
    def _unwind_cancelled_cycle(self, cyc) -> None:
        """A cancelled cycle's frame was never sent: its entries stay
        tabled, its requests go back on the queue (they are cache hits
        and re-bid next cycle), and its mask leaves the in-flight
        sequence — identically on every rank that overlapped."""
        self._drop_inflight_mask(cyc.plan.mask)
        reqs = [req for _slot, req in cyc.bit_requests]
        if reqs:
            self.tensor_table.requeue(reqs)

    @world_coherent
    def _drop_inflight_mask(self, mask: int) -> None:
        try:
            self._inflight_masks.remove(mask)
        except ValueError:
            pass

    def _note_round(self) -> int:
        """One world negotiation round (gather + broadcast — classic,
        cached, native steady or overlapped) completed on this rank.
        The counter is WORLD-IDENTICAL: every rank participates in
        every round in wire order (overlapped cycles apply at drain in
        submission order, which IS the wire order), so the same round
        carries the same number everywhere — the correlation key the
        timeline, the world trace and the flight recorder all stamp."""
        self._world_cycle += 1
        wc = self._world_cycle
        self.timeline.set_world_cycle(wc)
        self._flight.record(htrace.EV_CYCLE, wc)
        return wc

    def _maybe_publish_trace(self) -> None:
        """Per-interval trace shipping (background thread only):
        drain the span collector and either feed rank 0's world
        writer directly or ride one TAG_TRACE frame up the control
        tree — out-of-band, exactly like METRICS frames. The frame
        also carries the clock-sync echo closing the NTP loop."""
        now = time.monotonic()
        # A hierarchical local root forwards buffered child frames on
        # the next tick rather than waiting out its own interval: a
        # child's clock-sync echo ages while parked, and every parked
        # microsecond inflates t4 — a systematic (same-period publish
        # timers, constant phase) negative bias on the leaf's offset
        # that min-RTT filtering cannot remove.
        pending_children = bool(
            getattr(self.controller, "_child_trace", None))
        if (now - self._trace_last_pub < self.config.trace_interval_s
                and not pending_children):
            return
        self._trace_last_pub = now
        spans, dropped = self._trace.drain()
        if self._trace_writer is not None:
            self._trace_writer.add_section(0, spans, dropped)
            self._trace_spans_sent += len(spans)
            return
        echo = htrace.clock().take_echo()
        if (not spans and not dropped and echo is None
                and not pending_children):
            return
        try:
            payload = wire.serialize_trace_frame(
                [{"rank": self.controller.rank, "dropped": dropped,
                  "echo": echo, "spans": spans}])
        except Exception:
            return  # a malformed span must not kill the loop
        self._trace_spans_sent += len(spans)
        self.controller.send_trace(payload)

    def _record_signature(self, req: Request) -> None:
        if req.request_type not in CACHEABLE_REQUESTS:
            return
        numel = 1
        for d in req.tensor_shape[1:]:
            numel *= d
        self._pending_sigs[req.tensor_name] = (
            ResponseCache.signature(req), req.tensor_type, numel)

    def _run_loop_once(self) -> bool:
        """One negotiation cycle; returns False to exit
        (reference: operations.cc:986-1338). With the response cache
        enabled, steady-state cycles ride the bitmask fast path: each
        rank's frame is one bit per cache slot (AND-reduced up the
        gather tree), the coordinator broadcasts the world-granted
        mask, and every rank locally replays the cached responses in
        ascending slot order — no per-tensor serialization, no
        ConstructResponse, no fusion pass. Any miss, signature change,
        eviction, or non-cacheable op rides the full path alongside
        the masks and repopulates the cache coherently everywhere."""
        t0 = time.monotonic()
        self._cycle_count += 1
        faults.tick_cycle(self, self._cycle_count)
        # Demote-verdict pacing: every member EXCEPT the demoted
        # straggler defers a hair (mirroring the delay-fault injection
        # point), so gather arrivals cluster instead of the world
        # blocking inside the collective on one late rank.
        pace = selfop.cycle_pace_s(self.controller.rank)
        if pace > 0.0:
            time.sleep(pace)
        if self._elastic is not None \
                and (t0 - self._selfop_last_tick >= 1.0
                     or selfop.preempted()):
            # Supervision tick: preemption notices on every rank,
            # straggler-demotion analysis on the coordinator. A
            # verdict fans the SAME benign world abort the elastic
            # join sweep uses — the decision is enacted by the next
            # rendezvous barrier. An already-armed preemption event
            # skips the throttle: the grace clock is running, every
            # cycle spent not draining is budget lost.
            self._selfop_last_tick = t0
            decision = self._selfop_policy.tick(self)
            if decision is not None:
                cause, origin = decision
                cause = (f"selfop-{cause}: supervision policy "
                         f"drain-and-resize")
                err = WorldAbortedError(
                    world_abort_message(origin, cause),
                    origin_rank=origin, cause=cause)
                err.resolved = True  # deliberate: drain, then resize
                raise err
        if self._elastic is not None \
                and t0 - self._elastic_last_poll >= 0.25:
            # Elastic join sweep: the coordinator parks any join
            # manifest waiting on its elastic listener and fans a
            # benign world abort so every member reaches the
            # re-rendezvous barrier (where the joiner is admitted);
            # other ranks answer stray dials with a redirect to the
            # current coordinator. Four syscalls a second when idle.
            self._elastic_last_poll = t0
            cause = self._elastic.poll_joins(self.controller.rank == 0)
            if cause is not None:
                err = WorldAbortedError(
                    world_abort_message(-1, cause), origin_rank=-1,
                    cause=cause)
                err.resolved = True  # deliberate: skip the drain
                raise err
        self.timeline.mark_cycle_start()

        if self._overlap is not None and self._overlap.outstanding:
            # Apply finished overlapped cycles (and resolve a parked
            # deviation) BEFORE building this cycle's frame — their
            # verdicts move the cache state the frame build reads.
            self._drain_overlap(block=self._overlap.stalled)

        requests = self.tensor_table.pop_messages()
        if requests and self._cache is not None:
            if self._metrics_on:
                tb = time.monotonic()
                requests = self._absorb_burst(requests)
                self._m_burst_hold_s.inc(time.monotonic() - tb)
            else:
                requests = self._absorb_burst(requests)
            requests = self._split_buckets(requests)
        shutting_down = self._shutdown_requested.is_set()

        if self._tenant_lane is not None and requests \
                and not shutting_down:
            # QoS-weighted tenant scheduling (common/tenancy.py): a
            # cycle with local work waits for this tenant's turn in
            # the process-local weighted interleave, and an over-quota
            # tenant is DEFERRED — never skipped, so no frame is ever
            # lost. The wait is bounded by the same hold rule as every
            # other hold in this loop (far under the heartbeat
            # deadline), so a deferred tenant's peers can never
            # mistake pacing for death.
            self._tenant_lane.acquire(self._bounded_hold_s(8, 2.0))

        if (self._overlap is not None and not requests
                and not shutting_down
                and (self._overlap.outstanding or self._steady)):
            # Overlap regime with nothing local to negotiate: hold for
            # work instead of initiating an empty classic round. A
            # wake from a runner completion is NOT work — without this
            # hold, completion wakes leak empty frames into the world
            # rounds, misalign them across ranks, and every
            # speculative bid that lands in such a round dies as a
            # dead grant. Bounded like the steady idle hold (far under
            # the heartbeat deadline) so stall detection, full-path
            # peers and shutdown all keep their liveness: at expiry
            # the empty round proceeds after all.
            now = time.monotonic()
            if self._overlap_hold_deadline is None:
                self._overlap_hold_deadline = now + \
                    self._bounded_hold_s(8, self._STEADY_IDLE_S)
            if now < self._overlap_hold_deadline:
                self._wake.wait(self._overlap_hold_deadline - now)
                self._wake.clear()
                self._drain_overlap(block=False)
                return True
            self._overlap_hold_deadline = None
        elif requests:
            self._overlap_hold_deadline = None

        payload, bit_requests = self._build_request_frame(
            requests, shutting_down)

        # 0.0 (not unbound) when dark: _trace_on may be flipped from
        # another thread mid-cycle (the trace-overhead toggle bench),
        # and the span emit below must then skip, never NameError.
        tn = (time.monotonic()
              if self._metrics_on or self._trace_on else 0.0)
        submitted = False
        meta = None
        if not isinstance(payload, hsteady.SteadyPlan) \
                and self._overlap is not None \
                and self._overlap.outstanding:
            # Classic frame while cycles are in flight: the wire must
            # quiesce first (cycles are strictly ordered), and the
            # drained verdicts may have moved cache state or requeued
            # cancelled buckets — rebuild the frame afterwards.
            self._drain_overlap(block=True)
            requests.extend(self.tensor_table.pop_messages())
            payload, bit_requests = self._build_request_frame(
                requests, shutting_down)
        if isinstance(payload, hsteady.SteadyPlan):
            if self._overlap is not None:
                submitted = self._submit_overlap_cycle(payload,
                                                       bit_requests)
                if not submitted:
                    # Runner stalled or stopped under us: quiesce, then
                    # run this cycle synchronously — the wire is ours
                    # again once the drain returns. The drain applies
                    # OTHER cycles' verdicts, whose apply path clears
                    # the speculative in-flight state — save THIS
                    # unsent cycle's across it.
                    spec_save = (self._spec_steady,
                                 self._spec_inflight)
                    self._drain_overlap(block=True)
                    self._spec_steady, self._spec_inflight = spec_save
            if not submitted:
                # Zero-copy steady step: negotiation + data plane in
                # ONE native call (deviations rejoin the classic path
                # inside). An abort raised from inside the C loop must
                # leave no in-flight speculative state behind: elastic
                # recovery re-enters a fresh cycle loop, and stale
                # inflight entries would satisfy the next spec verdict
                # with dead arrays.
                try:
                    meta = self._native_steady_cycle(payload)
                except BaseException:
                    self._spec_inflight = None
                    self._spec_steady = None
                    raise
        else:
            gathered = self.controller.gather_requests(payload)
            if self.controller.is_coordinator:
                reply, meta = self._coordinate_cycle(gathered)
                self.controller.broadcast_responses(reply)
            else:
                data = self.controller.broadcast_responses(None)
                meta = wire.parse_cycle_response(self._unstamp(data))
        if meta is not None:
            # A world round completed synchronously in this iteration
            # (a submitted overlap cycle completes at drain instead).
            wc = self._note_round()
            if self._trace_on and tn:
                self._trace.slice(
                    "STEADY" if isinstance(payload, hsteady.SteadyPlan)
                    else "ROUND", tn, time.monotonic() - tn, wc)
        if self._metrics_on:
            self._m_negotiation_s.observe(time.monotonic() - tn)

        if submitted:
            # The cycle completes out of band; its verdict applies at
            # a later drain, in submission order. Handles resolve
            # then — synchronize() only ever blocks on the tail
            # bucket. Treat the submit as activity and loop
            # immediately: the next bucket may already be queued.
            self._idle_cycles = 0
            if self._tenant_lane is not None:
                self._tenant_lane.note_cycle(self._cycle_bytes)
                if self.parameter_manager is None:
                    self._cycle_bytes = 0
                if self.tensor_table.queue_pending():
                    self._tenant_lane.want = True  # backlog persists
            if self.parameter_manager is not None:
                self.parameter_manager.on_cycle(self._cycle_bytes)
                self._cycle_bytes = 0
            if self._metrics_on:
                self._m_cycle_s.observe(time.monotonic() - t0)
                self._maybe_publish_metrics()
            if self._trace_on:
                self._maybe_publish_trace()
            return True

        if isinstance(meta, CacheCycleResponse):
            resp_list = self._apply_cached_cycle(meta, bit_requests)
        else:
            if self._cache is not None:
                raise ConnectionError(
                    "coordinator negotiated without the response cache "
                    "while this rank has it enabled — HOROVOD_CACHE_"
                    "ENABLED/HOROVOD_CACHE_CAPACITY must be identical "
                    "on every rank")
            resp_list = meta

        self._perform_operations(resp_list)

        if resp_list.shutdown:
            return False

        # Pace the cycle (reference: operations.cc:987-995). The autotuner
        # may be steering cycle_time_ms (reference: parameter_manager.cc).
        cycle_time_ms = self.config.cycle_time_ms
        if self._tenant_lane is not None:
            # Report this cycle's negotiated bytes to the tenant
            # scheduler's quota bucket (the live metrics plane carries
            # the same totals; the lane prefers whichever is armed).
            self._tenant_lane.note_cycle(self._cycle_bytes)
            if self.parameter_manager is None:
                self._cycle_bytes = 0
            if self.tensor_table.queue_pending():
                self._tenant_lane.want = True  # backlog persists
        if self.parameter_manager is not None:
            self.parameter_manager.apply_synced(
                resp_list.tuned_fusion_threshold_bytes,
                resp_list.tuned_cycle_time_ms,
                resp_list.tuned_overlap_buckets)
            self.parameter_manager.on_cycle(self._cycle_bytes)
            self._cycle_bytes = 0
            cycle_time_ms = self.parameter_manager.cycle_time_ms()
        if resp_list.responses or requests:
            # Local submissions count as activity too: a rank whose own
            # tensor is still negotiating (peers not yet submitted)
            # must keep cycling at full rate or the blocking gather
            # makes the whole world pay its backoff sleep.
            self._idle_cycles = 0
        else:
            self._idle_cycles += 1
        elapsed = time.monotonic() - t0
        if self._metrics_on:
            self._m_cycle_s.observe(elapsed)
            self._maybe_publish_metrics()
        if self._trace_on:
            self._maybe_publish_trace()
        idle_hold = False
        sleep_s = cycle_time_ms / 1000.0 - elapsed
        if not self.tensor_table.queue_pending():
            if sleep_s <= 0:
                # The cycle overran the pace budget (normal on a
                # loaded host) and drained everything local. Starting
                # the next world-synchronized round right now loses a
                # race with the completion callbacks' re-enqueue —
                # every steady-state step would pay one DEAD
                # gather+broadcast round of empty frames. Pace from
                # cycle END instead: wait out one cycle period on
                # _wake, which new local work snaps open immediately,
                # so a training loop's next step starts its round with
                # the queue populated. A rank waiting here delays a
                # remote-only negotiation by at most cycle_time_ms —
                # the same bound the reference's start-measured pacing
                # imposes (operations.cc:987-995).
                sleep_s = cycle_time_ms / 1000.0
            if self._steady:
                # Established steady state sharpens that reasoning —
                # and applies even when the cycle FINISHED under
                # budget (fast fused cycles on a quiet host): the
                # world's next round cannot grant ANYTHING until this
                # rank's training thread re-submits a steady set
                # (every collective requires every rank's request), so
                # initiating an empty round early buys nothing and
                # costs everyone a dead gather+broadcast (its
                # AND-grant is zero). Hold until work arrives or a
                # generous deadline passes — the next enqueue and
                # request_shutdown both snap _wake open instantly, and
                # the hold stays far under the heartbeat deadline, so
                # the only cost is bounded frame latency on a world
                # where OTHER ranks are active while this one idles —
                # and their grants were blocked on this rank anyway.
                sleep_s = max(sleep_s, self._bounded_hold_s(
                    8, self._STEADY_IDLE_S, cycle_ms=cycle_time_ms))
                idle_hold = True
        backoff_ms = self.config.idle_backoff_ms
        if backoff_ms > 0 and self._idle_cycles > self._IDLE_GRACE:
            backoff_s = backoff_ms / 1000.0
            if self.config.heartbeat_timeout_s > 0:
                # A sleeping rank sends nothing; its only proof of
                # life is the next cycle's request frame. Cap the
                # backoff under the heartbeat deadline or an idle
                # world's waiting peers would declare the sleeper
                # dead (the two knobs are set independently).
                backoff_s = min(backoff_s,
                                self.config.heartbeat_timeout_s / 2.0)
            ramp = (cycle_time_ms / 1000.0
                    * (self._idle_cycles - self._IDLE_GRACE))
            sleep_s = max(sleep_s, min(backoff_s, ramp))
        # Async checkpoint shards ride the idle/hold windows the pacing
        # machinery already bounds: the submit is a pool handoff, the
        # serialization runs on the checkpoint writer thread while this
        # loop sleeps (common/selfop.py; no-op without
        # HOROVOD_SELFOP_CKPT_DIR).
        selfop.maybe_checkpoint(self.controller.rank,
                                self.controller.size,
                                idle=idle_hold or sleep_s > 0)
        if sleep_s > 0:
            # Wake early on shutdown OR new local work (enqueue sets
            # _wake) so backoff never adds submit latency.
            if self._metrics_on and idle_hold:
                tw = time.monotonic()
                self._wake.wait(sleep_s)
                self._m_idle_hold_s.inc(time.monotonic() - tw)
            else:
                self._wake.wait(sleep_s)
        self._wake.clear()
        return True

    def _coordinate_cycle(self, gathered: List[bytes]):
        """Parse every rank's cycle frame and produce this cycle's
        broadcast payload. Returns (payload, meta) where ``meta`` is
        the ResponseList (cache disabled) or CacheCycleResponse that
        every rank — this one included — applies identically."""
        if self._world_id:
            # Tenant world: verify + strip every rank's world-id
            # envelope before parsing (a mismatched id names both
            # worlds instead of decoding a foreign mask).
            gathered = [self._unstamp(f) if f else f for f in gathered]
        cache = self._cache
        if cache is None:
            req_lists = [wire.parse_cycle_request(f)
                         for f in gathered if f]
            for rl in req_lists:
                if not isinstance(rl, RequestList):
                    raise ConnectionError(
                        "a rank negotiated with the response cache "
                        "while the coordinator has it disabled — "
                        "HOROVOD_CACHE_ENABLED/HOROVOD_CACHE_CAPACITY "
                        "must be identical on every rank")
            resp_list = self._coordinate(req_lists)
            return self._stamp(
                wire.serialize_cycle_response(resp_list)), resp_list
        epoch = cache.epoch
        and_hits = -1  # all-ones identity; every rank ANDs one mask in
        or_invalid = 0
        shutdown = False
        req_lists: List[RequestList] = []
        spec_frames: List[CacheCycleRequest] = []
        n_frames = 0
        for f in gathered:
            if not f:
                # member slot folded into its host's CACHED_AGG frame
                continue
            n_frames += 1
            cf = wire.parse_cycle_request(f)
            if not isinstance(cf, CacheCycleRequest):
                raise ConnectionError(
                    "a rank negotiated without the response cache "
                    "while the coordinator has it enabled — "
                    "HOROVOD_CACHE_ENABLED/HOROVOD_CACHE_CAPACITY "
                    "must be identical on every rank")
            if cf.epoch != epoch or cf.nslots != cache.nslots:
                raise ConnectionError(
                    f"response-cache state diverged: a rank reported "
                    f"epoch {cf.epoch}/{cf.nslots} slots vs the "
                    f"coordinator's {epoch}/{cache.nslots} — "
                    f"negotiation cannot continue safely")
            and_hits &= cf.hit_mask
            or_invalid |= cf.invalid_mask
            shutdown = shutdown or cf.shutdown
            if cf.spec_payload is not None:
                spec_frames.append(cf)
            if cf.requests:
                req_lists.append(RequestList(cf.requests, cf.shutdown))
        if self.parameter_manager is not None:
            # Tuner moved the active (algorithm, wire dtype) combo:
            # every cached allreduce verdict was stamped under the
            # OLD plan. Fold a coordinator-initiated eviction of
            # those slots into the broadcast invalid mask — a
            # world-identical event by construction, so every rank's
            # cache (this one included) drops them in the same
            # canonical order and the tensors renegotiate under the
            # new plan. Also suppresses this cycle's spec grant
            # (or_invalid is part of its precondition).
            rev = self.parameter_manager.plan_revision
            if rev != self._wire_plan_rev:
                self._wire_plan_rev = rev
                or_invalid |= self._stale_plan_slots()
        if (spec_frames and len(spec_frames) == n_frames
                and not shutdown and not or_invalid
                and all(cf.hit_mask == and_hits
                        for cf in spec_frames)):
            # Fused speculative cycle: every rank bid the SAME pure-hit
            # mask with its fused buffers attached — reduce inline and
            # broadcast grant + result in this very response. One
            # world round-trip total: no separate data-plane round, no
            # ConstructResponse, no fusion pass.
            reduced = self._reduce_spec(spec_frames)
            self.timeline.negotiate_cached(fused=True)
            # Stall detection must not go blind while the world hums
            # in fused steady state: a full-path tensor some rank
            # submitted earlier may still be aging in the table.
            self._check_stall(self._message_table,
                              self.controller.size)
            meta = CacheCycleResponse(epoch=epoch,
                                      nslots=cache.nslots,
                                      grant_mask=and_hits,
                                      spec_payload=reduced)
            return self._stamp(wire.serialize_cycle_response(meta)), \
                meta
        grant = and_hits & ~or_invalid
        resp_list = self._coordinate(req_lists,
                                     extra_shutdown=shutdown)
        if grant and not resp_list.responses:
            self.timeline.negotiate_cached()
        meta = CacheCycleResponse(epoch=epoch, nslots=cache.nslots,
                                  grant_mask=grant,
                                  invalid_mask=or_invalid,
                                  response_list=resp_list)
        return self._stamp(wire.serialize_cycle_response(meta)), meta

    def _stale_plan_slots(self) -> int:
        """Mask of every cached slot holding an ALLREDUCE verdict —
        the entries whose stamped (algorithm, wire dtype) belongs to
        a superseded tuner plan. Read-only over the coordinator's own
        cache; the eviction itself happens on every rank through the
        broadcast invalid mask."""
        return self._cache.slot_mask(ResponseType.ALLREDUCE)

    # Canonical ascending-bit iteration, shared with the cache's own
    # mask-driven mutations (coordinator.iter_set_bits) so replay and
    # eviction can never drift apart.
    _iter_slots = staticmethod(iter_set_bits)

    @world_coherent
    def _apply_cached_cycle(self, meta: CacheCycleResponse,
                            bit_requests: List[tuple]) -> ResponseList:
        """Apply the coordinator's cycle verdict to the local cache —
        identically on every rank: evict the OR'ed invalid slots
        (ascending), replay the granted slots (ascending, fused with
        the threshold this very frame carries), repopulate from the
        freshly negotiated responses (stream order), and requeue hits
        the world did not grant. @world_coherent: every input here is
        the broadcast verdict itself."""
        cache = self._cache
        if cache is None or meta.epoch != cache.epoch \
                or meta.nslots != cache.nslots:
            local = ("disabled" if cache is None
                     else f"epoch {cache.epoch}/{cache.nslots} slots")
            raise ConnectionError(
                f"response-cache state diverged from the coordinator "
                f"(local {local}, coordinator epoch "
                f"{meta.epoch}/{meta.nslots} slots) — negotiation "
                f"cannot continue safely")
        if meta.spec_payload is not None:
            return self._complete_spec_cycle(meta, bit_requests)
        # Epoch-coupled compiled state in the backends (the XLA mesh
        # executable cache) evicts at this broadcast-driven position —
        # one int compare per cycle; a bump lands one cycle after
        # _populate_cache moves the epoch, which is fine because the
        # executables are KEYED correctly (verdict + shapes) and the
        # eviction is hygiene.
        self.op_manager.note_cache_epoch(cache.epoch)
        inner = meta.response_list
        if meta.invalid_mask:
            cache.evict_slots(meta.invalid_mask)
        if inner.tuned_fusion_threshold_bytes:
            # The coordinator's effective threshold — the WORLD value
            # every rank must replay and speculate with.
            self._world_fusion_threshold = \
                inner.tuned_fusion_threshold_bytes
        replayed: List[Response] = []
        if meta.grant_mask:
            replayed = self._replay_grants(meta.grant_mask,
                                           self._world_fusion_threshold)
            if not inner.responses:
                self._cached_cycles += 1
        if inner.responses:
            self._populate_cache(inner)
        if bit_requests and not inner.shutdown:
            now = time.monotonic()
            missed = []
            for slot, req in bit_requests:
                if (meta.grant_mask >> slot) & 1:
                    self._bit_pending_since.pop(req.tensor_name, None)
                else:
                    self._bit_pending_since.setdefault(
                        req.tensor_name, now)
                    missed.append(req)
            self._requeued_names = frozenset(
                r.tensor_name for r in missed)
            if missed:
                self.tensor_table.requeue(missed)
            # A fully granted pure-hit cycle makes its mask (and name
            # set) a steady-state prediction: _absorb_burst holds for
            # its enqueue bursts, and the next identical cycle may
            # speculate its fused payload onto the bitmask round.
            if self._steady_epoch != cache.epoch:
                # slot<->name bindings moved; every mask is stale
                self._steady.clear()
                self._spec_denied.clear()
                self._steady_epoch = cache.epoch
            if self._spec_inflight is not None and not missed:
                # We bid speculatively; the world granted everything
                # yet answered classically — some peer will not (or
                # cannot) speculate. Count it so repeat bids stop
                # wasting a full fused payload per cycle.
                bid = 0
                for slot, _req in bit_requests:
                    bid |= 1 << slot
                self._spec_denied[bid] = \
                    self._spec_denied.get(bid, 0) + 1
                self._spec_denials_total += 1
                self._spec_inflight = None
            if not missed and not inner.responses \
                    and not meta.invalid_mask:
                self._steady[meta.grant_mask] = frozenset(
                    cache.entry(s).name
                    for s in self._iter_slots(meta.grant_mask))
                self._steady.move_to_end(meta.grant_mask)
                if len(self._steady) > self._steady_cap:
                    self._steady.popitem(last=False)
            elif meta.grant_mask or inner.responses \
                    or meta.invalid_mask:
                # a PARTIAL verdict for this bid: whatever mask was
                # bid is not unanimously steady — drop it so repeat
                # bids stop wasting speculative payloads. A fully
                # DENIED bid (dead round: some rank simply had
                # nothing queued yet, a scheduling race) keeps its
                # prediction and re-speculates on the re-bid.
                bid_mask = 0
                for slot, _req in bit_requests:
                    bid_mask |= 1 << slot
                self._steady.pop(bid_mask, None)
        if not replayed:
            return inner
        return ResponseList(
            replayed + inner.responses, shutdown=inner.shutdown,
            tuned_cycle_time_ms=inner.tuned_cycle_time_ms,
            tuned_fusion_threshold_bytes=(
                inner.tuned_fusion_threshold_bytes))

    def _replay_plan(self, grant_mask: int,
                     threshold: int) -> List[Response]:
        """The fused execution list for a granted mask: clone the
        granted entries in ascending slot order and fuse them exactly
        as the coordinator would have. Memoized per (grant, threshold)
        for the current cache epoch — a steady-state training loop
        grants the same mask every cycle, so this collapses to a dict
        hit. Pure: never touches the LRU (the speculative frame
        builder calls it before any grant exists)."""
        cache = self._cache
        if self._replay_epoch != cache.epoch:
            self._replay_plans.clear()
            self._replay_epoch = cache.epoch
        key = (grant_mask, threshold)
        plan = self._replay_plans.get(key)
        if plan is None:
            responses: List[Response] = []
            dtypes: Dict[str, DataType] = {}
            slices: Dict[str, int] = {}
            for slot in self._iter_slots(grant_mask):
                e = cache.entry(slot)
                responses.append(e.clone_response())
                dtypes[e.name] = e.dtype
                slices[e.name] = e.slice_numel
            plan = fuse_responses(responses, dtypes, threshold, slices)
            if len(self._replay_plans) >= 64:
                self._replay_plans.clear()
            self._replay_plans[key] = plan
        return plan

    def _replay_grants(self, grant_mask: int,
                       threshold: int) -> List[Response]:
        plan = self._replay_plan(grant_mask, threshold)
        self._cache.touch_mask(grant_mask)
        return plan

    @staticmethod
    def _reduce_spec(spec_frames: List[CacheCycleRequest]):
        """Coordinator half of the fused speculative cycle: sum every
        rank's pre-packed fused buffers segment-by-segment (ascending
        rank order, mirroring the star data plane). Frames already
        passed the epoch/mask equality gate, so a layout mismatch here
        means the caches diverged structurally — fail fast."""
        import numpy as np

        from horovod_tpu import native as _native
        first = spec_frames[0].spec_payload
        if any(len(sf.spec_payload) != len(first)
               for sf in spec_frames[1:]):
            raise ConnectionError(
                "speculative fused payloads disagree on layout "
                "across ranks — response-cache state diverged")
        out = []
        for i, (dt, buf0) in enumerate(first):
            np_dt = datatype_to_numpy_dtype(dt)
            acc = np.frombuffer(buf0, dtype=np_dt).copy()
            for sf in spec_frames[1:]:
                d2, b2 = sf.spec_payload[i]
                if d2 != dt or b2.nbytes != buf0.nbytes:
                    raise ConnectionError(
                        "speculative fused payloads disagree on "
                        "layout across ranks — response-cache state "
                        "diverged")
                src = np.frombuffer(b2, dtype=np_dt)
                if not _native.sum_into(acc, src):
                    acc += src
            out.append((dt, acc))
        return out

    @world_coherent
    def _complete_spec_cycle(self, meta: CacheCycleResponse,
                             bit_requests: List[tuple]) -> ResponseList:
        """Worker half of the fused speculative cycle: the grant is by
        construction exactly what this rank bid, and the payload is
        the world-reduced result of the buffers it packed at frame
        build — unpack into the (still-tabled) entries, fire their
        callbacks, and keep every counter/LRU effect identical to a
        classic hit cycle so cache coherence is unaffected."""
        from horovod_tpu.ops.socket_ops import _unpack_fused
        import numpy as np
        inflight = self._spec_inflight
        self._spec_inflight = None
        if inflight is None or meta.spec_payload is None \
                or len(meta.spec_payload) != len(inflight):
            raise ConnectionError(
                "fused speculative response does not match the frame "
                "this rank sent — control plane corrupted")
        timeline_on = self.timeline.enabled
        metrics_on = self._metrics_on
        ok = Status.OK()
        for (resp, entries, arrays), (dt, buf) in zip(
                inflight, meta.spec_payload):
            self._op_count += 1
            faults.tick_op(self, self._op_count)
            if metrics_on:
                # The fused round IS the data plane for this batch:
                # keep the allreduce op/byte totals exact even though
                # OperationManager.execute never sees it.
                self._m_ops_allreduce.inc()
                self._m_bytes_allreduced.inc(
                    sum(a.nbytes for a in arrays))
            # Autotune score attribution: spec cycles bypass
            # _perform_operations, so their bytes must feed the
            # tuner's bytes/µs stream here (the grid phase measures
            # the deployment regime, spec cycle included).
            self._cycle_bytes += sum(a.nbytes for a in arrays)
            names = resp.tensor_names
            popped = self.tensor_table.pop_entries(names)
            if resp.wire_dtype:
                # Compressed steady cycle: the world result arrived in
                # the negotiated wire dtype; decompress ONCE into a
                # fresh full-precision array outputs may alias (a
                # cast, not a fallback byte copy — hvd_data_copies
                # stays 0 on this path).
                result = _wd.decompress(
                    buf, resp.wire_dtype, arrays[0].dtype,
                    sum(a.size for a in arrays))
            elif isinstance(buf, np.ndarray):
                # Zero-copy plane: the native cycle received the world
                # result into a FRESH writable per-step buffer (never
                # arena memory), so outputs may alias it directly.
                result = buf
            else:
                # Classic frame: a memoryview over the immutable recv
                # bytes — one defensive copy buys writable outputs
                # (the contract of the star plane), and the counter
                # records that the fallback path is carrying traffic.
                self._m_data_copies.inc()
                result = np.frombuffer(bytearray(buf),
                                       dtype=datatype_to_numpy_dtype(dt))
            op_name = resp.response_type.name
            if timeline_on:
                for n in names:
                    self.timeline.start(n, op_name)
            _unpack_fused(entries, arrays, result, resp)
            if timeline_on:
                for n in names:
                    self.timeline.end(n)
            for e in popped:
                if e.callback:
                    e.callback(ok)
        self._cached_cycles += 1
        self._spec_cycles += 1
        self._spec_denied.pop(meta.grant_mask, None)
        self._cache.touch_mask(meta.grant_mask)
        for _slot, req in bit_requests:
            self._bit_pending_since.pop(req.tensor_name, None)
        self._requeued_names = frozenset()
        return ResponseList([])

    @staticmethod
    def _unfuse(resp: Response, i: int, world_size: int) -> Response:
        """Entry ``i`` of a (possibly fused) response as a standalone
        single-tensor Response — the unit the cache stores, so a later
        hit cycle can re-fuse under whatever threshold is then in
        effect. ALLGATHER tensor_sizes are entry-major
        (sizes[ec * world_size + rc]); ALLREDUCE sizes are per-entry
        numels; every other cacheable type never fuses."""
        if resp.response_type == ResponseType.ALLGATHER:
            sizes = list(resp.tensor_sizes[i * world_size:
                                           (i + 1) * world_size])
        elif resp.tensor_sizes:
            sizes = [resp.tensor_sizes[i]]
        else:
            sizes = []
        return Response(response_type=resp.response_type,
                        tensor_names=[resp.tensor_names[i]],
                        devices=list(resp.devices),
                        tensor_sizes=sizes,
                        prescale_factor=resp.prescale_factor,
                        postscale_factor=resp.postscale_factor,
                        wire_dtype=resp.wire_dtype,
                        algorithm=resp.algorithm)

    @world_coherent
    def _populate_cache(self, resp_list: ResponseList) -> None:
        """Refresh the cache from freshly negotiated responses — in
        broadcast-stream order, the world-identical order every rank
        sees, which is what keeps slot assignment and LRU eviction
        bit-identical everywhere. ERROR verdicts evict any stale entry
        under the same names."""
        cache = self._cache
        world_size = self.controller.size
        for resp in resp_list.responses:
            rt = resp.response_type
            if rt == ResponseType.ERROR:
                for name in resp.tensor_names:
                    cache.evict_name(name)
                    self._pending_sigs.pop(name, None)
                continue
            if rt not in CACHEABLE_RESPONSES:
                for name in resp.tensor_names:
                    self._pending_sigs.pop(name, None)
                continue
            for i, name in enumerate(resp.tensor_names):
                info = self._pending_sigs.pop(name, None)
                if info is None:
                    # A response for a tensor this rank never submitted
                    # through the full path: the negotiation streams
                    # have diverged; continuing would silently diverge
                    # the cache next.
                    raise ConnectionError(
                        f"negotiated response for tensor {name!r} "
                        f"without a matching local request — control "
                        f"plane corrupted")
                sig, dtype, slice_numel = info
                cache.put(name, sig, self._unfuse(resp, i, world_size),
                          dtype, slice_numel)

    # -- metrics plane ---------------------------------------------------
    def _collect_runtime_metrics(self) -> None:
        """Registry collector: mirror counters whose true source lives
        on hot paths that must not pay per-event metric calls (cache
        hit/miss tallies, cycle counts, queue depth, per-peer
        heartbeat ages). Runs once per snapshot, never per event."""
        c = self._cache
        if c is not None:
            self._m_cache_hits.set_total(c.hits)
            self._m_cache_misses.set_total(c.misses)
            self._m_cache_evictions.set_total(c.evictions)
            self._m_cache_entries.set(len(c))
        self._m_world_size.set(self.controller.size)
        if self._elastic is not None:
            self._m_world_resizes.set_total(self._elastic.resizes)
            self._m_elastic_rejoins.set_total(
                self._elastic.rejoins_admitted)
            for v in self._elastic.take_rendezvous_observations():
                self._m_rdzv_s.observe(v)
            self._m_sync_bytes.set_total(
                self._elastic.sync_bytes_total)
            for dt_s, _ in self._elastic.take_sync_observations():
                self._m_sync_s.observe(dt_s)
        # Supervision decisions mirror lazily per kind — the series
        # appears the first time the policy makes that decision.
        for kind, n in selfop.decision_counts().items():
            m = self._selfop_decision_metrics.get(kind)
            if m is None:
                m = self.metrics.counter(
                    f'hvd_supervisor_decisions_total{{kind="{kind}"}}',
                    "supervision-policy decisions this process made "
                    "(common/selfop.py)")
                self._selfop_decision_metrics[kind] = m
            m.set_total(n)
        self._m_ckpt_age.set(selfop.checkpoint_age_s())
        # Scaling efficiencies mirror lazily per world size, same
        # doctrine: the series appears once something measured one
        # (the MULTICHIP harness, or an operator calibration pass).
        for n, eff in hmetrics.scaling_efficiencies().items():
            g = self._scaling_eff_metrics.get(n)
            if g is None:
                g = self.metrics.gauge(
                    f'hvd_scaling_efficiency{{world_size="{n}"}}',
                    "measured throughput fraction of ideal linear "
                    "scaling at this world size (fed by "
                    "__graft_entry__.run_multichip)")
                self._scaling_eff_metrics[n] = g
            g.set(eff)
        self._m_cycles.set_total(self._cycle_count)
        self._m_cached_cycles.set_total(self._cached_cycles)
        self._m_spec_cycles.set_total(self._spec_cycles)
        self._m_spec_bids.set_total(self._spec_bids)
        self._m_spec_denials.set_total(self._spec_denials_total)
        self._m_native_steady.set_total(self._native_steady_cycles)
        self._m_overlap_cycles.set_total(self._overlap_cycles)
        self._m_overlap_buckets.set_total(
            self._overlap_buckets_submitted)
        self._m_inflight.set(
            self._overlap.outstanding if self._overlap is not None
            else 0)
        self._m_arena_bytes.set(harena.total_bytes())
        self._m_queue_depth.set(len(self.tensor_table))
        self._m_lock_inversions.set_total(lockdep.inversion_count())
        self._m_affinity_violations.set_total(
            threadcheck.violation_count())
        self._m_trace_spans.set_total(self._trace_spans_sent)
        for r, age in self.controller.peer_heartbeat_ages().items():
            self.metrics.gauge(
                f'hvd_peer_heartbeat_age_seconds{{peer="{r}"}}',
                "seconds since the last control frame from this peer",
                agg=hmetrics.AGG_MAX).set(age)

    def _maybe_publish_metrics(self) -> None:
        """Per-interval fold point (background thread only): snapshot
        the local registry, then either feed the rank-0 aggregator
        (plus the JSONL log) or ship one compact METRICS frame up the
        control tree — out-of-band, the way PING frames ride."""
        now = time.monotonic()
        if now - self._metrics_last_pub \
                < self.config.metrics_interval_s:
            return
        self._metrics_last_pub = now
        snap = self.metrics.snapshot()
        if self._aggregator is not None:
            self._aggregator.update_local(snap)
            if self._metrics_log is not None:
                self._metrics_log.append(self._aggregator.world())
            return
        try:
            payload = wire.serialize_metrics_frame(1, snap)
        except Exception:
            return  # a malformed record must not kill the loop
        self.controller.send_metrics(payload)

    def metrics_view(self) -> Dict:
        """The horovod_tpu.metrics() payload: the freshest local
        snapshot, the world aggregate (rank 0; None elsewhere — the
        world view materializes only at the fold point), and the HTTP
        port when the Prometheus endpoint is live."""
        local = self.metrics.snapshot()
        view = {"enabled": self._metrics_on, "local": local,
                "world": None, "http_port": None}
        if self._aggregator is not None:
            self._aggregator.update_local(local)
            world = self._aggregator.world()
            if not self._world_id:
                # The fleet's read surface also carries its co-located
                # tenants' world folds: every tenant series is
                # tenant-labelled, so the merge is collision-free (a
                # tenant whose coordinator lives elsewhere appears on
                # THAT process's surface instead).
                world = _merge_tenant_worlds(world)
            view["world"] = world
        if self._metrics_http is not None:
            view["http_port"] = self._metrics_http.port
        return view

    def _world_status_line(self) -> str:
        """Steady-state health context for the stall report: queue
        depth and timeline drops always; per-peer heartbeat ages when
        the metrics plane maintains them — one warning then carries
        enough to diagnose without a second tool."""
        parts = [f"world cycle {self._world_cycle}",
                 f"tensor queue depth {len(self.tensor_table)}"]
        if self._world_id:
            # Per-tenant line: which job this runtime serves, and how
            # the process-local scheduler has been treating it — a
            # starved tenant's stall warning answers "why" inline.
            line = (f"tenant {self._tenant or '?'} "
                    f"(world {self._world_id:#010x})")
            lane = self._tenant_lane
            if lane is not None:
                line += ": " + lane.status_line()
            parts.append(line)
        if self._last_wire_verdict is not None:
            alg, w = self._last_wire_verdict
            line = (f"wire plan {_wd.ALG_NAMES.get(alg, alg)}"
                    f"/{_wd.WIRE_NAMES.get(w, w)}")
            if self._ici_plane is not None:
                # Whether the mesh leg is actually carrying cycles —
                # an ici verdict with 0 mesh cycles means every pack
                # fell back to the host path (see troubleshooting.md).
                line += (f" (ici mesh {self._ici_plane.ndev} devices, "
                         f"{self._ici_cycles} cycles)")
            parts.append(line)
        if self._elastic is not None:
            parts.append(self._elastic.world_line())
        selfop_line = self._selfop_policy.status_line()
        if selfop_line:
            parts.append(selfop_line)
        ages = self.controller.peer_heartbeat_ages()
        if ages:
            # Ages are last-frame-to-now durations measured on THIS
            # host's clock — on rank 0 (where the stall report runs)
            # that IS the coordinator clock, and the offsets line
            # below quantifies how far each peer's own clock sits
            # from it, so a skewed host's timeline no longer reads
            # as "silent".
            worst = sorted(ages.items(), key=lambda kv: -kv[1])[:4]
            parts.append(
                "oldest peer heartbeat ages (coordinator clock): "
                + ", ".join(f"rank {r} {a:.1f}s" for r, a in worst))
        if self.controller.is_coordinator:
            offs = htrace.clock_offsets_line()
            if offs:
                parts.append("peer clock offsets vs coordinator: "
                             + offs)
        if self.timeline.dropped_events:
            parts.append(f"timeline events dropped "
                         f"{self.timeline.dropped_events}")
        return "; ".join(parts)

    def negotiation_cache_stats(self) -> Dict:
        """Local observability for benchmarks, tests and the stall
        report: lookup hit/miss counters, cached-cycle count, and the
        coherent-state epoch."""
        c = self._cache
        if c is None:
            return {"enabled": False}
        total = c.hits + c.misses
        return {"enabled": True, "capacity": c.capacity,
                "entries": len(c), "hits": c.hits, "misses": c.misses,
                "hit_rate": (c.hits / total) if total else 0.0,
                "cached_cycles": self._cached_cycles,
                "spec_cycles": self._spec_cycles,
                "spec_bids": self._spec_bids,
                "native_steady_cycles": self._native_steady_cycles,
                "ici_cycles": self._ici_cycles,
                "ici_compiles": (self._ici_plane.compiles
                                 if self._ici_plane is not None else 0),
                "overlap_cycles": self._overlap_cycles,
                "overlap_inflight": (self._overlap.outstanding
                                     if self._overlap is not None
                                     else 0),
                "epoch": c.epoch}

    def _cache_stats_line(self) -> str:
        s = self.negotiation_cache_stats()
        if not s.get("enabled"):
            return ""
        return (f"cache: {s['hits']} hits / {s['misses']} misses "
                f"({s['hit_rate']:.1%} hit rate), "
                f"{s['cached_cycles']} fully cached cycles "
                f"({s['spec_cycles']} fused single-round, "
                f"{s['native_steady_cycles']} native zero-copy, "
                f"{s['overlap_cycles']} overlapped), "
                f"{s['entries']}/{s['capacity']} slots")

    def _check_stall(self, table: MessageTable, size: int) -> None:
        """Periodic coordinator-side stall scan — runs on EVERY cycle
        shape, including fused speculative ones (a tensor one rank
        submitted the full way can sit in the MessageTable while the
        rest of the world hums along in fused steady state; the PR 2
        stall warnings and fail-fast shutdown must still see it)."""
        if not self._stall.should_check():
            return
        straggler = (self._straggler.report_line()
                     if self._straggler is not None else "")
        if self._stall.check(table,
                             cache_stats=self._cache_stats_line(),
                             world_stats=self._world_status_line(),
                             straggler_stats=straggler):
            self._flight.record(htrace.EV_STALL, self._world_cycle,
                                note="stall shutdown threshold")
            # The stall-shutdown threshold fires the fail-fast
            # abort so every rank gets a structured error naming
            # the condition, instead of the silent clean-shutdown
            # fan-out the reference performs (operations.cc:609).
            # Blame the stalled rank(s), not the healthy
            # coordinator observing them: the missing ranks on the
            # OLDEST pending tensor are the culprits. origin -1
            # ("unknown rank") only if the table emptied racily.
            origin, missing_note = -1, ""
            pending = sorted(table.pending(), key=lambda p: -p[1])
            if pending:
                name, _, reported = pending[0]
                missing = [r for r in range(size)
                           if r not in set(reported)]
                if missing:
                    origin = min(missing)
                    missing_note = (f" (tensor '{name}' never "
                                    f"submitted by ranks "
                                    f"{missing})")
            cause = ("stall shutdown threshold "
                     f"({self._stall.shutdown_time:g}s) exceeded: "
                     "one or more tensors were never submitted by "
                     "every rank (see coordinator stall warnings "
                     f"for names and missing ranks){missing_note}")
            raise WorldAbortedError(world_abort_message(origin,
                                                        cause),
                                    origin_rank=origin, cause=cause)

    def _stamp_wire_plan(self, fused: List[Response]) -> None:
        """Coordinator-side algorithm/dtype stamping of a cycle's
        fused allreduce batches: the policy (static config or the
        autotuner's per-bucket table) picks the ALG_* route for the
        batch's UNCOMPRESSED size and may cap the min-resolved wire
        dtype (the tuner explores dtypes by capping — it can only
        ever weaken a rank's proposal, never exceed it, so tuning
        stays numerics-safe). Runs before the broadcast, so the
        verdicts ride the same world-identical response stream as
        everything else."""
        for resp in fused:
            if resp.response_type != ResponseType.ALLREDUCE \
                    or not resp.tensor_names:
                continue
            dtype = self._dtypes.get(resp.tensor_names[0])
            if dtype is None:
                continue
            nbytes = sum(resp.tensor_sizes) * datatype_size(dtype)
            alg, cap = self._wire_policy.plan(nbytes)
            resp.algorithm = alg
            if cap is not None and resp.wire_dtype > cap:
                resp.wire_dtype = cap
            if alg or resp.wire_dtype:
                self._last_wire_verdict = (alg, resp.wire_dtype)
                self.timeline.wire_plan(
                    f"{_wd.ALG_NAMES[alg]}/"
                    f"{_wd.WIRE_NAMES[resp.wire_dtype]}")

    def _coordinate(self, req_lists: List[RequestList],
                    extra_shutdown: bool = False) -> ResponseList:
        """Coordinator half of the cycle
        (reference: operations.cc:1018-1258)."""
        table = self._message_table
        size = self.controller.size
        shutdown = extra_shutdown
        for rl in req_lists:
            shutdown = shutdown or rl.shutdown
            for req in rl.requests:
                self._dtypes[req.tensor_name] = req.tensor_type
                numel = 1
                for d in req.tensor_shape[1:]:
                    numel *= d
                self._slice_numels[req.tensor_name] = numel
                table.increment_tensor_count(req, size, self.timeline)
        ready = table.pop_ready()
        responses = []
        for name in ready:
            resp = construct_response(table, name, size)
            # The NEGOTIATE_* span's end names the resolved wire
            # dtype, so a timeline reader can see compression engage
            # per tensor without cross-referencing metrics.
            self.timeline.negotiate_end(
                name, verdict=_wd.WIRE_NAMES[resp.wire_dtype]
                if resp.wire_dtype else "")
            responses.append(resp)
        threshold = self.config.fusion_threshold_bytes
        if self.parameter_manager is not None:
            threshold = self.parameter_manager.fusion_threshold_bytes()
        fused = fuse_responses(responses, self._dtypes, threshold,
                               self._slice_numels)
        self._stamp_wire_plan(fused)
        for resp in fused:
            for n in resp.tensor_names:
                self._dtypes.pop(n, None)
                self._slice_numels.pop(n, None)

        self._check_stall(table, size)

        resp_list = ResponseList(fused, shutdown=shutdown)
        if self.parameter_manager is not None:
            resp_list.tuned_cycle_time_ms = \
                self.parameter_manager.cycle_time_ms()
            resp_list.tuned_fusion_threshold_bytes = \
                self.parameter_manager.fusion_threshold_bytes()
            resp_list.tuned_overlap_buckets = \
                self.parameter_manager.tuned_overlap_buckets
        elif self._cache is not None:
            # Cached-cycle replay re-fuses granted slots on every rank
            # with this threshold; broadcast the coordinator's value
            # so a rank launched with a divergent
            # HOROVOD_FUSION_THRESHOLD converges instead of building
            # mismatched fused batches from the same grant.
            resp_list.tuned_fusion_threshold_bytes = \
                self.config.fusion_threshold_bytes
        return resp_list

    class _SpanCloser:
        """Closes a fused batch's timeline COLLECTIVE + top-level spans
        exactly once, when the LAST entry's completion callback fires —
        so async (InProgress) collectives trace their true duration
        instead of their issue time, the way the reference's CUDA
        finalizer thread drives Timeline end
        (reference: cuda_operations.cc:148-179). The deferred spans are
        Chrome ASYNC NESTABLE events keyed by a per-batch id: a tensor
        may legally re-negotiate the same name while its previous batch
        is still in flight, and deferred plain B/E events would mispair
        on the per-pid stack. Thread-safe: async callbacks arrive from
        finalizer threads; the timeline is a queue fed from any
        thread."""

        __slots__ = ("_timeline", "_names", "_op_name", "_batch_id",
                     "_remaining", "_lock", "_closed")

        def __init__(self, timeline, names, op_name: str,
                     batch_id: int, n_entries: int):
            self._timeline = timeline
            self._names = names
            self._op_name = op_name
            self._batch_id = batch_id
            self._remaining = n_entries
            self._lock = lockdep.lock("runtime._SpanCloser._lock")
            self._closed = False

        def entry_done(self) -> None:
            with self._lock:
                self._remaining -= 1
                if self._remaining > 0 or self._closed:
                    return
                self._closed = True
            self._close()

        def _close(self) -> None:
            for n in self._names:
                self._timeline.async_end(n, ACT_COLLECTIVE,
                                         self._batch_id)
            for n in self._names:
                self._timeline.async_end(n, self._op_name,
                                         self._batch_id)

    def _perform_operations(self, resp_list: ResponseList) -> None:
        """Execute each agreed response and fire callbacks
        (reference: operations.cc:450-539 PerformOperation)."""
        for response in resp_list.responses:
            self._op_count += 1
            faults.tick_op(self, self._op_count)
            if response.wire_dtype or response.algorithm:
                # Rank-local observability: the stall report names the
                # last applied (algorithm, wire dtype) on every rank,
                # not just the stamping coordinator.
                self._last_wire_verdict = (response.algorithm,
                                           response.wire_dtype)
            entries = self.tensor_table.pop_entries(
                response.tensor_names)
            if response.response_type == ResponseType.ERROR:
                for e in entries:
                    if e.callback:
                        e.callback(
                            Status.PreconditionError(response.error_message))
                continue
            if not entries and response.response_type != ResponseType.BARRIER:
                continue
            names = [e.tensor_name for e in entries]
            op_name = response.response_type.name
            # Async-capable batches trace through async-nestable span
            # events closed at COMPLETION by _SpanCloser; everything
            # else keeps the reference's plain B/E spans.
            use_async_spans = (self.finalizer is not None
                               and self.timeline.enabled
                               and bool(entries))
            closer = None
            if use_async_spans:
                self._batch_seq += 1
                closer = self._SpanCloser(self.timeline, names, op_name,
                                          self._batch_seq, len(entries))
                for n in names:
                    self.timeline.async_start(n, op_name,
                                              self._batch_seq)
            elif self.timeline.enabled:
                for e in entries:
                    self.timeline.start(e.tensor_name, op_name)
            # Input readiness: the reference polls CUDA ReadyEvents here
            # (operations.cc:507-518) because its backends consume raw
            # device pointers. JAX tensors are futures — every consumer
            # (np.asarray on the socket path, device_put/jit on the mesh
            # path) orders on the producing computation, so a blocking
            # poll adds nothing but latency (and is_ready() from a
            # non-main thread costs ~100 ms flat on some platforms).
            # The QUEUE activity stays in the trace as the handoff
            # marker between negotiation and execution.
            self.timeline.activity_start_all(names, ACT_QUEUE)
            self.timeline.activity_end_all(names)

            # Async backends fire entry callbacks from finalizer threads
            # when the collective COMPLETES; pre-wrap them so the batch's
            # timeline spans close at that true end (sync backends fire
            # the same wrappers in-loop below — same path, same result).
            if use_async_spans:
                for n in names:
                    self.timeline.async_start(n, ACT_COLLECTIVE,
                                              self._batch_seq)
                for e in entries:
                    user_cb = e.callback

                    def _cb(status, _u=user_cb, _c=closer):
                        _c.entry_done()
                        if _u:
                            _u(status)

                    e.callback = _cb
            else:
                self.timeline.activity_start_all(names, ACT_COLLECTIVE)
            # 0.0 (not unbound) when dark — _trace_on may flip from
            # another thread mid-execute (the trace-overhead toggle
            # bench); the emit below must then skip, never NameError.
            tx = time.monotonic() if self._trace_on else 0.0
            try:
                status = self.op_manager.execute(entries, response)
            except WorldAbortedError as e:
                # An abort notice surfaced mid-collective (e.g. the
                # controller channel died during a data-plane
                # gather): fail this batch with the structured status,
                # then let the loop-level handler fan the abort. The
                # origin is resolved against any queued control-plane
                # notice BEFORE the callbacks fire — these complete
                # user-visible handles, and a data-plane blame can
                # misattribute a cascading teardown (see _fail_world).
                raise self._data_plane_abort(
                    entries, e.origin_rank,
                    getattr(e, "cause", str(e))) from e
            except (ConnectionError, OSError, TimeoutError) as e:
                # Data-plane transport failure (dead ring neighbor,
                # severed link): this is a world-level event, not a
                # per-batch soft error — a lone UnknownError here
                # would leave every peer blocked mid-collective.
                rank = self.controller.rank
                raise self._data_plane_abort(
                    entries, rank,
                    f"data-plane failure during {op_name} on "
                    f"rank {rank}: {e}") from e
            except Exception as e:
                status = Status.UnknownError(
                    f"collective execution failed: {e!r}")
            if self._trace_on and tx:
                # Issue-side wall time of the batch (async backends
                # complete on finalizer threads — their tail rides
                # the next ROUND span, like the timeline's B span).
                self._trace.slice(f"{op_name} x{len(entries)}", tx,
                                  time.monotonic() - tx,
                                  self._world_cycle)
            if closer is None and self.timeline.enabled:
                self.timeline.activity_end_all(names)
                for e in entries:
                    self.timeline.end(e.tensor_name)
            self._cycle_bytes += sum(
                getattr(e.tensor, "nbytes", 0) for e in entries)
            if not status.in_progress():
                for e in entries:
                    if e.callback:
                        e.callback(status)
# -- thread-affinity sanitizer (HOROVOD_TPU_THREADCHECK) ------------------
# Checked-field ids mirror the static thread-ownership analyzer's.
# _tenant_lane has no fixed owner: it legitimately migrates (main
# binds, background unwinds) under Runtime._lane_lock.
threadcheck.install(Runtime, "_tenant_lane",
                    "runtime.Runtime._tenant_lane")
