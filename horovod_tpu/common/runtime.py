"""The background coordination loop — heart of the runtime.

Python re-architecture of the reference's ``BackgroundThreadLoop`` /
``RunLoopOnce`` / ``PerformOperation``
(reference: horovod/common/operations.cc:662-955, 986-1338, 450-539):
one daemon thread per process paces a negotiation cycle every
``HOROVOD_CYCLE_TIME`` ms; each cycle drains this rank's request queue,
gathers all ranks' requests at the coordinator, fuses ready tensors
under the fusion threshold, broadcasts the agreed ResponseList, and
executes it through the backend priority list. Enqueue APIs return
immediately; completion flows back through per-entry callbacks
(reference: common.h:162 StatusCallback).

Hot-loop notes for TPU: the data plane executed here is an XLA
computation per fused response (see ops/xla_ops.py); this thread only
*issues* it, so the Python cycle overhead rides in the shadow of device
execution, like the reference's detached CUDA finalizer threads
(reference: ops/cuda_operations.cc:148-179).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.common import faults
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import wire
from horovod_tpu.common.config import Config
from horovod_tpu.common.controller import Controller
from horovod_tpu.common.coordinator import (
    MessageTable, StallInspector, construct_response, fuse_responses,
)
from horovod_tpu.common.message import (
    DataType, Request, RequestList, RequestType, Response, ResponseList,
    ResponseType,
)
from horovod_tpu.common.status import (
    DUPLICATE_NAME_ERROR_FMT, SHUT_DOWN_ERROR, Status, WorldAbortedError,
    world_abort_message,
)
from horovod_tpu.common.tensor_table import (
    HandleManager, TensorTable, TensorTableEntry,
)
from horovod_tpu.common.timeline import (
    ACT_COLLECTIVE, ACT_QUEUE, NOOP_TIMELINE, create_timeline,
)
from horovod_tpu.ops.operation_manager import OperationManager


class Runtime:
    """Process-global state + background thread
    (reference: HorovodGlobalState, common/global_state.h:33-136)."""

    def __init__(self, config: Config, controller: Controller,
                 op_manager: OperationManager,
                 parameter_manager=None):
        self.config = config
        self.controller = controller
        self.op_manager = op_manager
        self.tensor_table = TensorTable()
        self.handle_manager = HandleManager()
        self.parameter_manager = parameter_manager
        self.timeline = NOOP_TIMELINE
        if controller.rank == 0 and config.timeline_path:
            self.timeline = create_timeline(config.timeline_path,
                                            config.timeline_mark_cycles)
        op_manager.attach_timeline(self.timeline)
        self._dtypes: Dict[str, DataType] = {}
        # name -> elements per dim-0 row, for allgather fusion byte
        # accounting (reference: TotalByteSizeOfAllgatherOutput).
        self._slice_numels: Dict[str, int] = {}
        self._stall = StallInspector(
            controller.size,
            warning_time=config.stall_check_time_seconds,
            shutdown_time=config.stall_shutdown_time_seconds,
            disabled=config.stall_check_disable)
        # A completed negotiation clears its stall-warning record so a
        # RECURRING tensor name that stalls again warns again.
        self._message_table = MessageTable(
            on_remove=self._stall.tensor_completed) \
            if controller.rank == 0 else None
        # Async completion: backends that return InProgress complete on
        # detached finalizer threads while this loop keeps negotiating
        # (reference: cuda_operations.cc:148-179).
        self.finalizer = None
        if config.async_completion:
            from horovod_tpu.common.finalizer import Finalizer
            self.finalizer = Finalizer()
            op_manager.attach_finalizer(self.finalizer)
        self._shutdown_requested = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[Exception] = None
        # (origin_rank, cause) once the world has aborted: handles that
        # were in flight or are enqueued afterwards fail with a
        # structured WorldAbortedError instead of a generic shutdown.
        self._abort_info: Optional[tuple] = None
        # Lifetime count of executed responses (fault-injection op
        # triggers key off it to land failures squarely mid-collective).
        self._op_count = 0
        faults.load_env()
        # Autotune plumbing: bytes reduced this cycle.
        self._cycle_bytes = 0
        # Monotone id for async-nestable timeline batches.
        self._batch_seq = 0
        # Idle backoff: after _IDLE_GRACE empty cycles the loop ramps
        # its sleep toward config.idle_backoff_ms instead of spinning
        # the negotiation at full cycle rate forever (the reference
        # wakes every cycle_time_ms regardless, operations.cc:987-995 —
        # needless wakeups on a TPU host whose hot path is in-jit).
        # ``_wake`` snaps the loop awake the moment work arrives or
        # shutdown is requested, so pickup latency IMPROVES over a
        # fixed cycle; each rank's sleep is local, and a straggling
        # rank only delays the blocking gather, never deadlocks it.
        self._idle_cycles = 0
        self._cycle_count = 0  # lifetime cycles (observability/tests)
        self._wake = threading.Event()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._background_loop,
                                        name="hvd-background",
                                        daemon=True)
        self._thread.start()

    def request_shutdown(self) -> None:
        self._shutdown_requested.set()
        self._wake.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._done.is_set())

    def _terminal_status(self) -> Status:
        """Status for work that can no longer run: a structured abort
        (naming the failed rank) when the world was torn down by the
        fail-fast protocol, the plain shutdown error otherwise."""
        if self._abort_info is not None:
            origin, cause = self._abort_info
            return Status.WorldAborted(origin, cause)
        return Status.Aborted(SHUT_DOWN_ERROR)

    # -- enqueue APIs (reference: operations.cc:1430-1549) ---------------
    def enqueue(self, request_type: RequestType, entry: TensorTableEntry,
                dtype: DataType, shape, prescale: float = 1.0,
                postscale: float = 1.0) -> Status:
        if self._done.is_set() or self._shutdown_requested.is_set():
            return self._terminal_status()
        req = Request(request_rank=self.controller.rank,
                      request_type=request_type,
                      tensor_type=dtype,
                      tensor_name=entry.tensor_name,
                      root_rank=entry.root_rank,
                      device=entry.device,
                      tensor_shape=shape,
                      prescale_factor=prescale,
                      postscale_factor=postscale)
        entry.request_type = request_type
        if not self.tensor_table.add(entry, req):
            return Status.InvalidArgument(
                DUPLICATE_NAME_ERROR_FMT
                % (request_type.name.lower(), entry.tensor_name))
        if self._done.is_set():
            # The loop exited between the liveness check and the add; the
            # shutdown fan-out may have missed this entry — reclaim it so
            # its handle cannot hang forever.
            if self.tensor_table.pop_entry_if_present(entry.tensor_name):
                return self._terminal_status()
        self._wake.set()  # snap an idle-backed-off loop awake
        return Status.OK()

    def enqueue_group(self, request_type: RequestType, items,
                      prescale: float = 1.0,
                      postscale: float = 1.0) -> Status:
        """Atomically enqueue several entries as one negotiation batch
        (the grouped-collective contract, later-Horovod
        ``grouped_allreduce``): every request enters the same
        RequestList on this rank, so a concurrent cycle tick cannot
        split the group, all members become ready in the same
        coordinator cycle, and compatible members fuse into ONE
        Response under the threshold. ``items`` is a list of
        (entry, dtype, shape)."""
        if self._done.is_set() or self._shutdown_requested.is_set():
            return self._terminal_status()
        pairs = []
        for entry, dtype, shape in items:
            req = Request(request_rank=self.controller.rank,
                          request_type=request_type,
                          tensor_type=dtype,
                          tensor_name=entry.tensor_name,
                          root_rank=entry.root_rank,
                          device=entry.device,
                          tensor_shape=shape,
                          prescale_factor=prescale,
                          postscale_factor=postscale)
            entry.request_type = request_type
            pairs.append((entry, req))
        dup = self.tensor_table.add_all(pairs)
        if dup is not None:
            return Status.InvalidArgument(
                DUPLICATE_NAME_ERROR_FMT
                % (request_type.name.lower(), dup))
        if self._done.is_set():
            # Same liveness race as enqueue(): reclaim anything the
            # shutdown fan-out may have missed. Per-entry, because the
            # fan-out may already have completed some members — their
            # callbacks must not fire twice.
            for entry, _ in pairs:
                if self.tensor_table.pop_entry_if_present(
                        entry.tensor_name) and entry.callback:
                    entry.callback(self._terminal_status())
        self._wake.set()
        return Status.OK()

    def _resolve_abort(self, origin: int, cause: str) -> tuple:
        """A blame inferred from an anonymous transport error can race
        the AUTHORITATIVE notice from the rank that actually detected
        the failure — its teardown closes channels, which peers see as
        a second, misattributable failure (a ring survivor names its
        dead neighbor and collapses; this rank only sees the
        survivor's close). Sweep the control plane for a
        queued/just-arriving ABORT and defer to it — the whole world
        then converges on one origin. Failure path only; adds nothing
        to healthy cycles."""
        try:
            notice = self.controller.drain_abort_notice(0.25)
        except Exception:
            notice = None
        return notice if notice is not None else (origin, cause)

    def _data_plane_abort(self, entries, origin: int,
                          cause: str) -> WorldAbortedError:
        """Fail a mid-collective batch as a world abort: resolve the
        origin against the control plane FIRST (the callbacks complete
        user-visible handles — they must carry the converged origin),
        fire the callbacks, and return the error for the caller to
        raise into the loop-level handler."""
        origin, cause = self._resolve_abort(origin, cause)
        status = Status.WorldAborted(origin, cause)
        for en in entries:
            if en.callback:
                en.callback(status)
        err = WorldAbortedError(world_abort_message(origin, cause),
                                origin_rank=origin, cause=cause)
        err.resolved = True  # _fail_world: don't re-drain
        return err

    def _fail_world(self, origin: int, cause: str,
                    resolved: bool = False) -> None:
        """Record the world abort and fan the notice to every
        reachable peer (see _resolve_abort for why an unresolved blame
        is checked against the control plane before committing)."""
        if not resolved:
            origin, cause = self._resolve_abort(origin, cause)
        self._error = WorldAbortedError(
            world_abort_message(origin, cause), origin_rank=origin,
            cause=cause)
        self._abort_info = (origin, cause)
        hlog.error(f"horovod_tpu world aborted: {self._error}",
                   rank=self.controller.rank)
        try:
            self.controller.abort(origin, cause)
        except Exception:
            pass

    # -- the loop --------------------------------------------------------
    def _background_loop(self) -> None:
        try:
            while self._run_loop_once():
                pass
        except WorldAbortedError as e:
            # Either received over the wire (a peer initiated the
            # abort) or raised locally (we detected the failure). Fan
            # the notice to every peer we can still reach — relays are
            # idempotent, so re-fanning a received abort is harmless —
            # then fail everything in flight with the structured error.
            # The BARE cause travels/persists, so each hop wraps the
            # origin banner exactly once.
            self._fail_world(e.origin_rank, getattr(e, "cause", str(e)),
                             resolved=getattr(e, "resolved", False))
        except (ConnectionError, OSError, TimeoutError) as e:
            # Transport failure nobody upstream could name: this rank
            # is the origin as far as the rest of the world knows.
            rank = self.controller.rank
            self._fail_world(rank,
                             f"transport failure on rank {rank}: {e}")
        except Exception as e:  # backend bug, ...
            self._error = e
            hlog.error(f"horovod_tpu background loop failed: {e!r}",
                       rank=self.controller.rank)
        finally:
            self._done.set()
            # Drain in-flight async completions first so every issued
            # collective fires its real status, then fail what was never
            # issued (reference: operations.cc:898-913).
            if self.finalizer is not None:
                self.finalizer.drain()
            terminal = self._terminal_status()
            for entry in self.tensor_table.pop_all():
                if entry.callback:
                    entry.callback(terminal)
            self.timeline.shutdown()
            self.op_manager.close()
            try:
                self.controller.close()
            except Exception:
                pass

    _IDLE_GRACE = 16  # empty cycles before the backoff ramp starts

    def _run_loop_once(self) -> bool:
        """One negotiation cycle; returns False to exit
        (reference: operations.cc:986-1338)."""
        t0 = time.monotonic()
        self._cycle_count += 1
        faults.tick_cycle(self, self._cycle_count)
        self.timeline.mark_cycle_start()

        requests = self.tensor_table.pop_messages()
        shutting_down = self._shutdown_requested.is_set()
        req_list = RequestList(requests, shutdown=shutting_down)
        payload = wire.serialize_request_list(req_list)

        gathered = self.controller.gather_requests(payload)
        if self.controller.is_coordinator:
            resp_list = self._coordinate(gathered)
            self.controller.broadcast_responses(
                wire.serialize_response_list(resp_list))
        else:
            data = self.controller.broadcast_responses(None)
            resp_list = wire.parse_response_list(data)

        self._perform_operations(resp_list)

        if resp_list.shutdown:
            return False

        # Pace the cycle (reference: operations.cc:987-995). The autotuner
        # may be steering cycle_time_ms (reference: parameter_manager.cc).
        cycle_time_ms = self.config.cycle_time_ms
        if self.parameter_manager is not None:
            self.parameter_manager.apply_synced(
                resp_list.tuned_fusion_threshold_bytes,
                resp_list.tuned_cycle_time_ms)
            self.parameter_manager.on_cycle(self._cycle_bytes)
            self._cycle_bytes = 0
            cycle_time_ms = self.parameter_manager.cycle_time_ms()
        if resp_list.responses or requests:
            # Local submissions count as activity too: a rank whose own
            # tensor is still negotiating (peers not yet submitted)
            # must keep cycling at full rate or the blocking gather
            # makes the whole world pay its backoff sleep.
            self._idle_cycles = 0
        else:
            self._idle_cycles += 1
        elapsed = time.monotonic() - t0
        sleep_s = cycle_time_ms / 1000.0 - elapsed
        backoff_ms = self.config.idle_backoff_ms
        if backoff_ms > 0 and self._idle_cycles > self._IDLE_GRACE:
            backoff_s = backoff_ms / 1000.0
            if self.config.heartbeat_timeout_s > 0:
                # A sleeping rank sends nothing; its only proof of
                # life is the next cycle's request frame. Cap the
                # backoff under the heartbeat deadline or an idle
                # world's waiting peers would declare the sleeper
                # dead (the two knobs are set independently).
                backoff_s = min(backoff_s,
                                self.config.heartbeat_timeout_s / 2.0)
            ramp = (cycle_time_ms / 1000.0
                    * (self._idle_cycles - self._IDLE_GRACE))
            sleep_s = max(sleep_s, min(backoff_s, ramp))
        if sleep_s > 0:
            # Wake early on shutdown OR new local work (enqueue sets
            # _wake) so backoff never adds submit latency.
            self._wake.wait(sleep_s)
        self._wake.clear()
        return True

    def _coordinate(self, gathered: List[bytes]) -> ResponseList:
        """Coordinator half of the cycle
        (reference: operations.cc:1018-1258)."""
        table = self._message_table
        size = self.controller.size
        shutdown = False
        for data in gathered:
            rl = wire.parse_request_list(data)
            shutdown = shutdown or rl.shutdown
            for req in rl.requests:
                self._dtypes[req.tensor_name] = req.tensor_type
                numel = 1
                for d in req.tensor_shape[1:]:
                    numel *= d
                self._slice_numels[req.tensor_name] = numel
                table.increment_tensor_count(req, size, self.timeline)
        ready = table.pop_ready()
        responses = []
        for name in ready:
            self.timeline.negotiate_end(name)
            responses.append(construct_response(table, name, size))
        threshold = self.config.fusion_threshold_bytes
        if self.parameter_manager is not None:
            threshold = self.parameter_manager.fusion_threshold_bytes()
        fused = fuse_responses(responses, self._dtypes, threshold,
                               self._slice_numels)
        for resp in fused:
            for n in resp.tensor_names:
                self._dtypes.pop(n, None)
                self._slice_numels.pop(n, None)

        if self._stall.should_check():
            if self._stall.check(table):
                # The stall-shutdown threshold fires the fail-fast
                # abort so every rank gets a structured error naming
                # the condition, instead of the silent clean-shutdown
                # fan-out the reference performs (operations.cc:609).
                # Blame the stalled rank(s), not the healthy
                # coordinator observing them: the missing ranks on the
                # OLDEST pending tensor are the culprits. origin -1
                # ("unknown rank") only if the table emptied racily.
                origin, missing_note = -1, ""
                pending = sorted(table.pending(), key=lambda p: -p[1])
                if pending:
                    name, _, reported = pending[0]
                    missing = [r for r in range(size)
                               if r not in set(reported)]
                    if missing:
                        origin = min(missing)
                        missing_note = (f" (tensor '{name}' never "
                                        f"submitted by ranks "
                                        f"{missing})")
                cause = ("stall shutdown threshold "
                         f"({self._stall.shutdown_time:g}s) exceeded: "
                         "one or more tensors were never submitted by "
                         "every rank (see coordinator stall warnings "
                         f"for names and missing ranks){missing_note}")
                raise WorldAbortedError(world_abort_message(origin,
                                                           cause),
                                        origin_rank=origin, cause=cause)

        resp_list = ResponseList(fused, shutdown=shutdown)
        if self.parameter_manager is not None:
            resp_list.tuned_cycle_time_ms = \
                self.parameter_manager.cycle_time_ms()
            resp_list.tuned_fusion_threshold_bytes = \
                self.parameter_manager.fusion_threshold_bytes()
        return resp_list

    class _SpanCloser:
        """Closes a fused batch's timeline COLLECTIVE + top-level spans
        exactly once, when the LAST entry's completion callback fires —
        so async (InProgress) collectives trace their true duration
        instead of their issue time, the way the reference's CUDA
        finalizer thread drives Timeline end
        (reference: cuda_operations.cc:148-179). The deferred spans are
        Chrome ASYNC NESTABLE events keyed by a per-batch id: a tensor
        may legally re-negotiate the same name while its previous batch
        is still in flight, and deferred plain B/E events would mispair
        on the per-pid stack. Thread-safe: async callbacks arrive from
        finalizer threads; the timeline is a queue fed from any
        thread."""

        __slots__ = ("_timeline", "_names", "_op_name", "_batch_id",
                     "_remaining", "_lock", "_closed")

        def __init__(self, timeline, names, op_name: str,
                     batch_id: int, n_entries: int):
            self._timeline = timeline
            self._names = names
            self._op_name = op_name
            self._batch_id = batch_id
            self._remaining = n_entries
            self._lock = threading.Lock()
            self._closed = False

        def entry_done(self) -> None:
            with self._lock:
                self._remaining -= 1
                if self._remaining > 0 or self._closed:
                    return
                self._closed = True
            self._close()

        def _close(self) -> None:
            for n in self._names:
                self._timeline.async_end(n, ACT_COLLECTIVE,
                                         self._batch_id)
            for n in self._names:
                self._timeline.async_end(n, self._op_name,
                                         self._batch_id)

    def _perform_operations(self, resp_list: ResponseList) -> None:
        """Execute each agreed response and fire callbacks
        (reference: operations.cc:450-539 PerformOperation)."""
        for response in resp_list.responses:
            self._op_count += 1
            faults.tick_op(self, self._op_count)
            entries: List[TensorTableEntry] = []
            for name in response.tensor_names:
                entry = self.tensor_table.get_entry(name)
                if entry is not None:
                    entries.append(self.tensor_table.pop_entry(name))
            if response.response_type == ResponseType.ERROR:
                for e in entries:
                    if e.callback:
                        e.callback(
                            Status.PreconditionError(response.error_message))
                continue
            if not entries and response.response_type != ResponseType.BARRIER:
                continue
            names = [e.tensor_name for e in entries]
            op_name = response.response_type.name
            # Async-capable batches trace through async-nestable span
            # events closed at COMPLETION by _SpanCloser; everything
            # else keeps the reference's plain B/E spans.
            use_async_spans = (self.finalizer is not None
                               and self.timeline.enabled
                               and bool(entries))
            closer = None
            if use_async_spans:
                self._batch_seq += 1
                closer = self._SpanCloser(self.timeline, names, op_name,
                                          self._batch_seq, len(entries))
                for n in names:
                    self.timeline.async_start(n, op_name,
                                              self._batch_seq)
            else:
                for e in entries:
                    self.timeline.start(e.tensor_name, op_name)
            # Input readiness: the reference polls CUDA ReadyEvents here
            # (operations.cc:507-518) because its backends consume raw
            # device pointers. JAX tensors are futures — every consumer
            # (np.asarray on the socket path, device_put/jit on the mesh
            # path) orders on the producing computation, so a blocking
            # poll adds nothing but latency (and is_ready() from a
            # non-main thread costs ~100 ms flat on some platforms).
            # The QUEUE activity stays in the trace as the handoff
            # marker between negotiation and execution.
            self.timeline.activity_start_all(names, ACT_QUEUE)
            self.timeline.activity_end_all(names)

            # Async backends fire entry callbacks from finalizer threads
            # when the collective COMPLETES; pre-wrap them so the batch's
            # timeline spans close at that true end (sync backends fire
            # the same wrappers in-loop below — same path, same result).
            if use_async_spans:
                for n in names:
                    self.timeline.async_start(n, ACT_COLLECTIVE,
                                              self._batch_seq)
                for e in entries:
                    user_cb = e.callback

                    def _cb(status, _u=user_cb, _c=closer):
                        _c.entry_done()
                        if _u:
                            _u(status)

                    e.callback = _cb
            else:
                self.timeline.activity_start_all(names, ACT_COLLECTIVE)
            try:
                status = self.op_manager.execute(entries, response)
            except WorldAbortedError as e:
                # An abort notice surfaced mid-collective (e.g. the
                # controller channel died during a data-plane
                # gather): fail this batch with the structured status,
                # then let the loop-level handler fan the abort. The
                # origin is resolved against any queued control-plane
                # notice BEFORE the callbacks fire — these complete
                # user-visible handles, and a data-plane blame can
                # misattribute a cascading teardown (see _fail_world).
                raise self._data_plane_abort(
                    entries, e.origin_rank,
                    getattr(e, "cause", str(e))) from e
            except (ConnectionError, OSError, TimeoutError) as e:
                # Data-plane transport failure (dead ring neighbor,
                # severed link): this is a world-level event, not a
                # per-batch soft error — a lone UnknownError here
                # would leave every peer blocked mid-collective.
                rank = self.controller.rank
                raise self._data_plane_abort(
                    entries, rank,
                    f"data-plane failure during {op_name} on "
                    f"rank {rank}: {e}") from e
            except Exception as e:
                status = Status.UnknownError(
                    f"collective execution failed: {e!r}")
            if closer is None:
                self.timeline.activity_end_all(names)
                for e in entries:
                    self.timeline.end(e.tensor_name)
            self._cycle_bytes += sum(
                getattr(e.tensor, "nbytes", 0) for e in entries)
            if not status.in_progress():
                for e in entries:
                    if e.callback:
                        e.callback(status)
