"""Heartbeat and abort frames for the fail-fast control plane.

The reference has no liveness story at all: a rank that dies uncleanly
(SIGKILL, OOM, host loss) leaves every peer blocked in a control-plane
recv forever, and only the external launcher's kill-on-exit unblocks
them (reference: horovod/run/run.py). This module defines the two tiny
wire payloads the TPU port uses to do better:

``PING``  — sent DOWN the control tree (coordinator -> owners, local
root -> leaves) whenever the sender is alive but has nothing else to
say: its gather is idle-waiting on a straggler. A receiver treats any
frame — ping or real — as proof of life and resets its recv deadline,
so a healthy-but-waiting world never false-positives while a silent
peer is detected within ``HOROVOD_HEARTBEAT_TIMEOUT``.

``ABORT`` — fanned down the relay tree (and escalated up by workers)
when any rank observes a transport failure, a data-plane exception, or
the stall-shutdown threshold. Carries the originating global rank and
a human-readable cause, which every survivor surfaces as a structured
:class:`~horovod_tpu.common.status.WorldAbortedError`.

Both payloads are fixed little-endian structs (+ UTF-8 cause) so they
can be produced/parsed by the native core later without a codec
dependency.
"""

from __future__ import annotations

import struct
from typing import Tuple

_PING = struct.Struct("<iQ")        # sender rank | monotone sequence
_ABORT_HEAD = struct.Struct("<iI")  # origin rank | cause byte length


def encode_ping(rank: int, seq: int) -> bytes:
    return _PING.pack(rank, seq)


def decode_ping(payload: bytes) -> Tuple[int, int]:
    """-> (sender_rank, sequence). Raises ValueError on a bad frame."""
    if len(payload) != _PING.size:
        raise ValueError(
            f"ping frame must be {_PING.size} bytes, got {len(payload)}")
    return _PING.unpack(payload)


def encode_abort(origin_rank: int, cause: str) -> bytes:
    body = cause.encode("utf-8")
    return _ABORT_HEAD.pack(origin_rank, len(body)) + body


def decode_abort(payload: bytes) -> Tuple[int, str]:
    """-> (origin_rank, cause). Tolerates a truncated cause (a dying
    sender may not flush the whole frame) but rejects a short header."""
    if len(payload) < _ABORT_HEAD.size:
        raise ValueError(
            f"abort frame too short: {len(payload)} bytes")
    origin, n = _ABORT_HEAD.unpack_from(payload, 0)
    body = payload[_ABORT_HEAD.size:_ABORT_HEAD.size + n]
    return origin, body.decode("utf-8", errors="replace")
