"""Tensor table, message queue and handle manager.

The tensor table holds the per-process payloads of in-flight collectives,
keyed by name, while the message queue carries the matching Requests to
the background loop (reference: horovod/common/global_state.h:48-57 and
common.h:165-184 ``TensorTableEntry``/``TensorTable``). Handles mirror
the torch binding's ``HandleManager`` (reference:
horovod/torch/handle_manager.h:31-42) and are used by every async API.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.common import lockdep
from horovod_tpu.common.message import Request
from horovod_tpu.common.status import Status


class TensorTableEntry:
    """One in-flight collective on this process
    (reference: common.h:165-182)."""

    __slots__ = ("tensor_name", "tensor", "output", "root_rank", "device",
                 "callback", "ready_fn", "request_type", "context")

    def __init__(self, tensor_name: str, tensor: Any,
                 root_rank: int = -1, device: int = -1,
                 callback: Optional[Callable[[Status], None]] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 request_type=None, context: Any = None):
        self.tensor_name = tensor_name
        self.tensor = tensor          # input payload (numpy or jax array)
        self.output = None            # set by the executing backend
        self.root_rank = root_rank
        self.device = device
        self.callback = callback
        self.ready_fn = ready_fn      # None => ready immediately
        self.request_type = request_type
        self.context = context        # adapter-specific opaque state


class TensorTable:
    """Name-keyed table of pending entries + the per-cycle message queue,
    guarded by one mutex like the reference's
    (reference: operations.cc:1455 mutex usage)."""

    def __init__(self):
        self._lock = lockdep.lock("tensor_table.TensorTable._lock")
        self._table: Dict[str, TensorTableEntry] = {}
        self._message_queue: List[Request] = []

    def add(self, entry: TensorTableEntry, request: Request) -> bool:
        """Insert entry + request atomically. Returns False on duplicate
        name (reference: operations.cc:1459-1462 DUPLICATE_NAME_ERROR)."""
        with self._lock:
            if entry.tensor_name in self._table:
                return False
            self._table[entry.tensor_name] = entry
            self._message_queue.append(request)
            return True

    def add_all(self, pairs) -> Optional[str]:
        """Insert several (entry, request) pairs under ONE lock hold —
        all-or-nothing, and atomic w.r.t. pop_messages, so a concurrent
        cycle tick can never split the batch across two RequestLists
        (the grouped-allreduce atomicity contract). Returns the first
        duplicate name, or None on success."""
        with self._lock:
            for entry, _ in pairs:
                if entry.tensor_name in self._table:
                    return entry.tensor_name
            for entry, request in pairs:
                self._table[entry.tensor_name] = entry
                self._message_queue.append(request)
            return None

    def pop_messages(self) -> List[Request]:
        """Drain the message queue for this cycle
        (reference: operations.cc:1000-1012)."""
        with self._lock:
            msgs = self._message_queue
            self._message_queue = []
            return msgs

    def requeue(self, requests: List[Request]) -> None:
        """Return popped requests to the FRONT of the message queue, in
        order (negotiation fast path: a cache hit the world did not
        grant this cycle stays pending and rides the next cycle's
        bitmask). Requests whose entry vanished meanwhile (shutdown
        fan-out reclaimed it) are dropped — resurrecting them would
        complete a handle twice."""
        with self._lock:
            live = [r for r in requests if r.tensor_name in self._table]
            if live:
                self._message_queue[:0] = live

    def queue_pending(self) -> bool:
        """True if any request is waiting for the next cycle (new
        submissions or fast-path requeues) — the cycle loop's signal
        that it must start another negotiation round immediately."""
        with self._lock:
            return bool(self._message_queue)

    def pop_entry(self, name: str) -> TensorTableEntry:
        with self._lock:
            return self._table.pop(name)

    def peek_entries(self, names):
        """The entries for ``names`` WITHOUT removing them, or None if
        any is absent — the speculative fused cycle packs its payload
        from live entries but must not consume them until the world
        confirms the grant (a mispredicted cycle falls back to the
        classic path, which pops them itself)."""
        with self._lock:
            table = self._table
            try:
                return [table[n] for n in names]
            except KeyError:
                return None

    def pop_entries(self, names) -> List[TensorTableEntry]:
        """Remove and return the present entries among ``names`` under
        ONE lock acquisition — a fused response's per-entry get/pop
        pairs are a measurable share of the execution hot path."""
        with self._lock:
            table = self._table
            return [table.pop(n) for n in names if n in table]

    def pop_entry_if_present(self, name: str):
        with self._lock:
            self._message_queue = [m for m in self._message_queue
                                   if m.tensor_name != name]
            return self._table.pop(name, None)

    def get_entry(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._table.get(name)

    def pop_all(self) -> List[TensorTableEntry]:
        """Remove and return every pending entry (shutdown fan-out,
        reference: operations.cc:898-913)."""
        with self._lock:
            entries = list(self._table.values())
            self._table.clear()
            self._message_queue = []
            return entries

    def __len__(self):
        with self._lock:
            return len(self._table)


# Process-lifetime handle watermark: an elastic resize
# (common/elastic.py) replaces the Runtime — and with it the
# HandleManager — while user code may still hold handles from the old
# world. Restarting ids at 0 would let a stale handle COLLIDE with a
# fresh one and silently return the wrong tensor; continuing from the
# watermark makes a stale handle an unambiguous "Invalid handle"
# instead. Only one live manager allocates at a time (the old
# runtime is torn down before the new one starts), so the plain
# module global needs no lock of its own.
_HANDLE_WATERMARK = 0


class HandleManager:
    """Integer handles for async ops; poll/wait on completion status
    (reference: horovod/torch/handle_manager.{h,cc}). Ids are unique
    across every manager the process ever creates (elastic resizes
    create a new one per world generation — see _HANDLE_WATERMARK)."""

    def __init__(self):
        self._lock = lockdep.lock("tensor_table.HandleManager._lock")
        self._cv = threading.Condition(self._lock)
        self._base = _HANDLE_WATERMARK  # ids at or below: prior manager
        self._last = _HANDLE_WATERMARK
        self._waiters = 0
        self._results: Dict[int, Optional[Status]] = {}
        self._outputs: Dict[int, Any] = {}

    def from_prior_generation(self, handle: int) -> bool:
        """True when ``handle`` was allocated by a manager that
        predates this one (an elastic resize replaced the runtime):
        its collective completed — with WorldAbortedError — before
        the old world tore down. Distinguishes that case from
        current-world misuse (double release, garbage id)."""
        return 0 < handle <= self._base

    def allocate(self) -> int:
        global _HANDLE_WATERMARK
        with self._lock:
            self._last += 1
            handle = self._last
            _HANDLE_WATERMARK = self._last
            self._results[handle] = None
            return handle

    def allocate_many(self, n: int) -> List[int]:
        """``n`` fresh handles under ONE lock acquisition — a grouped
        submission's per-handle locking is a measurable share of the
        steady-state submit path."""
        global _HANDLE_WATERMARK
        with self._lock:
            first = self._last + 1
            self._last += n
            _HANDLE_WATERMARK = self._last
            handles = list(range(first, self._last + 1))
            for h in handles:
                self._results[h] = None
            return handles

    def poll(self, handle: int) -> bool:
        with self._lock:
            if handle not in self._results:
                raise ValueError(f"Invalid handle {handle}")
            return self._results[handle] is not None

    def mark_done(self, handle: int, status: Status,
                  output: Any = None) -> None:
        with self._cv:
            # Output BEFORE status: wait()'s lock-free fast path keys
            # on a non-None status, so the status store must publish
            # last or a racing synchronize() could release a handle
            # whose output was not yet visible.
            self._outputs[handle] = output
            self._results[handle] = status
            # A fused batch completes its handles in one burst while
            # the app waits on at most a few of them — the wake-up is
            # only worth paying when somebody is actually blocked.
            if self._waiters:
                self._cv.notify_all()

    _MISSING = object()

    def wait(self, handle: int, timeout: Optional[float] = None) -> Status:
        # Lock-free fast path: dict reads are atomic under the GIL and
        # mark_done stores the final Status in one assignment, so a
        # completed handle (the common case when draining a fused
        # batch: the first wait blocks, the rest are already done)
        # never pays the condition-variable lock.
        res = self._results.get(handle, self._MISSING)
        if res is self._MISSING:
            raise ValueError(f"Invalid handle {handle}")
        if res is not None:
            return res
        with self._cv:
            if self._results[handle] is not None:
                return self._results[handle]
            self._waiters += 1
            try:
                ok = self._cv.wait_for(
                    lambda: self._results[handle] is not None, timeout)
            finally:
                self._waiters -= 1
            if not ok:
                raise TimeoutError(
                    f"Timed out waiting for handle {handle}")
            return self._results[handle]

    def release(self, handle: int) -> Any:
        """Return the output and clear the handle
        (reference: handle_manager.cc ReleaseHandle/WaitAndClear).
        Lockless: dict pops are GIL-atomic and a handle is released by
        exactly one caller, after completion — no invariant spans the
        two pops."""
        out = self._outputs.pop(handle, None)
        self._results.pop(handle, None)
        return out
