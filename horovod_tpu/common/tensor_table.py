"""Tensor table, message queue and handle manager.

The tensor table holds the per-process payloads of in-flight collectives,
keyed by name, while the message queue carries the matching Requests to
the background loop (reference: horovod/common/global_state.h:48-57 and
common.h:165-184 ``TensorTableEntry``/``TensorTable``). Handles mirror
the torch binding's ``HandleManager`` (reference:
horovod/torch/handle_manager.h:31-42) and are used by every async API.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.common.message import Request
from horovod_tpu.common.status import Status


class TensorTableEntry:
    """One in-flight collective on this process
    (reference: common.h:165-182)."""

    __slots__ = ("tensor_name", "tensor", "output", "root_rank", "device",
                 "callback", "ready_fn", "request_type", "context")

    def __init__(self, tensor_name: str, tensor: Any,
                 root_rank: int = -1, device: int = -1,
                 callback: Optional[Callable[[Status], None]] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 request_type=None, context: Any = None):
        self.tensor_name = tensor_name
        self.tensor = tensor          # input payload (numpy or jax array)
        self.output = None            # set by the executing backend
        self.root_rank = root_rank
        self.device = device
        self.callback = callback
        self.ready_fn = ready_fn      # None => ready immediately
        self.request_type = request_type
        self.context = context        # adapter-specific opaque state


class TensorTable:
    """Name-keyed table of pending entries + the per-cycle message queue,
    guarded by one mutex like the reference's
    (reference: operations.cc:1455 mutex usage)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[str, TensorTableEntry] = {}
        self._message_queue: List[Request] = []

    def add(self, entry: TensorTableEntry, request: Request) -> bool:
        """Insert entry + request atomically. Returns False on duplicate
        name (reference: operations.cc:1459-1462 DUPLICATE_NAME_ERROR)."""
        with self._lock:
            if entry.tensor_name in self._table:
                return False
            self._table[entry.tensor_name] = entry
            self._message_queue.append(request)
            return True

    def add_all(self, pairs) -> Optional[str]:
        """Insert several (entry, request) pairs under ONE lock hold —
        all-or-nothing, and atomic w.r.t. pop_messages, so a concurrent
        cycle tick can never split the batch across two RequestLists
        (the grouped-allreduce atomicity contract). Returns the first
        duplicate name, or None on success."""
        with self._lock:
            for entry, _ in pairs:
                if entry.tensor_name in self._table:
                    return entry.tensor_name
            for entry, request in pairs:
                self._table[entry.tensor_name] = entry
                self._message_queue.append(request)
            return None

    def pop_messages(self) -> List[Request]:
        """Drain the message queue for this cycle
        (reference: operations.cc:1000-1012)."""
        with self._lock:
            msgs = self._message_queue
            self._message_queue = []
            return msgs

    def pop_entry(self, name: str) -> TensorTableEntry:
        with self._lock:
            return self._table.pop(name)

    def pop_entry_if_present(self, name: str):
        with self._lock:
            self._message_queue = [m for m in self._message_queue
                                   if m.tensor_name != name]
            return self._table.pop(name, None)

    def get_entry(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._table.get(name)

    def pop_all(self) -> List[TensorTableEntry]:
        """Remove and return every pending entry (shutdown fan-out,
        reference: operations.cc:898-913)."""
        with self._lock:
            entries = list(self._table.values())
            self._table.clear()
            self._message_queue = []
            return entries

    def __len__(self):
        with self._lock:
            return len(self._table)


class HandleManager:
    """Integer handles for async ops; poll/wait on completion status
    (reference: horovod/torch/handle_manager.{h,cc})."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._last = 0
        self._results: Dict[int, Optional[Status]] = {}
        self._outputs: Dict[int, Any] = {}

    def allocate(self) -> int:
        with self._lock:
            self._last += 1
            handle = self._last
            self._results[handle] = None
            return handle

    def poll(self, handle: int) -> bool:
        with self._lock:
            if handle not in self._results:
                raise ValueError(f"Invalid handle {handle}")
            return self._results[handle] is not None

    def mark_done(self, handle: int, status: Status,
                  output: Any = None) -> None:
        with self._cv:
            self._results[handle] = status
            self._outputs[handle] = output
            self._cv.notify_all()

    def wait(self, handle: int, timeout: Optional[float] = None) -> Status:
        with self._cv:
            if handle not in self._results:
                raise ValueError(f"Invalid handle {handle}")
            ok = self._cv.wait_for(
                lambda: self._results[handle] is not None, timeout)
            if not ok:
                raise TimeoutError(
                    f"Timed out waiting for handle {handle}")
            return self._results[handle]

    def release(self, handle: int) -> Any:
        """Return the output and clear the handle
        (reference: handle_manager.cc ReleaseHandle/WaitAndClear)."""
        with self._lock:
            out = self._outputs.pop(handle, None)
            self._results.pop(handle, None)
            return out
