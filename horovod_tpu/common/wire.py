"""Binary wire format for the coordinator control plane.

Role-equivalent of the reference's FlatBuffers schema
(reference: horovod/common/wire/message.fbs, message.cc:122-215,317-346).
We define a compact little-endian layout instead of FlatBuffers.

Why this codec is pure Python (measured decision, re-validated after
the struct-batching rewrite): the request path packs/parses each
Request's fixed fields with one precompiled Struct per segment and
fills slots directly, putting a 64-rank coordinator cycle at ~1 ms
(~15-30 us/rank across runs, see benchmarks/RESULTS_cpu.json
projected_scaling.coordinator_cpu) — an order of magnitude under the
64-chip control budget. A C++ codec behind ctypes cannot beat that without also
moving the whole negotiation loop in-core (materializing Python
Request/Response objects from C structs costs more than parsing the
bytes in Python), so the earlier native parity codec was deleted
rather than wired in.

Layout (all little-endian):
  varless fixed ints; strings are u32 length + UTF-8 bytes;
  vectors are u32 count + elements.

  Request      := u8 request_type | i32 request_rank | u8 tensor_type
                | u8 wire_dtype | i32 root_rank | i32 device
                | str tensor_name
                | f64 prescale | f64 postscale | u8 ndim | i64 dims[ndim]
  RequestList  := u8 shutdown | u32 n | Request[n]
  Response     := u8 response_type | u8 wire_dtype | u8 algorithm
                | str error_message
                | f64 prescale | f64 postscale
                | u32 nnames | str names[nnames]
                | u32 ndev | i32 devices[ndev]
                | u32 nsz  | i64 tensor_sizes[nsz]
  ResponseList := u8 shutdown | f64 tuned_cycle_time_ms
                | i64 tuned_fusion_threshold_bytes
                | i64 tuned_overlap_buckets | u32 n | Response[n]
"""

from __future__ import annotations

import struct

from horovod_tpu.common.message import (
    CacheCycleRequest, CacheCycleResponse, DataType, Request, RequestList,
    RequestType, Response, ResponseList, ResponseType,
)

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

# Combined-field structs for the hot request path: the coordinator
# parses world_size RequestLists per cycle, and per-field unpacks +
# enum __call__ dominate that cost (measured 86% of a synthetic
# 64-rank cycle). Same wire layout, one unpack per segment.
# type|rank|dtype|wire_dtype|root|device|namelen — wire_dtype is the
# rank's proposed on-the-wire compression (WIRE_* codes,
# common/wire_dtype.py), negotiated by the coordinator like the
# fusion threshold.
_REQ_HEAD = struct.Struct("<BiBBiiI")
_REQ_TAIL = struct.Struct("<ddB")     # prescale|postscale|ndim
_REQ_TYPE_OF = RequestType._value2member_map_
_DTYPE_OF = DataType._value2member_map_
_RESP_TYPE_OF = ResponseType._value2member_map_


class _Writer:
    def __init__(self):
        # hvdlint: owned-by=main -- codec objects are function-local: built, filled and drained inside one call frame, never shared
        self.parts = []

    def u8(self, v): self.parts.append(_U8.pack(v))
    def u32(self, v): self.parts.append(_U32.pack(v))
    def i32(self, v): self.parts.append(_I32.pack(v))
    def i64(self, v): self.parts.append(_I64.pack(v))
    def f64(self, v): self.parts.append(_F64.pack(v))

    def string(self, s: str):
        b = s.encode("utf-8")
        self.u32(len(b))
        self.parts.append(b)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, data: bytes, offset: int = 0):
        self.data = data
        # hvdlint: owned-by=main -- codec objects are function-local: built, consumed and dropped inside one call frame, never shared
        self.off = offset

    def _need(self, n: int) -> None:
        """Length guard ahead of every fixed-width read: a truncated
        frame must surface as a transport error (ConnectionError) the
        abort machinery understands, never as struct.error/IndexError
        deep inside a parse — and a short mask/segment slice must
        never silently decode a WRONG value (hvdlint: wire-protocol)."""
        if self.off + n > len(self.data):
            raise ConnectionError(
                f"truncated control frame: need {n} bytes at offset "
                f"{self.off}, have {len(self.data) - self.off}")

    def u8(self):
        self._need(1)
        v = _U8.unpack_from(self.data, self.off)[0]
        self.off += 1
        return v

    def u32(self):
        self._need(4)
        v = _U32.unpack_from(self.data, self.off)[0]
        self.off += 4
        return v

    def i32(self):
        self._need(4)
        v = _I32.unpack_from(self.data, self.off)[0]
        self.off += 4
        return v

    def i64(self):
        self._need(8)
        v = _I64.unpack_from(self.data, self.off)[0]
        self.off += 8
        return v

    def f64(self):
        self._need(8)
        v = _F64.unpack_from(self.data, self.off)[0]
        self.off += 8
        return v

    def string(self) -> str:
        n = self.u32()
        self._need(n)
        s = self.data[self.off:self.off + n].decode("utf-8")
        self.off += n
        return s


def _write_request(w: _Writer, req: Request) -> None:
    name = req.tensor_name.encode("utf-8")
    shape = req.tensor_shape
    w.parts.append(_REQ_HEAD.pack(
        int(req.request_type), req.request_rank, int(req.tensor_type),
        req.wire_dtype, req.root_rank, req.device, len(name)))
    w.parts.append(name)
    w.parts.append(_REQ_TAIL.pack(
        req.prescale_factor, req.postscale_factor, len(shape)))
    if shape:
        w.parts.append(struct.pack(f"<{len(shape)}q", *shape))


def _read_request(r: _Reader) -> Request:
    data, off = r.data, r.off
    r._need(_REQ_HEAD.size)
    (req_type, request_rank, tensor_type, wire_dtype, root_rank,
     device, namelen) = _REQ_HEAD.unpack_from(data, off)
    off += _REQ_HEAD.size
    if off + namelen + _REQ_TAIL.size > len(data):
        raise ConnectionError(
            f"truncated request frame at offset {off}")
    name = data[off:off + namelen].decode("utf-8")
    off += namelen
    prescale, postscale, ndim = _REQ_TAIL.unpack_from(data, off)
    off += _REQ_TAIL.size
    if ndim:
        if off + 8 * ndim > len(data):
            raise ConnectionError(
                f"truncated request frame at offset {off}")
        shape = struct.unpack_from(f"<{ndim}q", data, off)
        off += 8 * ndim
    else:
        shape = ()
    r.off = off
    # Direct slot assignment: the wire reader already holds real enum
    # members and an int tuple, so Request.__init__'s defensive
    # coercions (enum calls, per-dim int()) are pure overhead on the
    # coordinator's hottest loop.
    req = Request.__new__(Request)
    req.request_rank = request_rank
    req.request_type = _REQ_TYPE_OF[req_type]
    req.tensor_type = _DTYPE_OF[tensor_type]
    req.tensor_name = name
    req.root_rank = root_rank
    req.device = device
    req.tensor_shape = shape
    req.prescale_factor = prescale
    req.postscale_factor = postscale
    req.wire_dtype = wire_dtype
    return req


def serialize_request_list(rl: RequestList) -> bytes:
    w = _Writer()
    w.u8(1 if rl.shutdown else 0)
    w.u32(len(rl.requests))
    for req in rl.requests:
        _write_request(w, req)
    return w.bytes()


def parse_request_list(data: bytes) -> RequestList:
    r = _Reader(data)
    shutdown = bool(r.u8())
    n = r.u32()
    return RequestList([_read_request(r) for _ in range(n)], shutdown)


def _write_response(w: _Writer, resp: Response) -> None:
    w.u8(int(resp.response_type))
    # The coordinator's world-coherent data-plane verdicts: resolved
    # wire dtype + stamped algorithm (WIRE_*/ALG_*, wire_dtype.py).
    w.u8(resp.wire_dtype)
    w.u8(resp.algorithm)
    w.string(resp.error_message)
    w.f64(resp.prescale_factor)
    w.f64(resp.postscale_factor)
    w.u32(len(resp.tensor_names))
    for name in resp.tensor_names:
        w.string(name)
    # vectors as one pack each: every rank parses the broadcast
    # ResponseList each cycle, and devices/tensor_sizes grow with
    # world size (devices) and fused batch width (sizes)
    devices = resp.devices
    w.u32(len(devices))
    if devices:
        w.parts.append(struct.pack(f"<{len(devices)}i", *devices))
    sizes = resp.tensor_sizes
    w.u32(len(sizes))
    if sizes:
        w.parts.append(struct.pack(f"<{len(sizes)}q", *sizes))


def _read_response(r: _Reader) -> Response:
    resp_type = _RESP_TYPE_OF[r.u8()]
    wire_dtype = r.u8()
    algorithm = r.u8()
    err = r.string()
    prescale = r.f64()
    postscale = r.f64()
    names = [r.string() for _ in range(r.u32())]
    ndev = r.u32()
    if ndev:
        r._need(4 * ndev)
        devices = list(struct.unpack_from(f"<{ndev}i", r.data, r.off))
        r.off += 4 * ndev
    else:
        devices = []
    nsz = r.u32()
    if nsz:
        r._need(8 * nsz)
        sizes = list(struct.unpack_from(f"<{nsz}q", r.data, r.off))
        r.off += 8 * nsz
    else:
        sizes = []
    return Response(response_type=resp_type, tensor_names=names,
                    error_message=err, devices=devices, tensor_sizes=sizes,
                    prescale_factor=prescale, postscale_factor=postscale,
                    wire_dtype=wire_dtype, algorithm=algorithm)


def serialize_response_list(rl: ResponseList) -> bytes:
    w = _Writer()
    w.u8(1 if rl.shutdown else 0)
    w.f64(rl.tuned_cycle_time_ms)
    w.i64(rl.tuned_fusion_threshold_bytes)
    w.i64(rl.tuned_overlap_buckets)
    w.u32(len(rl.responses))
    for resp in rl.responses:
        _write_response(w, resp)
    return w.bytes()


def parse_response_list(data: bytes,
                        offset: int = 0) -> ResponseList:
    r = _Reader(data, offset)
    shutdown = bool(r.u8())
    tuned_cycle = r.f64()
    tuned_fusion = r.i64()
    tuned_overlap = r.i64()
    n = r.u32()
    return ResponseList([_read_response(r) for _ in range(n)], shutdown,
                        tuned_cycle_time_ms=tuned_cycle,
                        tuned_fusion_threshold_bytes=tuned_fusion,
                        tuned_overlap_buckets=tuned_overlap)


# ---------------------------------------------------------------------------
# Cycle frames — the per-cycle control payloads the runtime actually
# moves. A one-byte kind prefix selects the legacy full encoding
# (response cache disabled) or the cache-coherence framing:
#
#   CycleRequest  := u8 kind
#     kind 0 FULL        : RequestList
#     kind 1 CACHED      : u8 shutdown | u64 epoch | u32 nslots
#                        | hit_mask[ceil(nslots/8)] | invalid_mask[...]
#                        | u32 n | Request[n] (uncached remainder)
#     kind 2 CACHED_AGG  : same layout as CACHED — an aggregate a local
#                          root AND/OR-folded from its whole host, so
#                          the coordinator sees ONE mask per host
#                          instead of one frame per rank
#     kind 3 CACHED_SPEC : u64 epoch | u32 nslots | hit_mask[...]
#                        | segments — the fused speculative cycle: a
#                          steady-state rank's pure-hit bitmask WITH
#                          its pre-packed fused allreduce buffers
#                          attached, so the grant round-trip and the
#                          data-plane round-trip collapse into ONE
#                          world synchronization
#   CycleResponse := u8 kind
#     kind 0 FULL        : ResponseList
#     kind 1 CACHED      : u64 epoch | u32 nslots
#                        | grant_mask[...] | invalid_mask[...]
#                        | ResponseList (freshly negotiated remainder)
#     kind 3 CACHED_SPEC : u64 epoch | u32 nslots | grant_mask[...]
#                        | segments — the world-reduced fused buffers
#                          (grant == every rank's identical hit mask)
#
#   segments := u32 nseg | nseg x (u8 dtype | u64 nbytes | raw bytes)
#
# Masks are little-endian fixed-width bit vectors, one bit per response
# cache slot — a (non-speculative) steady-state cycle moves
# O(capacity/8) bytes per rank; a speculative one additionally moves
# exactly the fused tensor data the data plane would have moved anyway.

FRAME_FULL = 0
FRAME_CACHED = 1
FRAME_CACHED_AGG = 2
FRAME_CACHED_SPEC = 3
CACHED_AGG_PREFIX = bytes((FRAME_CACHED_AGG,))
# Relay envelope (NOT a cycle frame kind): a hierarchical local root
# prefixes an UNFOLDED per-rank pack on the request tag with this
# byte so the coordinator can distinguish it from a folded CACHED_AGG
# frame without sniffing ambiguous bytes — a raw pack_frames blob
# leads with its u32 frame count, and a 2-rank host's count byte is
# exactly FRAME_CACHED_AGG.
PACKED_PREFIX = b"\xfe"
# World-id envelope (common/tenancy.py): every cycle frame of a
# TENANT sub-world rides as ``0xFD | u32 world_id | frame`` so a
# frame that strays across worlds (a derived-port collision, a stale
# connection in service mode) fails fast with BOTH ids named instead
# of corrupting a foreign tensor table. world_id 0 is the default
# world; its frames ride unstamped, keeping the single-job wire
# byte-identical to every earlier build.
TENANT_PREFIX = b"\xfd"


def stamp_world(frame: bytes, world_id: int) -> bytes:
    """Wrap a cycle frame in the world-id envelope (identity for the
    default world)."""
    if not world_id:
        return frame
    return TENANT_PREFIX + _U32.pack(world_id) + frame


def read_world(data: bytes) -> tuple:
    """-> (world_id, payload_offset): (0, 0) for an unstamped frame."""
    if data[:1] != TENANT_PREFIX:
        return 0, 0
    if len(data) < 5:
        raise ConnectionError(
            f"truncated world-id envelope: {len(data)} bytes")
    return _U32.unpack_from(data, 1)[0], 5


def unstamp_world(data: bytes, expect_id: int) -> bytes:
    """Strip (and verify) the world-id envelope. A mismatch is a
    cross-world frame — the caller's world must fail fast, never
    decode a foreign table's masks."""
    world_id, off = read_world(data)
    if world_id != expect_id:
        raise ConnectionError(
            f"control frame for world {world_id:#010x} arrived in "
            f"world {expect_id:#010x} — two worlds are sharing a "
            f"connection (check sub-world coordinator ports)")
    return data[off:] if off else data


def _mask_nbytes(nslots: int) -> int:
    return (nslots + 7) // 8


def _write_mask(w: _Writer, mask: int, nslots: int) -> None:
    w.parts.append(mask.to_bytes(_mask_nbytes(nslots), "little"))


def _read_mask(r: _Reader, nslots: int) -> int:
    n = _mask_nbytes(nslots)
    # guard BEFORE the slice: int.from_bytes over a short slice would
    # silently decode a WRONG (truncated) mask — worse than a crash on
    # a world whose grants are driven by these bits
    r._need(n)
    mask = int.from_bytes(r.data[r.off:r.off + n], "little")
    r.off += n
    return mask


def _seg_hdr(dt, nbytes: int) -> bytes:
    """The constant 9-byte header in front of one raw segment."""
    return _U8.pack(int(dt)) + _I64.pack(nbytes)


def spec_frame_parts(epoch: int, nslots: int, mask: int, seg_meta,
                     world_id: int = 0):
    """(prefix, [seg_hdr, ...]): the CONSTANT byte regions of a
    CACHED_SPEC cycle frame — everything except the raw segment data.
    ``seg_meta`` is [(DataType, nbytes), ...]. This is THE single
    source of the speculative layout: serialize_cycle_request/response
    build their spec frames from these parts, and the native steady
    cycle (native/hvdtpu.cc hvd_steady_worker/coord) sends and
    byte-compares exactly these regions around fusion-arena pointers —
    so a native rank and a pure-Python rank can never drift apart on
    the wire. Request and response share one shape because a granted
    steady cycle's grant_mask IS the bid's hit_mask. A tenant world
    (``world_id`` != 0) leads the prefix with the world-id envelope,
    exactly as stamp_world wraps the classically-serialized frame."""
    w = _Writer()
    if world_id:
        w.parts.append(TENANT_PREFIX)
        w.u32(world_id)
    w.u8(FRAME_CACHED_SPEC)
    w.i64(epoch)
    w.u32(nslots)
    _write_mask(w, mask, nslots)
    w.u32(len(seg_meta))
    return w.bytes(), [_seg_hdr(dt, nbytes) for dt, nbytes in seg_meta]


def _write_segments(w: _Writer, segments) -> None:
    """[(DataType, buffer), ...] — buffers are any contiguous
    bytes-like (numpy arrays ride as zero-copy byte views; extension
    dtypes such as bfloat16 are handled by as_byte_view)."""
    from horovod_tpu.common.network import as_byte_view
    w.u32(len(segments))
    for dt, buf in segments:
        view = as_byte_view(buf)
        n = len(view) if isinstance(view, (bytes, bytearray)) \
            else view.nbytes
        w.parts.append(_seg_hdr(dt, n))
        w.parts.append(view)


def _read_segments(r: _Reader):
    """Zero-copy: segment buffers are memoryviews over the frame."""
    view = memoryview(r.data)
    segs = []
    for _ in range(r.u32()):
        dt = DataType(r.u8())
        n = r.i64()
        if n < 0:
            raise ConnectionError(
                f"corrupt segment length {n} in control frame")
        r._need(n)
        segs.append((dt, view[r.off:r.off + n]))
        r.off += n
    return segs


def serialize_cycle_request(obj, aggregate: bool = False) -> bytes:
    w = _Writer()
    if isinstance(obj, RequestList):
        w.u8(FRAME_FULL)
        w.u8(1 if obj.shutdown else 0)
        w.u32(len(obj.requests))
        for req in obj.requests:
            _write_request(w, req)
        return w.bytes()
    assert isinstance(obj, CacheCycleRequest)
    if obj.spec_payload is not None:
        w.u8(FRAME_CACHED_SPEC)
        w.i64(obj.epoch)
        w.u32(obj.nslots)
        _write_mask(w, obj.hit_mask, obj.nslots)
        _write_segments(w, obj.spec_payload)
        return w.bytes()
    w.u8(FRAME_CACHED_AGG if aggregate else FRAME_CACHED)
    w.u8(1 if obj.shutdown else 0)
    w.i64(obj.epoch)
    w.u32(obj.nslots)
    _write_mask(w, obj.hit_mask, obj.nslots)
    _write_mask(w, obj.invalid_mask, obj.nslots)
    w.u32(len(obj.requests))
    for req in obj.requests:
        _write_request(w, req)
    return w.bytes()


def parse_cycle_request(data: bytes):
    """-> RequestList (kind FULL) or CacheCycleRequest (CACHED[_AGG])."""
    r = _Reader(data)
    kind = r.u8()
    if kind == FRAME_FULL:
        shutdown = bool(r.u8())
        n = r.u32()
        return RequestList([_read_request(r) for _ in range(n)],
                           shutdown)
    if kind == FRAME_CACHED_SPEC:
        epoch = r.i64()
        nslots = r.u32()
        hit = _read_mask(r, nslots)
        return CacheCycleRequest(epoch=epoch, nslots=nslots,
                                 hit_mask=hit,
                                 spec_payload=_read_segments(r))
    if kind not in (FRAME_CACHED, FRAME_CACHED_AGG):
        raise ConnectionError(f"unknown cycle-request kind {kind}")
    shutdown = bool(r.u8())
    epoch = r.i64()
    nslots = r.u32()
    hit = _read_mask(r, nslots)
    invalid = _read_mask(r, nslots)
    n = r.u32()
    reqs = [_read_request(r) for _ in range(n)]
    return CacheCycleRequest(epoch=epoch, nslots=nslots, hit_mask=hit,
                             invalid_mask=invalid, requests=reqs,
                             shutdown=shutdown)


def serialize_cycle_response(obj) -> bytes:
    if isinstance(obj, ResponseList):
        return bytes((FRAME_FULL,)) + serialize_response_list(obj)
    assert isinstance(obj, CacheCycleResponse)
    w = _Writer()
    if obj.spec_payload is not None:
        w.u8(FRAME_CACHED_SPEC)
        w.i64(obj.epoch)
        w.u32(obj.nslots)
        _write_mask(w, obj.grant_mask, obj.nslots)
        _write_segments(w, obj.spec_payload)
        return w.bytes()
    w.u8(FRAME_CACHED)
    w.i64(obj.epoch)
    w.u32(obj.nslots)
    _write_mask(w, obj.grant_mask, obj.nslots)
    _write_mask(w, obj.invalid_mask, obj.nslots)
    rl = obj.response_list
    w.u8(1 if rl.shutdown else 0)
    w.f64(rl.tuned_cycle_time_ms)
    w.i64(rl.tuned_fusion_threshold_bytes)
    w.i64(rl.tuned_overlap_buckets)
    w.u32(len(rl.responses))
    for resp in rl.responses:
        _write_response(w, resp)
    return w.bytes()


def parse_cycle_response(data: bytes):
    """-> ResponseList (kind FULL) or CacheCycleResponse (CACHED)."""
    r = _Reader(data)
    kind = r.u8()
    if kind == FRAME_FULL:
        # offset, not data[1:]: slicing would copy the whole broadcast
        # payload every cycle on cache-disabled worlds
        return parse_response_list(data, offset=1)
    if kind == FRAME_CACHED_SPEC:
        epoch = r.i64()
        nslots = r.u32()
        grant = _read_mask(r, nslots)
        return CacheCycleResponse(epoch=epoch, nslots=nslots,
                                  grant_mask=grant,
                                  spec_payload=_read_segments(r))
    if kind != FRAME_CACHED:
        raise ConnectionError(f"unknown cycle-response kind {kind}")
    epoch = r.i64()
    nslots = r.u32()
    grant = _read_mask(r, nslots)
    invalid = _read_mask(r, nslots)
    shutdown = bool(r.u8())
    tuned_cycle = r.f64()
    tuned_fusion = r.i64()
    tuned_overlap = r.i64()
    n = r.u32()
    rl = ResponseList([_read_response(r) for _ in range(n)], shutdown,
                      tuned_cycle_time_ms=tuned_cycle,
                      tuned_fusion_threshold_bytes=tuned_fusion,
                      tuned_overlap_buckets=tuned_overlap)
    return CacheCycleResponse(epoch=epoch, nslots=nslots,
                              grant_mask=grant, invalid_mask=invalid,
                              response_list=rl)


# ---------------------------------------------------------------------------
# METRICS frames — the periodic observability payload that rides the
# control tree out-of-band (TAG_METRICS), the way PING frames do: each
# rank encodes its registry snapshot on HOROVOD_TPU_METRICS_INTERVAL, a
# hierarchical local root sums its host's latest frames into ONE frame
# upward, and rank 0 folds the owners into the world view
# (common/metrics.py WorldAggregator).
#
#   MetricsFrame := u8 version | u32 nranks | u32 nmetrics | Metric[n]
#   Metric       := u8 kind | str name | payload
#     kind 'c' COUNTER   : f64 value
#     kind 'g' GAUGE     : u8 agg ('s' sum | 'm' max) | f64 value
#     kind 'h' HISTOGRAM : u16 nbounds | f64 bounds[nbounds]
#                        | u64 counts[nbounds+1] | f64 sum | u64 count
#
# Bounds travel with every histogram so a frame is self-describing:
# the aggregator can verify bucket identity instead of assuming it.

_METRICS_VERSION = 1
_KIND_BYTE = {"c": 0, "g": 1, "h": 2}
_BYTE_KIND = {v: k for k, v in _KIND_BYTE.items()}
_AGG_BYTE = {"sum": 0, "max": 1}
_BYTE_AGG = {v: k for k, v in _AGG_BYTE.items()}
_U16 = struct.Struct("<H")
_U64 = struct.Struct("<Q")


def serialize_metrics_frame(nranks: int, snap: dict) -> bytes:
    """Encode a (possibly pre-summed) snapshot; ``nranks`` is how many
    ranks the frame represents (1 for a leaf, local_size for a folded
    host frame) so rank 0 can report hvd_ranks_reporting."""
    w = _Writer()
    w.u8(_METRICS_VERSION)
    w.u32(nranks)
    w.u32(len(snap))
    for name, rec in snap.items():
        w.u8(_KIND_BYTE[rec["k"]])
        w.string(name)
        if rec["k"] == "c":
            w.f64(rec["v"])
        elif rec["k"] == "g":
            w.u8(_AGG_BYTE[rec.get("agg", "sum")])
            w.f64(rec["v"])
        else:
            bounds = rec["bounds"]
            w.parts.append(_U16.pack(len(bounds)))
            if bounds:
                w.parts.append(
                    struct.pack(f"<{len(bounds)}d", *bounds))
            counts = rec["counts"]
            w.parts.append(
                struct.pack(f"<{len(counts)}Q", *counts))
            w.f64(rec["sum"])
            w.parts.append(_U64.pack(rec["count"]))
    return w.bytes()


def parse_metrics_frame(data: bytes):
    """-> (nranks, snapshot dict). Raises on a malformed or
    unknown-version frame; callers on the control plane treat that as
    a droppable best-effort payload, not a world error."""
    r = _Reader(data)
    version = r.u8()
    if version != _METRICS_VERSION:
        raise ValueError(f"unknown metrics frame version {version}")
    nranks = r.u32()
    snap = {}
    for _ in range(r.u32()):
        kind = _BYTE_KIND[r.u8()]
        name = r.string()
        if kind == "c":
            snap[name] = {"k": "c", "v": r.f64()}
        elif kind == "g":
            agg = _BYTE_AGG[r.u8()]
            snap[name] = {"k": "g", "agg": agg, "v": r.f64()}
        else:
            r._need(_U16.size)
            (nb,) = _U16.unpack_from(r.data, r.off)
            r.off += _U16.size
            r._need(8 * nb)
            bounds = list(struct.unpack_from(f"<{nb}d", r.data, r.off))
            r.off += 8 * nb
            r._need(8 * (nb + 1))
            counts = list(struct.unpack_from(f"<{nb + 1}Q", r.data,
                                             r.off))
            r.off += 8 * (nb + 1)
            total = r.f64()
            r._need(_U64.size)
            (count,) = _U64.unpack_from(r.data, r.off)
            r.off += _U64.size
            snap[name] = {"k": "h", "bounds": bounds, "counts": counts,
                          "sum": total, "count": count}
    return nranks, snap


def combine_metrics_frames(frames, drop_incompatible: bool = False
                           ) -> bytes:
    """Sum several METRICS frames into one (a local root folding its
    host before forwarding upward — the metrics analog of
    combine_cycle_requests). nranks adds; metric records merge with
    the registry's world semantics. ``drop_incompatible`` skips a
    garbled or identity-mismatched frame (one leaf on skewed code)
    instead of raising — the rest of the host must keep reporting;
    each frame folds into a scratch copy first so a half-merged bad
    frame can never leak partial sums."""
    from horovod_tpu.common.metrics import merge_into
    total_ranks = 0
    merged: dict = {}
    for f in frames:
        try:
            nranks, snap = parse_metrics_frame(f)
            trial = merge_into(merge_into({}, merged), snap)
        except Exception:
            if drop_incompatible:
                continue
            raise
        merged = trial
        total_ranks += nranks
    return serialize_metrics_frame(total_ranks, merged)


def combine_cycle_requests(frames) -> "bytes | None":
    """AND/OR-fold several ranks' cycle-request frames into one
    CACHED_AGG frame — the bitmask reduction a hierarchical local root
    applies before forwarding its host upward (hit masks AND, invalid
    masks and the shutdown flag OR, uncached Requests concatenated;
    every Request carries its rank, so attribution survives the fold).
    Returns None when any frame is not cache-framed or the epochs /
    slot counts disagree (divergence is the coordinator's to
    diagnose — the relay then forwards the frames unfolded). Tenant
    frames fold too: a host whose ranks all stamped the SAME world id
    folds behind one (re-stamped) aggregate; mixed ids mean two
    worlds' frames met on one relay — forwarded unfolded so the
    coordinator's unstamp check names the stray."""
    world_id = None
    parsed = []
    for f in frames:
        if not f:
            return None
        wid, off = read_world(f)
        if world_id is None:
            world_id = wid
        elif wid != world_id:
            return None
        if len(f) <= off or f[off] not in (FRAME_CACHED,
                                           FRAME_CACHED_AGG):
            return None
        parsed.append(parse_cycle_request(f[off:] if off else f))
    first = parsed[0]
    combined = CacheCycleRequest(
        epoch=first.epoch, nslots=first.nslots,
        hit_mask=first.hit_mask, invalid_mask=first.invalid_mask,
        requests=list(first.requests), shutdown=first.shutdown)
    for cf in parsed[1:]:
        if cf.epoch != first.epoch or cf.nslots != first.nslots:
            return None
        combined.hit_mask &= cf.hit_mask
        combined.invalid_mask |= cf.invalid_mask
        combined.shutdown = combined.shutdown or cf.shutdown
        combined.requests.extend(cf.requests)
    return stamp_world(serialize_cycle_request(combined,
                                               aggregate=True),
                       world_id)


# ---------------------------------------------------------------------------
# TRACE frames — the world trace plane's out-of-band payload
# (TAG_TRACE, common/trace.py): each rank ships bounded batches of
# completed spans upward the same way METRICS frames ride; a
# hierarchical local root CONCATENATES its host's sections into one
# frame (spans are one-shot deltas, not totals — unlike metrics they
# must never be latest-wins folded), and rank 0 merges every rank's
# track into ONE clock-aligned Chrome-trace file.
#
#   TraceFrame := u8 version | u32 nsections | Section[nsections]
#   Section    := i32 rank | u32 dropped
#               | u8 has_echo [| u64 ping_seq | f64 t_ping_recv
#                              | f64 t_send]
#               | u32 nspans | Span[nspans]
#   Span       := u8 kind | u64 cycle | f64 ts | f64 dur | str name
#
# The echo is the worker half of the NTP-style clock exchange
# (common/trace.py ClockSync): ``ping_seq`` names the coordinator
# PING being answered, ``t_ping_recv``/``t_send`` are this rank's
# monotonic clock at ping receipt and frame build. ``cycle`` is the
# world-identical negotiation-round sequence number, so spans
# correlate across ranks even before clock alignment converges.

_TRACE_VERSION = 1

# Span kinds (u8 on the wire; one family, pairwise distinct —
# enforced by the hvdlint wire-protocol analyzer like WIRE_*/ALG_*).
SPAN_SLICE = 0   # complete span: Chrome "X" (ts + dur)
SPAN_MARK = 1    # instant event: Chrome "i" (dur ignored)

SPAN_NAMES = {SPAN_SLICE: "slice", SPAN_MARK: "mark"}

# Flight-recorder event codes (u8 in the ring and the postmortem
# JSONL header — common/trace.py FlightRecorder). Same distinctness
# contract as SPAN_*.
EV_CYCLE = 0      # one world negotiation round completed
EV_ABORT = 1      # world abort observed/raised on this rank
EV_ELASTIC = 2    # elastic lifecycle event (recovery/resize/rejoin)
EV_STALL = 3      # stall-inspector warning/shutdown
EV_FAULT = 4      # injected fault fired (common/faults.py)
EV_TEARDOWN = 5   # runtime teardown entered
EV_MARK = 6       # free-form marker (tests, user code)
EV_SELFOP = 7     # supervision-policy verdict (common/selfop.py)

EV_NAMES = {EV_CYCLE: "cycle", EV_ABORT: "abort",
            EV_ELASTIC: "elastic", EV_STALL: "stall",
            EV_FAULT: "fault", EV_TEARDOWN: "teardown",
            EV_MARK: "mark", EV_SELFOP: "selfop"}


def serialize_trace_frame(sections) -> bytes:
    """``sections``: [{"rank", "dropped", "echo": None|(seq, t_recv,
    t_send), "spans": [(kind, cycle, ts, dur, name), ...]}, ...]."""
    w = _Writer()
    w.u8(_TRACE_VERSION)
    w.u32(len(sections))
    for sec in sections:
        w.i32(sec["rank"])
        w.u32(sec.get("dropped", 0))
        echo = sec.get("echo")
        if echo is None:
            w.u8(0)
        else:
            seq, t_recv, t_send = echo
            w.u8(1)
            w.parts.append(_U64.pack(seq))
            w.f64(t_recv)
            w.f64(t_send)
        spans = sec.get("spans", ())
        w.u32(len(spans))
        for kind, cycle, ts, dur, name in spans:
            w.u8(kind)
            w.parts.append(_U64.pack(cycle))
            w.f64(ts)
            w.f64(dur)
            w.string(name)
    return w.bytes()


def parse_trace_frame(data: bytes):
    """-> [section dict, ...] (layout above). Raises on a malformed
    or unknown-version frame; control-plane callers treat that as a
    droppable best-effort payload, like METRICS frames."""
    r = _Reader(data)
    version = r.u8()
    if version != _TRACE_VERSION:
        raise ValueError(f"unknown trace frame version {version}")
    sections = []
    for _ in range(r.u32()):
        rank = r.i32()
        dropped = r.u32()
        echo = None
        if r.u8():
            r._need(_U64.size)
            (seq,) = _U64.unpack_from(r.data, r.off)
            r.off += _U64.size
            echo = (seq, r.f64(), r.f64())
        spans = []
        for _s in range(r.u32()):
            kind = r.u8()
            r._need(_U64.size)
            (cycle,) = _U64.unpack_from(r.data, r.off)
            r.off += _U64.size
            spans.append((kind, cycle, r.f64(), r.f64(), r.string()))
        sections.append({"rank": rank, "dropped": dropped,
                         "echo": echo, "spans": spans})
    return sections


def combine_trace_frames(frames) -> bytes:
    """Concatenate several TRACE frames' sections into one (a local
    root folding its host before forwarding upward). Unlike
    combine_metrics_frames this NEVER merges two sections: spans are
    one-shot deltas, so every section must survive verbatim with its
    rank attribution. A garbled frame is dropped — one leaf on skewed
    code must not silence its healthy siblings."""
    sections = []
    for f in frames:
        try:
            sections.extend(parse_trace_frame(f))
        except Exception:
            continue
    return serialize_trace_frame(sections)


# -- elastic rendezvous frames (common/elastic.py) ---------------------------
#
# These ride short-lived dedicated sockets (never the controller
# channels), framed by network.Channel like everything else:
#
#   manifest := u8 kind | i64 generation | i32 old_rank
#             | string host | i32 elastic_port
#   verdict  := u8 verdict | i64 generation | i32 new_rank | i32 size
#             | string controller_addr | i32 controller_port
#             | string cause | u32 n_lost x string | i32 joined
#             | i32 coord_elastic_port | i32 demote_rank | u32 pace_us
#
# ``demote_rank``/``pace_us`` carry the supervision policy's topology
# verdict (common/selfop.py): the NEW rank the habitual straggler was
# reassigned to (-1 when no demotion rode this resize) and the
# per-cycle pacing budget the non-demoted members apply so arrivals
# cluster instead of fanning out behind the straggler.

def serialize_elastic_manifest(kind: int, generation: int,
                               old_rank: int, host: str,
                               elastic_port: int) -> bytes:
    w = _Writer()
    w.u8(kind)
    w.i64(generation)
    w.i32(old_rank)
    w.string(host)
    w.i32(elastic_port)
    return w.bytes()


def parse_elastic_manifest(data: bytes) -> dict:
    r = _Reader(data)
    return {"kind": r.u8(), "gen": r.i64(), "old_rank": r.i32(),
            "host": r.string(), "elastic_port": r.i32()}


def serialize_elastic_verdict(verdict: int, generation: int,
                              new_rank: int, size: int, addr: str,
                              port: int, cause: str,
                              lost=None, joined: int = 0,
                              coord_elastic_port: int = 0,
                              demote_rank: int = -1,
                              pace_us: int = 0) -> bytes:
    w = _Writer()
    w.u8(verdict)
    w.i64(generation)
    w.i32(new_rank)
    w.i32(size)
    w.string(addr)
    w.i32(port)
    w.string(cause)
    lost = lost or []
    w.u32(len(lost))
    for entry in lost:
        w.string(entry)
    w.i32(joined)
    w.i32(coord_elastic_port)
    w.i32(demote_rank)
    w.u32(pace_us)
    return w.bytes()


def parse_elastic_verdict(data: bytes) -> dict:
    r = _Reader(data)
    out = {"verdict": r.u8(), "gen": r.i64(), "rank": r.i32(),
           "size": r.i32(), "addr": r.string(), "port": r.i32(),
           "cause": r.string()}
    out["lost"] = [r.string() for _ in range(r.u32())]
    out["joined"] = r.i32()
    out["coord_elastic_port"] = r.i32()
    out["demote_rank"] = r.i32()
    out["pace_us"] = r.u32()
    return out


# -- rejoin state-sync manifest (common/selfop.py) ---------------------------
#
# The fast State.sync() route descriptor, broadcast from rank 0
# through the ordinary collective plane before the side-channel data
# stream opens (so every member derives the identical transfer plan):
#
#   sync := u8 version | string host | i32 port | i64 generation
#         | u32 chunk_bytes | string compression
#         | u32 n_arrays x (string key | string dtype | u8 ndim
#                           | i64 dims[ndim])
#         | u32 n_scalars x (string key | u8 stype | string repr)
#         | u32 n_legacy x string key

_SELFOP_SYNC_VERSION = 1

# scalar type codes (u8 stype above)
_SYNC_SCALAR_TYPES = {bool: 0, int: 1, float: 2}
_SYNC_SCALAR_CTORS = {0: lambda s: s == "True", 1: int, 2: float}


def serialize_selfop_sync(host: str, port: int, generation: int,
                          chunk_bytes: int, compression: str,
                          arrays, scalars, legacy) -> bytes:
    """``arrays``: [(key, dtype_str, shape)], ``scalars``:
    [(key, stype_code, repr_str)], ``legacy``: [key, ...] — keys whose
    values ride the per-key broadcast fallback instead."""
    w = _Writer()
    w.u8(_SELFOP_SYNC_VERSION)
    w.string(host)
    w.i32(port)
    w.i64(generation)
    w.u32(chunk_bytes)
    w.string(compression)
    w.u32(len(arrays))
    for key, dtype, shape in arrays:
        w.string(key)
        w.string(dtype)
        w.u8(len(shape))
        for d in shape:
            w.i64(d)
    w.u32(len(scalars))
    for key, stype, rep in scalars:
        w.string(key)
        w.u8(stype)
        w.string(rep)
    w.u32(len(legacy))
    for key in legacy:
        w.string(key)
    return w.bytes()


def parse_selfop_sync(data: bytes) -> dict:
    r = _Reader(data)
    version = r.u8()
    if version != _SELFOP_SYNC_VERSION:
        raise ValueError(f"unknown selfop sync version {version}")
    out = {"host": r.string(), "port": r.i32(), "gen": r.i64(),
           "chunk": r.u32(), "compression": r.string()}
    arrays = []
    for _ in range(r.u32()):
        key = r.string()
        dtype = r.string()
        shape = tuple(r.i64() for _ in range(r.u8()))
        arrays.append((key, dtype, shape))
    out["arrays"] = arrays
    out["scalars"] = [(r.string(), r.u8(), r.string())
                      for _ in range(r.u32())]
    out["legacy"] = [r.string() for _ in range(r.u32())]
    return out


# -- tenant service frames (common/tenancy.py) -------------------------------
#
# The service gate's attach/detach/snapshot protocol — the PR 8
# manifest machinery generalized to jobs that join the WARM fleet's
# service plane instead of its world: frames ride short-lived
# dedicated sockets framed by network.Channel, exactly like the
# elastic rendezvous frames above. One u8 kind family (TENANT_*,
# pairwise distinct — enforced by the hvdlint wire-protocol analyzer
# like WIRE_*/ALG_*):
#
#   attach   := u8 kind | u32 world_id | i64 generation | str tenant
#             | i32 replica | i32 group | str host | i32 port
#   lease    := u8 kind | u32 world_id | i64 generation | i64 lease
#             | i32 size | u32 n x (str host | i32 port) | str cause
#   snapshot := u8 kind | u64 version
#             | u32 n x (str name | u8 dtype | u8 ndim | i64 dims[ndim]
#                        | u64 nbytes | raw bytes)
#   detach/ack/req reuse the attach/lease layouts with their own kind.

TENANT_ATTACH = 0        # job replica -> gate: join the service plane
TENANT_LEASE = 1         # gate -> replica: admitted; replica-group map
TENANT_SNAPSHOT_REQ = 2  # group root -> gate: parameter snapshot pull
TENANT_SNAPSHOT = 3      # gate -> root -> children: fanout payload
TENANT_DETACH = 4        # replica -> gate: leaving (fleet unaffected)
TENANT_ACK = 5           # gate -> replica: detach acknowledged
TENANT_REFUSE = 6        # gate -> dialer: not serving (wrong world /
                         # service mode off / unknown tenant group)

TENANT_NAMES = {TENANT_ATTACH: "attach", TENANT_LEASE: "lease",
                TENANT_SNAPSHOT_REQ: "snapshot_req",
                TENANT_SNAPSHOT: "snapshot", TENANT_DETACH: "detach",
                TENANT_ACK: "ack", TENANT_REFUSE: "refuse"}


def serialize_tenant_attach(kind: int, world_id: int, generation: int,
                            tenant: str, replica: int, group: int,
                            host: str, port: int) -> bytes:
    w = _Writer()
    w.u8(kind)
    w.u32(world_id)
    w.i64(generation)
    w.string(tenant)
    w.i32(replica)
    w.i32(group)
    w.string(host)
    w.i32(port)
    return w.bytes()


def parse_tenant_attach(data: bytes) -> dict:
    r = _Reader(data)
    return {"kind": r.u8(), "world_id": r.u32(), "gen": r.i64(),
            "tenant": r.string(), "replica": r.i32(),
            "group": r.i32(), "host": r.string(), "port": r.i32()}


def serialize_tenant_lease(kind: int, world_id: int, generation: int,
                           lease: int, size: int, members,
                           cause: str = "") -> bytes:
    """``members``: [(host, port), ...] in replica order — the fanout
    tree every replica derives its children from."""
    w = _Writer()
    w.u8(kind)
    w.u32(world_id)
    w.i64(generation)
    w.i64(lease)
    w.i32(size)
    w.u32(len(members))
    for host, port in members:
        w.string(host)
        w.i32(port)
    w.string(cause)
    return w.bytes()


def parse_tenant_lease(data: bytes) -> dict:
    r = _Reader(data)
    out = {"kind": r.u8(), "world_id": r.u32(), "gen": r.i64(),
           "lease": r.i64(), "size": r.i32()}
    out["members"] = [(r.string(), r.i32())
                      for _ in range(r.u32())]
    out["cause"] = r.string()
    return out


def serialize_tenant_snapshot(version: int, params) -> bytes:
    """``params``: {name: numpy array} — the published parameter
    snapshot a replica group pulls over the broadcast fanout."""
    from horovod_tpu.common.message import numpy_dtype_to_datatype
    from horovod_tpu.common.network import as_byte_view
    w = _Writer()
    w.u8(TENANT_SNAPSHOT)
    w.parts.append(_U64.pack(version))
    w.u32(len(params))
    for name, arr in params.items():
        w.string(name)
        w.u8(int(numpy_dtype_to_datatype(arr.dtype)))
        shape = arr.shape
        w.u8(len(shape))
        if shape:
            w.parts.append(struct.pack(f"<{len(shape)}q", *shape))
        view = as_byte_view(arr)
        n = len(view) if isinstance(view, (bytes, bytearray)) \
            else view.nbytes
        w.parts.append(_U64.pack(n))
        w.parts.append(view)
    return w.bytes()


def parse_tenant_snapshot(data: bytes) -> tuple:
    """-> (version, {name: numpy array}). Arrays are fresh copies —
    the frame buffer is transport-owned."""
    import numpy as _np
    from horovod_tpu.common.message import (
        DataType, datatype_to_numpy_dtype,
    )
    r = _Reader(data)
    kind = r.u8()
    if kind != TENANT_SNAPSHOT:
        raise ConnectionError(
            f"expected tenant snapshot frame, got kind {kind}")
    r._need(_U64.size)
    (version,) = _U64.unpack_from(r.data, r.off)
    r.off += _U64.size
    params = {}
    for _ in range(r.u32()):
        name = r.string()
        dt = DataType(r.u8())
        ndim = r.u8()
        if ndim:
            r._need(8 * ndim)
            shape = struct.unpack_from(f"<{ndim}q", r.data, r.off)
            r.off += 8 * ndim
        else:
            shape = ()
        r._need(_U64.size)
        (nbytes,) = _U64.unpack_from(r.data, r.off)
        r.off += _U64.size
        r._need(nbytes)
        arr = _np.frombuffer(
            bytes(r.data[r.off:r.off + nbytes]),
            dtype=datatype_to_numpy_dtype(dt)).reshape(shape).copy()
        r.off += nbytes
        params[name] = arr
    return version, params
