"""Flax adapter — TrainState helpers and training callbacks.

Role-equivalent of the reference's Keras facade layer
(reference: horovod/keras/__init__.py, horovod/_keras/__init__.py and
callbacks.py): state broadcast at start, metric averaging at epoch end,
and the linear-scaling + warmup learning-rate policy, restated for
flax/optax training loops.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu import ops as _ops
from horovod_tpu.ops import Average, Sum  # noqa: F401


def create_distributed_train_state(apply_fn, params, tx,
                                   op: int = Average, axis="data"):
    """flax TrainState whose ``tx`` averages gradients over the mesh
    axis inside jit (reference contract:
    _keras/__init__.py:20-70 create_distributed_optimizer)."""
    from flax.training import train_state
    from horovod_tpu.jax import DistributedOptimizer

    return train_state.TrainState.create(
        apply_fn=apply_fn, params=params,
        tx=DistributedOptimizer(tx, op=op, axis=axis))


def broadcast_train_state(state, root_rank: int = 0):
    """Broadcast every array leaf of a TrainState (params + opt state +
    step) from root via the background runtime — run once after restore
    (reference: _keras/callbacks.py:20-30
    BroadcastGlobalVariablesCallback)."""
    from horovod_tpu.jax import broadcast_parameters
    return broadcast_parameters(state, root_rank=root_rank)


def average_metrics(metrics: Dict[str, Any],
                    prefix: str = "metric") -> Dict[str, Any]:
    """Allreduce-average scalar metrics across workers at epoch end
    (reference: _keras/callbacks.py:33-67 MetricAverageCallback)."""
    out = {}
    for i, key in enumerate(sorted(metrics)):
        v = np.asarray(metrics[key], np.float64).reshape(())
        out[key] = float(_ops.allreduce(v, op=Average,
                                        name=f"{prefix}.{key}"))
    return out


def scaled_lr_schedule(base_lr: float, warmup_steps: int = 0,
                       world_size: Optional[int] = None,
                       staircase: bool = True):
    """The linear-scaling rule + gradual warmup as an optax schedule
    (reference: _keras/callbacks.py:70-168
    LearningRateWarmupCallback: ramp from base_lr to base_lr*size over
    warmup, the Goyal et al. recipe the reference implements)."""
    import optax
    n = world_size if world_size is not None else max(size(), 1)
    target = base_lr * n
    if warmup_steps <= 0:
        return optax.constant_schedule(target)
    return optax.linear_schedule(init_value=base_lr, end_value=target,
                                 transition_steps=warmup_steps)


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "Average", "Sum", "Compression",
    "create_distributed_train_state", "broadcast_train_state",
    "average_metrics", "scaled_lr_schedule",
]
