"""TensorFlow adapter (TF2 eager / tf.function-free host path).

Role-equivalent of the reference's TF binding + Python API
(reference: horovod/tensorflow/__init__.py:1-326,
horovod/tensorflow/mpi_ops.py). On a TPU host the compute path is JAX;
TF participates the way torch does — tensors staged through numpy into
the background runtime, with ``DistributedGradientTape`` and
``DistributedOptimizer`` providing the reference's gradient-averaging
contract for TF training loops. The TF1 graph-mode custom-op path
(AsyncOpKernel, reference: horovod/tensorflow/mpi_ops.cc:276-433) is
intentionally not reproduced: there is no TF runtime on TPU here, and
eager numpy staging covers the behavioral contract.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu import ops as _ops
from horovod_tpu.ops import Average, Sum, poll  # noqa: F401


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    return t.numpy() if hasattr(t, "numpy") else np.asarray(t)


def _to_tf(arr, like):
    import tensorflow as tf
    return tf.constant(np.ascontiguousarray(arr), dtype=like.dtype)


def allreduce(tensor, op: int = Average, name: Optional[str] = None,
              compression=Compression.none,
              sparse_as_dense: bool = False):
    """Sparse tensors (tf.IndexedSlices) take the allgather path like
    the reference (reference: horovod/tensorflow/__init__.py:46-92),
    unless ``sparse_as_dense`` densifies them first — a win for
    moderately sized embeddings where one dense psum beats gathering
    every rank's slices (reference: horovod/tensorflow/__init__.py:
    157,195-202 convert_to_tensor before allreduce)."""
    import tensorflow as tf
    if isinstance(tensor, tf.IndexedSlices):
        if sparse_as_dense:
            # scatter-add into the dense shape; duplicated indices sum,
            # matching the gather path's effective gradient.
            tensor = tf.convert_to_tensor(tensor)
        else:
            values = allgather(tensor.values, name=f"{name}.values"
                               if name else None)
            indices = allgather(tensor.indices, name=f"{name}.indices"
                                if name else None)
            if op == Average:
                values = values / size()
            return tf.IndexedSlices(values, indices,
                                    dense_shape=tensor.dense_shape)
    resolved = name if name is not None else _ops._auto_name("allreduce")

    def _host_allreduce(t, op_name):
        host = _to_numpy(t)
        comp, ctx = compression.compress(host)
        out = _ops.allreduce(comp, op=op, name=op_name)
        # `like` must always carry a dtype: the input may be a plain
        # Python scalar/list, which has none — the numpy view does.
        return _to_tf(
            np.asarray(compression.decompress(np.asarray(out), ctx),
                       dtype=host.dtype), host)

    if _differentiable(tensor):
        # Variables differentiate like tensors; convert so the
        # custom_gradient sees one input kind.
        tensor = tf.convert_to_tensor(tensor)
        # Differentiable under GradientTape (reference: the registered
        # gradient of HorovodAllreduce, tensorflow/mpi_ops.py — the
        # gradient of an allreduce is the allreduce of the gradient).
        # The grad op's name derives from the forward's: backward
        # execution order may differ across ranks, so the auto-name
        # counter must not pair the gradient collectives.
        @tf.custom_gradient
        def _op(x):
            y = _host_allreduce(x, resolved)

            def grad(dy):
                return allreduce(dy, op=op, name=f"{resolved}.grad",
                                 compression=compression)

            return y, grad

        return _op(tensor)
    return _host_allreduce(tensor, resolved)


def _differentiable(tensor):
    import tensorflow as tf
    return (tf.executing_eagerly()
            and (tf.is_tensor(tensor) or isinstance(tensor, tf.Variable))
            and tensor.dtype.is_floating)


def allgather(tensor, name: Optional[str] = None):
    import tensorflow as tf
    resolved = name if name is not None else _ops._auto_name("allgather")

    def _host(t):
        return tf.constant(np.ascontiguousarray(
            _ops.allgather(_to_numpy(t), name=resolved)))

    if _differentiable(tensor):
        tensor = tf.convert_to_tensor(tensor)

        # Reference gradient of HorovodAllgather
        # (tensorflow/mpi_ops.py:127-148), via the shared
        # ops.allgather_grad: allreduce-SUM the upstream gradient,
        # then keep this rank's dim-0 slice (variable allgather).
        @tf.custom_gradient
        def _op(x):
            y = _host(x)
            d0 = int(x.shape[0]) if x.shape.rank else 1

            def grad(dy):
                piece = _ops.allgather_grad(_to_numpy(dy), d0, resolved)
                if not x.shape.rank:
                    piece = piece.reshape(())
                return _to_tf(piece.astype(x.dtype.as_numpy_dtype), x)

            return y, grad

        return _op(tensor)
    return _host(tensor)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    import tensorflow as tf
    resolved = name if name is not None else _ops._auto_name("broadcast")

    def _host(t):
        return _to_tf(np.asarray(_ops.broadcast(
            _to_numpy(t), root_rank=root_rank, name=resolved)), _to_numpy(t))

    if _differentiable(tensor):
        tensor = tf.convert_to_tensor(tensor)

        # Reference gradient of HorovodBroadcast
        # (tensorflow/mpi_ops.py:168-181): allreduce-SUM of the
        # upstream gradient on the root; zeros elsewhere (non-root
        # inputs do not influence the output).
        @tf.custom_gradient
        def _op(x):
            y = _host(x)

            def grad(dy):
                summed = allreduce(dy, op=Sum, name=f"{resolved}.grad")
                if rank() != root_rank:
                    # zeros_like, not summed*0: a non-finite upstream
                    # (loss-scaling inf) would otherwise become NaN here
                    return tf.zeros_like(summed)
                return summed

            return y, grad

        return _op(tensor)
    return _host(tensor)


def alltoall(tensor, name: Optional[str] = None):
    out = _ops.alltoall(_to_numpy(tensor), name=name)
    import tensorflow as tf
    return tf.constant(np.ascontiguousarray(out))


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign root's values into ``variables``
    (reference: horovod/tensorflow/__init__.py:95-103)."""
    for i, var in enumerate(variables):
        host = _to_numpy(var)
        out = _ops.broadcast(host, root_rank=root_rank,
                             name=f"tf.bcast.{i}")
        var.assign(np.asarray(out).astype(host.dtype)
                   .reshape(host.shape))


def broadcast_global_variables(root_rank: int = 0) -> None:
    """TF1-compat global-variable broadcast
    (reference: horovod/tensorflow/__init__.py:106-114)."""
    import tensorflow as tf
    broadcast_variables(tf.compat.v1.global_variables(), root_rank)


class DistributedGradientTape:
    """Wrap tf.GradientTape so ``gradient()`` returns allreduced grads
    (reference: horovod/tensorflow/__init__.py:252-326)."""

    def __init__(self, tape, compression=Compression.none,
                 op: int = Average):
        self._tape = tape
        self._compression = compression
        self._op = op

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        import tensorflow as tf
        grads = self._tape.gradient(target, sources, output_gradients)
        # Mirror the sources' structure (bare variable in → bare tensor
        # out), like the reference's tf.nest handling.
        flat = tf.nest.flatten(grads)
        out = []
        for i, g in enumerate(flat):
            if g is None:
                out.append(None)
                continue
            out.append(allreduce(g, op=self._op, name=f"tape.grad.{i}",
                                 compression=self._compression))
        return tf.nest.pack_sequence_as(grads, out)

    def __getattr__(self, item):
        return getattr(self._tape, item)


def DistributedOptimizer(optimizer, compression=Compression.none,
                         op: int = Average,
                         sparse_as_dense: bool = False):
    """Wrap a tf.keras optimizer: apply_gradients averages first
    (reference: horovod/tensorflow/__init__.py:151-249;
    ``sparse_as_dense`` densifies IndexedSlices gradients before the
    reduce, :157,195-202)."""
    cls = optimizer.__class__

    class _Distributed(cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = []
            for i, (g, v) in enumerate(gv):
                if g is None:
                    reduced.append((None, v))
                    continue
                reduced.append((allreduce(
                    g, op=op, name=f"tfopt.grad.{i}",
                    compression=compression,
                    sparse_as_dense=sparse_as_dense), v))
            return super().apply_gradients(reduced, *args, **kwargs)

    config = optimizer.get_config()
    dist = _Distributed.from_config(config)
    _Distributed.__name__ = cls.__name__
    return dist


_hook_cls = None


def BroadcastGlobalVariablesHook(root_rank: int = 0, device: str = ""):
    """SessionRunHook that broadcasts rank 0's global variables after
    session creation (reference: horovod/tensorflow/__init__.py:
    117-148). Returns an instance of a real
    ``tf.compat.v1.train.SessionRunHook`` subclass (built lazily so
    importing this module never imports TF), so estimator/
    MonitoredSession isinstance checks accept it and the broadcast
    actually runs — in graph mode through the session (read via
    ``session.run``, write via ``Variable.load``), in eager through
    ``broadcast_variables``."""
    global _hook_cls
    if _hook_cls is None:
        import tensorflow as tf
        try:
            base = tf.compat.v1.train.SessionRunHook
        except AttributeError:  # exotic TF builds without compat.v1
            base = object

        class _BroadcastHook(base):
            def __init__(self, root_rank: int, device: str = ""):
                self.root_rank = root_rank

            def begin(self):
                pass

            def after_create_session(self, session, coord):
                import tensorflow as tf
                variables = tf.compat.v1.global_variables()
                if session is None:  # eager / no-session harnesses
                    broadcast_variables(variables, self.root_rank)
                    return
                for i, var in enumerate(variables):
                    host = np.asarray(session.run(var))
                    out = _ops.broadcast(host, root_rank=self.root_rank,
                                         name=f"tf.hook.bcast.{i}")
                    var.load(np.asarray(out).astype(host.dtype)
                             .reshape(host.shape), session)

            def before_run(self, run_context):
                return None

            def after_run(self, run_context, run_values):
                pass

            def end(self, session):
                pass

        _hook_cls = _BroadcastHook
    return _hook_cls(root_rank, device)


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "is_homogeneous",
    "Average", "Sum", "Compression", "poll",
    "allreduce", "allgather", "broadcast", "alltoall",
    "broadcast_variables", "broadcast_global_variables",
    "DistributedGradientTape", "DistributedOptimizer",
    "BroadcastGlobalVariablesHook",
]
