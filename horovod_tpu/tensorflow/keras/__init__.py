"""tf.keras facade (reference: horovod/tensorflow/keras/__init__.py —
a thin binding of the shared ``horovod/_keras`` implementation to
``tf.keras``; since TF 2.16 ``tf.keras`` IS Keras 3, so the shared
implementation here is ``horovod_tpu.keras`` itself).

Import as ``import horovod_tpu.tensorflow.keras as hvd`` in scripts
written against the reference's ``horovod.tensorflow.keras``.
"""

from __future__ import annotations

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu.ops import Average, Sum  # noqa: F401

from horovod_tpu.keras import (  # noqa: F401
    DistributedOptimizer, broadcast_global_variables, load_model,
)
from horovod_tpu.tensorflow.keras import callbacks  # noqa: F401


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "Average", "Sum", "Compression", "callbacks",
    "DistributedOptimizer", "broadcast_global_variables", "load_model",
]
