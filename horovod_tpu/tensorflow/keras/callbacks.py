"""tf.keras callbacks facade (reference:
horovod/tensorflow/keras/callbacks.py — re-export of the shared
``horovod/_keras/callbacks.py`` suite; with Keras 3 the shared suite is
``horovod_tpu.keras.callbacks``)."""

from horovod_tpu.keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback, LearningRateScheduleCallback,
    LearningRateWarmupCallback, MetricAverageCallback,
)

__all__ = [
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
]
