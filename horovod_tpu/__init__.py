"""horovod_tpu — a TPU-native distributed training framework.

A ground-up re-design of Horovod's contract (reference: Horovod v0.16.1,
/root/reference) for TPU hardware: named-tensor collectives (allreduce,
allgather, broadcast) negotiated by a rank-0 coordinator, tensor fusion,
auto-tuning, timeline profiling and stall detection — with the data plane
lowered to XLA collectives over a `jax.sharding.Mesh` (ICI/DCN) instead of
MPI/NCCL, and the control plane carried by a TCP coordination service
instead of `MPI_Gather`/`MPI_Bcast` (reference: horovod/common/operations.cc).

Framework adapters live in submodules, mirroring the reference layout
(reference: horovod/{tensorflow,torch,mxnet,keras}/__init__.py):

- ``horovod_tpu.jax``   — flagship adapter: jax arrays, optax optimizers.
- ``horovod_tpu.flax``  — flax TrainState helpers + callbacks.
- ``horovod_tpu.torch`` — torch CPU tensors staged via dlpack.
- ``horovod_tpu.keras`` — Keras-3 (JAX backend) callbacks.
- ``horovod_tpu.spmd``  — in-jit SPMD collectives over the device mesh.
- ``horovod_tpu.parallel`` — beyond-parity extensions: tensor/sequence
  parallelism, ring attention for long context.

Top-level exports are the framework-neutral basics + numpy-facing ops API,
so ``import horovod_tpu as hvd; hvd.init(); hvd.allreduce(x)`` works with
no framework at all (reference: horovod/common/__init__.py HorovodBasics).
"""

from horovod_tpu.version import __version__

from horovod_tpu.common.basics import (
    init,
    shutdown,
    initialized,
    metrics,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    is_homogeneous,
    coordinator_threads_supported,
    mpi_threads_supported,
)

from horovod_tpu.ops import (
    allreduce,
    allreduce_async,
    grouped_allreduce,
    grouped_allreduce_async,
    allgather,
    allgather_async,
    broadcast,
    broadcast_async,
    alltoall,
    alltoall_async,
    reducescatter,
    reducescatter_async,
    barrier,
    poll,
    synchronize,
    Average,
    Sum,
)

from horovod_tpu.common.compression import Compression
from horovod_tpu.common.status import (
    HorovodInternalError,
    WorldAbortedError,
)

# Elastic worlds (HOROVOD_ELASTIC=1, docs/fault_tolerance.md):
# hvd.elastic.State + @hvd.elastic.run make WorldAbortedError a
# recoverable event — survivors re-rendezvous into a shrunk world and
# training continues (upstream analog: Elastic Horovod, v0.20).
from horovod_tpu.common import elastic

# Multi-tenant collective service (docs/multitenancy.md):
# hvd.create_tenant runs several jobs' sub-worlds concurrently on one
# warm fleet under QoS-weighted scheduling; hvd.service attaches jobs
# to an hvdtpurun --service fleet and pulls parameter snapshots over
# a broadcast fanout, with no fleet re-rendezvous.
from horovod_tpu.common import tenancy as service
from horovod_tpu.common.tenancy import Tenant, create_tenant

__all__ = [
    "HorovodInternalError", "WorldAbortedError",
    "__version__",
    "init", "shutdown", "initialized", "metrics",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "is_homogeneous", "coordinator_threads_supported", "mpi_threads_supported",
    "allreduce", "allreduce_async",
    "grouped_allreduce", "grouped_allreduce_async",
    "allgather", "allgather_async",
    "broadcast", "broadcast_async",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async",
    "barrier", "poll", "synchronize",
    "Average", "Sum",
    "Compression",
    "elastic",
    "Tenant", "create_tenant", "service",
]
