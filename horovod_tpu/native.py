"""ctypes loader for the native runtime core (native/libhvdtpu.so).

Role-equivalent of the reference's ``HorovodBasics`` shared-library
loading (reference: horovod/common/__init__.py:51-63 ctypes CDLL with
RTLD_GLOBAL), with one twist: if the library has not been built yet and
a compiler is available, it is built on first import (the reference
front-loads this into its 1,012-line setup.py; we have one make rule).

Set ``HOROVOD_NATIVE=0`` to force the pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import lockdep
from horovod_tpu.common import logging as hlog

_lock = lockdep.lock("native._lock")
_lib = None
_tried = False

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libhvdtpu.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "hvdtpu.cc")

# Idle-slice callback type for hvd_steady_coord (the coordinator's
# PING fan-out re-enters Python once per idle poll slice). Module
# level so _configure and callers share one ctypes identity — a
# per-call CFUNCTYPE would defeat argtype checking AND risk the
# callback being garbage-collected mid-call.
ON_IDLE_FUNC = ctypes.CFUNCTYPE(None)

# The null idle callback, shared: callers that run a steady cycle
# WITHOUT a liveness deadline previously constructed a fresh
# ON_IDLE_FUNC(0) per cycle — a per-step allocation on the hot path
# whose mid-call garbage collection the type comment above warns
# about. One module-level instance removes both hazards and survives
# elastic re-inits (common/elastic.py) unchanged.
NULL_ON_IDLE = ON_IDLE_FUNC(0)


def disabled_via_env() -> bool:
    """The one definition of 'native core disabled by the operator'.
    Two spellings for compatibility: HOROVOD_NATIVE (docs) and
    HOROVOD_TPU_NATIVE (Config.native_core, common/config.py). Exact
    legacy truthiness on purpose (only these values disable) —
    env_bool's narrower truthy set would silently drop the C++ core
    for e.g. HOROVOD_NATIVE=ON deployments. Shared by get() and the
    CI gate (tests/conftest.py), so the two can never drift."""
    return (hconfig.env_str("HOROVOD_NATIVE", "1") == "0"
            or hconfig.env_str("HOROVOD_TPU_NATIVE", "1")
            in ("0", "false"))


def _so_fresh() -> bool:
    """The built library exists and is no older than its source."""
    if not os.path.exists(_SO_PATH):
        return False
    return not (os.path.exists(_SRC_PATH)
                and os.path.getmtime(_SRC_PATH)
                > os.path.getmtime(_SO_PATH))


def _build() -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        return False
    # Multiple local ranks may race the first build. Serialize with an
    # flock'd lockfile and have make produce the .so atomically enough
    # (each rank re-checks FRESHNESS under the lock before building —
    # a bare existence check here used to defeat the stale-rebuild
    # path in get(): a source newer than the .so was never recompiled,
    # so new native entry points silently stayed missing).
    lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
    try:
        import fcntl
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if _so_fresh():
                return True
            tmp_target = f"libhvdtpu.build{os.getpid()}.so"
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s",
                 f"TARGET={tmp_target}"],
                check=True, capture_output=True, timeout=120)
            os.replace(os.path.join(_NATIVE_DIR, tmp_target), _SO_PATH)
            return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        hlog.debug(f"native build failed: {e}")
        return False


def _configure(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.hvd_gather_frames.restype = ctypes.c_int
    lib.hvd_gather_frames.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, u8p, ctypes.c_int,
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int64), u8p,
        ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
    lib.hvd_broadcast_frame.restype = ctypes.c_int
    lib.hvd_broadcast_frame.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_uint8,
        u8p, ctypes.c_int64, u8p, ctypes.c_int]
    lib.hvd_scatter_frames.restype = ctypes.c_int
    lib.hvd_scatter_frames.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_uint8,
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int64), u8p,
        ctypes.c_int]
    lib.hvd_free.restype = None
    lib.hvd_free.argtypes = [u8p]
    lib.hvd_pack.restype = None
    lib.hvd_pack.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_void_p]
    lib.hvd_unpack.restype = None
    lib.hvd_unpack.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.hvd_sum_into.restype = ctypes.c_int
    lib.hvd_sum_into.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int]
    try:
        # Stale-.so tolerance (see get()): a pre-compression library
        # lacks the cast symbol; cast_into then reports unavailable
        # and callers use the numpy fallback.
        lib.hvd_cast.restype = ctypes.c_int
        lib.hvd_cast.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int]
    except AttributeError:
        pass
    lib.hvd_hmac_sha256.restype = None
    lib.hvd_hmac_sha256.argtypes = [
        u8p, ctypes.c_int, ctypes.c_uint8, u8p, ctypes.c_int64, u8p]
    i64p = ctypes.POINTER(ctypes.c_int64)
    vpp = ctypes.POINTER(ctypes.c_void_p)
    u8pp = ctypes.POINTER(u8p)
    lib.hvd_sendv.restype = ctypes.c_int
    lib.hvd_sendv.argtypes = [
        ctypes.c_int, ctypes.c_uint8, vpp, i64p, ctypes.c_int,
        u8p, ctypes.c_int]
    lib.hvd_recv_into.restype = ctypes.c_int
    lib.hvd_recv_into.argtypes = [
        ctypes.c_int, u8p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int64,
        u8p, ctypes.c_int,
        i64p, u8p,
        ctypes.c_int, ctypes.c_int,
        u8pp]
    lib.hvd_steady_worker.restype = ctypes.c_int
    lib.hvd_steady_worker.argtypes = [
        ctypes.c_int, ctypes.c_uint8, ctypes.c_uint8,
        u8p, ctypes.c_int64,
        u8pp, i64p,
        vpp, vpp,
        i64p, ctypes.c_int,
        u8p, ctypes.c_int,
        u8p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
        u8pp, i64p, u8p]
    try:
        # Stale-.so tolerance (see get()): a pre-overlap library lacks
        # the chunked entry; SteadyPlan.chunked then stays False and
        # the classic one-shot worker carries the cycle.
        lib.hvd_steady_worker_chunked.restype = ctypes.c_int
        lib.hvd_steady_worker_chunked.argtypes = [
            ctypes.c_int, ctypes.c_uint8, ctypes.c_uint8,
            u8p, ctypes.c_int64,
            u8pp, i64p,
            vpp, vpp,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int64,
            vpp,
            i64p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            u8p, ctypes.c_int,
            u8p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            u8pp, i64p, u8p]
    except AttributeError:
        pass
    lib.hvd_steady_coord.restype = ctypes.c_int
    lib.hvd_steady_coord.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_uint8, ctypes.c_uint8,
        u8p, ctypes.c_int64,
        u8pp, i64p,
        i64p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        u8pp, vpp,
        u8p, ctypes.c_int,
        u8p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
        ON_IDLE_FUNC,
        u8p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int), u8pp, i64p, u8p]
    try:
        # Stale-.so tolerance (see get()): a pre-reactor library lacks
        # the batched/zerocopy/relay/codec entries; the wrappers below
        # and the controller fast paths then report unavailable and the
        # callers run the sequential/classic/numpy code, wire-identical.
        lib.hvd_gather_frames_batched.restype = ctypes.c_int
        lib.hvd_gather_frames_batched.argtypes = [
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            u8p, ctypes.c_int,
            ctypes.c_uint8, vpp,
            i64p, i64p,
            u8p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ON_IDLE_FUNC,
            u8p, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), u8pp, i64p, u8p]
        lib.hvd_sendv_zc.restype = ctypes.c_int
        lib.hvd_sendv_zc.argtypes = [
            ctypes.c_int, ctypes.c_uint8, vpp, i64p, ctypes.c_int,
            u8p, ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.hvd_relay_frame.restype = ctypes.c_int
        lib.hvd_relay_frame.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.c_uint8, ctypes.c_void_p, ctypes.c_int64,
            u8p, ctypes.c_int,
            u8p, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            i64p, u8p, u8pp]
        lib.hvd_build_flags.restype = ctypes.c_int
        lib.hvd_build_flags.argtypes = []
        lib.hvd_quant8.restype = ctypes.c_int
        lib.hvd_quant8.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, u8p]
        lib.hvd_dequant8.restype = ctypes.c_int
        lib.hvd_dequant8.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p]
    except AttributeError:
        pass


def get() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (pure-Python fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if disabled_via_env():
            return None
        if not _so_fresh() and not _build():
            if not os.path.exists(_SO_PATH):
                hlog.debug("native core unavailable; using Python paths")
                return None
            # rebuild of a stale .so failed: keep using the old one —
            # dtype-ABI extensions degrade gracefully (sum_into returns
            # False for codes the old library rejects)
            hlog.warning("native core rebuild failed; using stale "
                         "library")
        try:
            lib = ctypes.CDLL(_SO_PATH)
            _configure(lib)
            _lib = lib
            hlog.debug(f"native core loaded from {_SO_PATH}")
        except OSError as e:
            hlog.warning(f"failed to load native core: {e}")
    return _lib


# -- numpy-facing wrappers ----------------------------------------------

_DTYPE_CODES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3,
                "uint8": 4, "float16": 5, "bfloat16": 6}


def pack(arrays):
    """Concatenate same-dtype C-contiguous flat arrays into one fresh
    buffer with a single native call (the reference's fusion-buffer
    MemcpyInFusionBuffer, collective_operations.cc:35-63). Returns
    None when the native path is unavailable (caller falls back to
    numpy concatenation)."""
    lib = get()
    if lib is None or not arrays:
        return None
    import numpy as np
    dtype = arrays[0].dtype
    for a in arrays:
        if a.dtype != dtype or not a.flags["C_CONTIGUOUS"]:
            return None
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    total = sum(a.size for a in arrays)
    out = np.empty(total, dtype)
    lib.hvd_pack(srcs, sizes, n, out.ctypes.data_as(ctypes.c_void_p))
    return out


def pack_into(arrays, out) -> bool:
    """Concatenate same-dtype C-contiguous flat arrays into ``out``
    (a preallocated writable array/view of exactly the packed size)
    with ONE native call — the zero-allocation fusion-arena pack of
    the steady data plane. Returns False when the native path cannot
    serve this batch (caller falls back to per-entry numpy copies)."""
    lib = get()
    if lib is None or not arrays:
        return False
    dtype = arrays[0].dtype
    total = 0
    for a in arrays:
        if a.dtype != dtype or not a.flags["C_CONTIGUOUS"]:
            return False
        total += a.nbytes
    if total != out.nbytes or not out.flags["C_CONTIGUOUS"]:
        return False
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    lib.hvd_pack(srcs, sizes, n, out.ctypes.data_as(ctypes.c_void_p))
    return True


def unpack_into(src, outs) -> bool:
    """Scatter a packed buffer into preallocated per-entry arrays with
    one native call (the fusion-buffer MemcpyOut without intermediate
    byte objects). ``src`` must be C-contiguous and exactly the
    concatenation of ``outs``. Returns False on fallback."""
    lib = get()
    if lib is None or not outs:
        return False
    total = 0
    for o in outs:
        if not o.flags["C_CONTIGUOUS"] or not o.flags["WRITEABLE"]:
            return False
        total += o.nbytes
    if total != src.nbytes or not src.flags["C_CONTIGUOUS"]:
        return False
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    sizes = (ctypes.c_int64 * n)(*[o.nbytes for o in outs])
    lib.hvd_unpack(src.ctypes.data_as(ctypes.c_void_p), sizes, n, dsts)
    return True


def compiler_available() -> bool:
    """True when a C++ compiler the Makefile can drive is on PATH —
    the tier-1 gate between 'fail the build loudly' and 'skip native
    tests with a reason'."""
    import shutil
    return any(shutil.which(c) for c in ("g++", "c++", "clang++"))


def build_status():
    """(loaded, reason) for CI plumbing: attempt the normal get() path
    and explain a None result. Used by tests/conftest.py to build the
    library once up front and fail LOUDLY when a compiler exists but
    the build is broken (a silent skip would unhook every native test
    from CI forever)."""
    lib = get()
    if lib is not None:
        return True, ""
    if disabled_via_env():
        return False, "disabled via HOROVOD_NATIVE/HOROVOD_TPU_NATIVE"
    if not compiler_available():
        return False, "no C++ compiler on PATH"
    return False, "build or load failed with a compiler present"


def cast_into(src, dst) -> bool:
    """dst[:] = src with a dtype cast via the native kernel (the
    wire-compression leg: f32<->bf16/f16). Returns False when the
    native path cannot serve this pair (caller falls back to numpy
    casting). An older .so without the symbol degrades the same way —
    the stale-library contract of get()."""
    lib = get()
    if lib is None or not hasattr(lib, "hvd_cast"):
        return False
    sc = _DTYPE_CODES.get(str(src.dtype))
    dc = _DTYPE_CODES.get(str(dst.dtype))
    if sc is None or dc is None or src.size != dst.size \
            or not src.flags["C_CONTIGUOUS"] \
            or not dst.flags["C_CONTIGUOUS"]:
        return False
    rc = lib.hvd_cast(
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        src.size, sc, dc)
    return rc == 0


def sum_into(acc, src) -> bool:
    """acc += src elementwise via the native kernel. Returns False if
    the native path is unavailable for this dtype (caller falls back)."""
    lib = get()
    if lib is None:
        return False
    code = _DTYPE_CODES.get(str(acc.dtype))
    if code is None or not acc.flags["C_CONTIGUOUS"] \
            or not src.flags["C_CONTIGUOUS"]:
        return False
    rc = lib.hvd_sum_into(
        acc.ctypes.data_as(ctypes.c_void_p),
        src.ctypes.data_as(ctypes.c_void_p),
        acc.size, code)
    return rc == 0


# int8-codec dtype codes (hvd_quant8/hvd_dequant8's third argument).
_QUANT_CODES = {"float32": 0, "float64": 1}


def quant8(src, out, residual=None, residual_out=None) -> bool:
    """Quantize ``src`` (f32/f64) into the int8 wire layout in ``out``
    (uint8, 4 + src.size bytes) with the native kernel: scale scan,
    saturating round-half-even and the error-feedback residual update
    fused into one pass, bit-identical to the numpy reference in
    common/wire_dtype.py. ``residual`` is added lane-wise before
    quantizing and ``residual_out`` (may alias ``residual``) receives
    the post-quantization error. Returns False when the native path
    cannot serve this call (caller falls back to numpy)."""
    lib = get()
    if lib is None or not hasattr(lib, "hvd_quant8"):
        return False
    code = _QUANT_CODES.get(str(src.dtype))
    if code is None or not src.flags["C_CONTIGUOUS"] \
            or not out.flags["C_CONTIGUOUS"] \
            or out.dtype.itemsize != 1 or out.nbytes != 4 + src.size:
        return False
    res_p = None
    res_out_p = None
    if residual is not None:
        if residual.dtype != src.dtype or residual.size != src.size \
                or not residual.flags["C_CONTIGUOUS"] \
                or residual_out is None:
            return False
        res_p = ctypes.c_void_p(residual.ctypes.data)
    if residual_out is not None:
        if residual_out.dtype != src.dtype \
                or residual_out.size != src.size \
                or not residual_out.flags["C_CONTIGUOUS"]:
            return False
        res_out_p = ctypes.c_void_p(residual_out.ctypes.data)
    rc = lib.hvd_quant8(
        ctypes.c_void_p(src.ctypes.data), src.size, code,
        res_p, res_out_p,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return rc == 0


def dequant8(raw, out) -> bool:
    """Expand the int8 wire layout in ``raw`` (uint8, 4 + out.size
    bytes) into ``out`` (f32/f64) with the native kernel — the numpy
    astype/multiply round-trip collapsed into one pass, bit-identical.
    Returns False when the native path cannot serve this call."""
    lib = get()
    if lib is None or not hasattr(lib, "hvd_dequant8"):
        return False
    code = _QUANT_CODES.get(str(out.dtype))
    if code is None or not raw.flags["C_CONTIGUOUS"] \
            or not out.flags["C_CONTIGUOUS"] \
            or raw.dtype.itemsize != 1 or raw.nbytes < 4 + out.size:
        return False
    rc = lib.hvd_dequant8(
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.size, code, ctypes.c_void_p(out.ctypes.data))
    return rc == 0


def build_flags() -> int:
    """Capability bitmask of the loaded core (hvd_build_flags): bit 0
    io_uring compiled in, bit 1 the running kernel accepts it, bit 2
    MSG_ZEROCOPY sends compiled in. 0 when the native core (or a stale
    pre-reactor .so) does not export the symbol."""
    lib = get()
    if lib is None or not hasattr(lib, "hvd_build_flags"):
        return 0
    return int(lib.hvd_build_flags())
