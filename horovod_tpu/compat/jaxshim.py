"""jaxshim — the ONE sanctioned JAX version-compat boundary.

Every mesh/sharding construction in this tree routes through here, and
the ``jax_compat`` hvdlint analyzer (tools/hvdlint/jax_compat.py)
enforces it: JAX moves its partitioning surface roughly once a year
(``jax.experimental.maps`` / ``sharded_jit`` → ``pjit`` →
``jax.sharding`` + ``jax.experimental.shard_map`` → top-level
``jax.shard_map``), and every move has historically rotted exactly the
modules that call the APIs directly — the 52-test shard_map family was
red from PR 3 to PR 20 for this reason alone. One module pays the
version tax; everyone else imports semantics.

Policy:

* wrappers are **version-gated on ``jax.__version__``** (parsed once
  per call through :func:`jax_version` so tests can mock a future
  release), with a feature probe as the safety net where the gate's
  edge is known to have shipped off-cycle;
* the supported floor is pinned in :data:`SUPPORTED_JAX_FLOOR` (also
  pinned in pyproject + README); the analyzer's API table flags any
  symbol that does not exist across the whole supported span;
* new JAX surface is adopted by *extending this module* — never by
  calling the new API at a use site.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Optional, Sequence

# The oldest JAX this tree supports (pinned in pyproject.toml and
# README; tools/hvdlint/jax_compat.py imports it for its API table).
SUPPORTED_JAX_FLOOR = (0, 4, 37)

# jax >= this hoists shard_map to the top level (``jax.shard_map``,
# replication checker spelled ``check_vma``); older releases keep it
# in jax.experimental.shard_map with ``check_rep``.
_TOP_LEVEL_SHARD_MAP = (0, 5, 0)


def _parse_version(v: str) -> tuple:
    """'0.4.37' / '0.7.0.dev20260101+abc' -> (0, 4, 37) / (0, 7, 0)."""
    parts = []
    for piece in v.split(".")[:3]:
        m = re.match(r"\d+", piece)
        if not m:
            break
        parts.append(int(m.group()))
    return tuple(parts) if parts else (0,)


def jax_version() -> tuple:
    """The running jax release as an int tuple. Read per call (not
    cached at import) so the version gate is unit-testable against a
    mocked ``jax.__version__``."""
    import jax
    return _parse_version(jax.__version__)


# ---------------------------------------------------------------------------
# mesh construction
# ---------------------------------------------------------------------------

def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None,
              allow_split_physical_axes: bool = False):
    """Build a ``jax.sharding.Mesh`` from ``{axis_name: size}``.

    At most one size may be ``-1`` (filled with the remaining
    devices); default is one ``'data'`` axis over every visible
    device. On multi-host platforms the device order comes from
    ``mesh_utils.create_device_mesh`` so trailing axes map to ICI
    neighbours and leading axes to DCN.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not axes:
        axes = {"data": n}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may have size -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if known == 0 or n % known:
            raise ValueError(
                f"cannot infer -1 axis: {n} devices not divisible "
                f"by {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {math.prod(sizes)} "
            f"devices but {n} are visible")
    dev_array = _device_array(tuple(sizes), devices,
                              allow_split_physical_axes)
    return Mesh(dev_array, names)


def _device_array(sizes: tuple, devices, allow_split: bool):
    """Topology-aware device grid; plain reshape when mesh_utils cannot
    place this platform (CPU test meshes, forced host platforms)."""
    import numpy as np
    from jax.experimental import mesh_utils
    try:
        return mesh_utils.create_device_mesh(
            sizes, devices=devices,
            allow_split_physical_axes=allow_split)
    except Exception:
        return np.asarray(devices).reshape(sizes)


def make_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int]):
    """Two-level mesh for multi-slice jobs: ``dcn_axes`` shard across
    slices, ``ici_axes`` within a slice."""
    from jax.sharding import Mesh
    from jax.experimental import mesh_utils

    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    dev_array = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_axes.values()),
        dcn_mesh_shape=tuple(dcn_axes.values()))
    return Mesh(dev_array, names)


def make_raw_mesh(dev_array, axis_names: Sequence[str]):
    """``jax.sharding.Mesh`` from an explicit device grid — for callers
    that computed their own placement (the XLA backend's proc meshes)."""
    from jax.sharding import Mesh
    return Mesh(dev_array, tuple(axis_names))


# ---------------------------------------------------------------------------
# sharding construction
# ---------------------------------------------------------------------------

def partition_spec(*axis_names):
    """``jax.sharding.PartitionSpec(*axis_names)``. Stable since jax
    0.4.6 (before that it lived in jax.experimental.pjit — below the
    supported floor, kept here so the table has one citation site)."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(*axis_names)


def named_sharding(mesh, spec):
    """``NamedSharding(mesh, spec)``; ``spec`` is a PartitionSpec (or
    anything PartitionSpec accepts when given as a tuple)."""
    from jax.sharding import NamedSharding, PartitionSpec
    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec) if isinstance(spec, (tuple, list)) \
            else PartitionSpec(spec)
    return NamedSharding(mesh, spec)


def with_sharding_constraint(x, mesh, spec):
    """Anchor an intermediate's sharding inside jit. Modern jax takes a
    Sharding directly; the pre-0.4 pjit spelling is below the floor."""
    import jax
    return jax.lax.with_sharding_constraint(x, named_sharding(mesh, spec))


# ---------------------------------------------------------------------------
# shard_map + collectives
# ---------------------------------------------------------------------------

def shard_map(body, mesh, in_specs, out_specs, check: bool = False):
    """Version-portable shard_map. ``check=False`` (the project
    default) disables the static replication checker — collectives
    guarantee their own output sharding, which the checker cannot see.

    jax >= 0.5 hoists shard_map to the top level with ``check_vma``;
    the 0.4.x line keeps it in jax.experimental.shard_map with
    ``check_rep``. Gated on :func:`jax_version` with a feature probe
    as the net (0.4.35 briefly aliased the top-level name behind a
    deprecation gate that *raises* — the probe must tolerate that).
    """
    import jax
    if jax_version() >= _TOP_LEVEL_SHARD_MAP:
        fn = getattr(jax, "shard_map", None)
        if fn is not None:
            return fn(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as fn
    return fn(body, mesh=mesh, in_specs=in_specs,
              out_specs=out_specs, check_rep=check)


def axis_size(axis) -> int:
    """Static size of a named mesh axis, inside shard_map/pmap.
    ``jax.lax.axis_size`` only exists above the supported floor; the
    0.4.x spelling is the classic ``psum(1, axis)``, which jax
    constant-folds to the axis size at trace time."""
    import jax
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.lax.psum(1, axis)


def psum_scatter(x, axis, scatter_dimension: int = 0, tiled: bool = True):
    """``jax.lax.psum_scatter`` — stable across the supported span;
    wrapped so the reduce-scatter spelling has one version-gateable
    call site (its kwargs are the next most likely to move)."""
    import jax
    return jax.lax.psum_scatter(x, axis,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled)


__all__ = [
    "SUPPORTED_JAX_FLOOR", "jax_version",
    "make_mesh", "make_hybrid_mesh", "make_raw_mesh",
    "partition_spec", "named_sharding", "with_sharding_constraint",
    "shard_map", "axis_size", "psum_scatter",
]
