"""Version-compat shims. ``jaxshim`` is the single sanctioned module
for JAX mesh/sharding construction — see docs/static_analysis.md
(jax_compat analyzer) for the policy."""

from horovod_tpu.compat import jaxshim

__all__ = ["jaxshim"]
