"""Built-in warm host for ``hvdtpurun --service`` with no command.

Each slot inits the world and then idles warm: the fleet's collective
substrate (controller channels, heartbeats, metrics/trace planes, the
rank-0 service gate) stays hot while jobs attach and detach through
the tenant gate (common/tenancy.py, docs/multitenancy.md). Rank 0
publishes a small heartbeat snapshot so a freshly-attached replica
always has SOMETHING to pull before a real trainer publishes weights.

A real deployment usually runs its own training script under
--service instead; this module is the zero-config way to stand up a
warm fleet and the smoke-test target for service mode.
"""

from __future__ import annotations

import signal
import threading
import time

import numpy as np


def main() -> None:
    import horovod_tpu as hvd
    from horovod_tpu.common import tenancy

    hvd.init()
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    for s in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(s, _sig)
        except (ValueError, OSError):
            pass  # non-main thread / restricted platform

    beat = 0
    while not stop.is_set():
        if hvd.rank() == 0 and tenancy.service_gate() is not None:
            tenancy.publish_snapshot(
                {"service.heartbeat": np.asarray(
                    [time.time(), float(beat)], np.float64)},
                version=None)
        # A periodic world collective keeps every slot's control
        # plane exercised (and fail-fast if a peer dies) without
        # burning the host: one tiny allreduce per beat interval.
        # beat advances on EVERY rank — tensor names must agree.
        beat += 1
        hvd.allreduce(np.zeros(1, np.float32), average=False,
                      name="service.beat")
        stop.wait(5.0)
    hvd.shutdown()


if __name__ == "__main__":
    main()
