"""Programmatic function launcher: run a Python function on N ranks
and collect per-rank results.

Role-equivalent of ``horovod.spark.run(fn, ...)``
(reference: horovod/spark/__init__.py:82-199) without the Spark
dependency: the function is pickled, executed in N launched processes
(local by default), and the return values come back ordered by rank —
the same contract Spark users rely on. ``horovod_tpu.spark`` layers the
actual Spark scheduling on top when pyspark is present.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, List, Optional

from horovod_tpu.run.launch import run_local

_RUNNER = r"""
import pickle, sys
fn_path, out_path = sys.argv[1], sys.argv[2]
with open(fn_path, "rb") as f:
    fn, args, kwargs = pickle.load(f)
import horovod_tpu as hvd
hvd.init()
result = fn(*args, **kwargs)
rank = hvd.rank()
with open(out_path + f".{rank}", "wb") as f:
    pickle.dump(result, f)
hvd.shutdown()
"""


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: int = 1, env: Optional[dict] = None,
        start_timeout: float = 30.0) -> List[Any]:
    """Execute ``fn(*args, **kwargs)`` on ``num_proc`` ranks; returns
    the per-rank results ordered by rank
    (reference: horovod.spark.run result ordering,
    spark/__init__.py:195-199)."""
    kwargs = kwargs or {}
    with tempfile.TemporaryDirectory() as tmp:
        fn_path = os.path.join(tmp, "fn.pkl")
        out_path = os.path.join(tmp, "result")
        runner_path = os.path.join(tmp, "runner.py")
        with open(fn_path, "wb") as f:
            pickle.dump((fn, args, kwargs), f)
        with open(runner_path, "w") as f:
            f.write(_RUNNER)
        penv = dict(env or {})
        penv.setdefault("PYTHONPATH", os.pathsep.join(
            [p for p in ([os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))]
                + sys.path) if p]))
        code = run_local(
            num_proc,
            [sys.executable, runner_path, fn_path, out_path],
            env=penv, start_timeout=start_timeout)
        if code != 0:
            raise RuntimeError(f"horovod_tpu.run.api.run failed with "
                               f"exit code {code}")
        results = []
        for rank in range(num_proc):
            with open(f"{out_path}.{rank}", "rb") as f:
                results.append(pickle.load(f))
        return results
