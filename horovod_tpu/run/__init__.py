"""hvdtpurun — the launcher (reference: horovod/run/ ``horovodrun``).

``python -m horovod_tpu.run -np N [-H host1:slots,host2:slots] cmd...``

Local worlds (no ``-H``, or only localhost) spawn N processes directly.
Multi-host worlds start a driver TCP service, launch one task server
per host (over ssh), let tasks register their routable addresses,
assign ranks grouped by host (rank 0 on the first host, like the
reference's host ordering), and remote-exec the command with the
controller coordinates in the environment
(reference: horovod/run/run.py:193-264 _driver_fn + task_fn.py).
"""

from horovod_tpu.run.launch import main, run_local

__all__ = ["main", "run_local"]
