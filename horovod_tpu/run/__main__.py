from horovod_tpu.run.launch import main

if __name__ == "__main__":
    main()
