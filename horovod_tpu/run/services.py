"""Driver/task services for multi-host launches.

Re-architecture of the reference's launcher RPC layer
(reference: horovod/run/common/service/driver_service.py:43-152,
task_service.py, horovod/run/task_fn.py:23-52): a driver TCP service
collects task registrations (host index + routable addresses), tasks
probe their ring-neighbour's interfaces to drop NAT'ed/unroutable ones
(reference: run/task_fn.py:32-46 match_intf), the driver intersects
what remains, assigns ranks grouped by host, and commands each task to
exec the training processes. Wire format is JSON over the framed
HMAC channel (common/network.py) — no pickle on the wire, unlike the
reference's cloudpickle ``Wire``, so a forged frame can't execute code
even if the secret leaks.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import lockdep
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network

TAG_MSG = 7


def local_addresses() -> List[str]:
    """Routable-looking addresses of this host (loopback excluded
    unless nothing else exists)."""
    addrs: List[str] = []
    hostname = socket.gethostname()
    try:
        for info in socket.getaddrinfo(hostname, None, socket.AF_INET):
            a = info[4][0]
            if a not in addrs:
                addrs.append(a)
    except socket.gaierror:
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        a = s.getsockname()[0]
        if a not in addrs:
            addrs.append(a)
        s.close()
    except OSError:
        pass
    non_loop = [a for a in addrs if not a.startswith("127.")]
    return non_loop or ["127.0.0.1"]


def probe(addr: str, port: int, timeout: float = 2.0) -> bool:
    """Can this process open a TCP connection to addr:port?
    (reference: run/common/util/network.py:152-246 BasicClient
    multi-interface probing)."""
    try:
        with socket.create_connection((addr, port), timeout=timeout):
            return True
    except OSError:
        return False


class _JsonChannel:
    def __init__(self, ch: network.Channel):
        self._ch = ch

    def send(self, obj) -> None:
        self._ch.send(json.dumps(obj).encode(), TAG_MSG)

    def recv(self):
        tag, payload = self._ch.recv()
        if tag != TAG_MSG:
            raise ConnectionError(f"unexpected tag {tag}")
        return json.loads(payload.decode())

    def close(self):
        self._ch.close()


class DriverService:
    """Launcher-side registry + command fan-out
    (reference: horovod/run/driver/driver_service.py +
    common/service/driver_service.py)."""

    def __init__(self, num_hosts: int, secret: bytes = b""):
        self._num_hosts = num_hosts
        self._secret = secret
        self._server = network.listen(0)
        self.port = self._server.getsockname()[1]
        self._tasks: Dict[int, _JsonChannel] = {}
        self._task_info: Dict[int, dict] = {}
        self._lock = lockdep.lock("services.DriverService._lock")

    def wait_for_registration(self, timeout: float = 60.0) -> None:
        """Accept one connection per host; each sends
        {host_index, hostname, addresses, task_port}."""
        deadline = time.monotonic() + timeout
        self._server.settimeout(1.0)
        while len(self._tasks) < self._num_hosts:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(self._tasks)}/{self._num_hosts} task "
                    "servers registered before timeout")
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            try:
                sock.settimeout(10.0)
                ch = _JsonChannel(network.Channel(sock, self._secret))
                hello = ch.recv()
                idx = int(hello["host_index"])
                if idx < 0 or idx >= self._num_hosts or idx in self._tasks:
                    raise ConnectionError(f"bad host index {idx}")
            except (ConnectionError, socket.timeout, ValueError, KeyError,
                    TypeError, UnicodeDecodeError) as e:
                hlog.warning(f"driver rejected connection: {e}")
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.settimeout(None)
            self._tasks[idx] = ch
            self._task_info[idx] = hello

    def ring_probe(self) -> None:
        """Ask each task to probe its successor's addresses; keep only
        addresses the predecessor could reach (reference:
        run/task_fn.py:32-46 — NAT'ed interface filtering)."""
        n = self._num_hosts
        if n <= 1:
            return
        for i in range(n):
            nxt = self._task_info[(i + 1) % n]
            self._tasks[i].send({
                "cmd": "probe",
                "addresses": nxt["addresses"],
                "port": nxt["task_port"],
            })
        for i in range(n):
            result = self._tasks[i].recv()
            reachable = result.get("reachable", [])
            target = (i + 1) % n
            info = self._task_info[target]
            kept = [a for a in info["addresses"] if a in reachable]
            if kept:
                info["addresses"] = kept

    def assign_ranks(self, slots: Sequence[int]) -> List[dict]:
        """Contiguous ranks per host, host 0 first (reference:
        spark/__init__.py:144-154 host-hash grouping w/ rank 0 first).
        Returns one assignment dict per host."""
        assignments = []
        next_rank = 0
        for i in range(self._num_hosts):
            ranks = list(range(next_rank, next_rank + slots[i]))
            next_rank += slots[i]
            assignments.append({
                "host_index": i,
                "ranks": ranks,
                "size": sum(slots),
            })
        return assignments

    def controller_endpoint(self) -> dict:
        """Rank-0 host's reachable address + a port reserved ON that
        host (a port free on the launcher machine may be taken on the
        rank-0 host — the TaskServer holds the reservation until just
        before it spawns the training processes)."""
        self._tasks[0].send({"cmd": "alloc_port"})
        port = int(self._tasks[0].recv()["port"])
        info0 = self._task_info[0]
        addr = info0["addresses"][0]
        return {"addr": addr, "port": port}

    def launch(self, assignments: List[dict], command: List[str],
               env: Dict[str, str], controller: dict) -> None:
        for i in range(self._num_hosts):
            self._tasks[i].send({
                "cmd": "launch",
                "assignment": assignments[i],
                "command": command,
                "env": env,
                "controller": controller,
            })

    def wait_for_exit(self, timeout: Optional[float] = None) -> List[int]:
        """Collect per-host exit codes (first nonzero local process,
        signal deaths preserved as negatives)."""
        codes = []
        for i in range(self._num_hosts):
            msg = self._tasks[i].recv()
            codes.append(int(msg.get("exit_code", 1)))
        return codes

    def shutdown(self) -> None:
        for ch in self._tasks.values():
            try:
                ch.send({"cmd": "shutdown"})
            except OSError:
                pass
            try:
                ch.close()
            except OSError:
                pass  # stage-guarded: the listener below must still close
        self._server.close()


class TaskServer:
    """Per-host agent: registers with the driver, answers probes,
    spawns the local training processes, reports exit status
    (reference: horovod/run/task/task_service.py + task_fn.py)."""

    def __init__(self, host_index: int, driver_addr: str,
                 driver_port: int, secret: bytes = b""):
        self.host_index = host_index
        self._reserved: Optional[socket.socket] = None
        # listening socket other tasks probe against
        self._probe_server = network.listen(0)
        self.task_port = self._probe_server.getsockname()[1]
        self._accepting = threading.Thread(target=self._accept_probes,
                                           daemon=True)
        self._accepting.start()
        ch = network.connect(driver_addr, driver_port, secret,
                             timeout=30.0, retry_deadline=30.0)
        self._ch = _JsonChannel(ch)
        self._ch.send({
            "host_index": host_index,
            "hostname": socket.gethostname(),
            "addresses": local_addresses(),
            "task_port": self.task_port,
        })

    def _accept_probes(self) -> None:
        while True:
            try:
                sock, _ = self._probe_server.accept()
                sock.close()
            except OSError:
                return

    def serve_forever(self) -> int:
        """Process driver commands until shutdown; returns exit code."""
        exit_code = 0
        while True:
            msg = self._ch.recv()
            cmd = msg.get("cmd")
            if cmd == "probe":
                reachable = [a for a in msg["addresses"]
                             if probe(a, msg["port"])]
                self._ch.send({"reachable": reachable})
            elif cmd == "alloc_port":
                # Reserve a controller port on THIS host; held until
                # launch so nothing else can grab it meanwhile.
                self._reserved = network.listen(0)
                self._ch.send(
                    {"port": self._reserved.getsockname()[1]})
            elif cmd == "launch":
                exit_code = self._launch(msg)
                self._ch.send({"exit_code": exit_code})
            elif cmd == "shutdown":
                self._probe_server.close()
                self._ch.close()
                return exit_code
            else:
                hlog.warning(f"task {self.host_index}: unknown driver "
                             f"command {cmd!r}")

    def _launch(self, msg) -> int:
        assignment = msg["assignment"]
        controller = msg["controller"]
        procs = []
        for rank in assignment["ranks"]:
            env = dict(os.environ)
            env.update(msg.get("env", {}))
            env["HOROVOD_RANK"] = str(rank)
            env["HOROVOD_SIZE"] = str(assignment["size"])
            env["HOROVOD_CONTROLLER_ADDR"] = controller["addr"]
            env["HOROVOD_CONTROLLER_PORT"] = str(controller["port"])
            pass_fds = ()
            if rank == 0 and self._reserved is not None:
                # Hand the reserved listener to rank 0 as an inherited
                # fd (socket-activation style): the endpoint published
                # to every host can never be stolen, because the socket
                # is never unbound between reservation and init.
                fd = self._reserved.fileno()
                os.set_inheritable(fd, True)
                env["HOROVOD_CONTROLLER_FD"] = str(fd)
                pass_fds = (fd,)
            procs.append(subprocess.Popen(msg["command"], env=env,
                                          close_fds=True,
                                          pass_fds=pass_fds))
        if self._reserved is not None:
            # The child owns a duplicate now; drop ours.
            self._reserved.close()
            self._reserved = None
        # Same teardown contract as run_local: a local rank dying
        # nonzero starts the abort-propagation grace window — the
        # in-band ABORT usually fails this host's survivors cleanly —
        # then the remainder is hard-killed as a backstop.
        from horovod_tpu.run.launch import reap_with_grace
        return reap_with_grace(procs)


def task_main() -> None:
    """Entry for ``python -m horovod_tpu.run.services <host_index>
    <driver_addr> <driver_port>`` — what the launcher execs over ssh
    (reference: ssh-launched ``python -m horovod.run.task_fn``,
    run/run.py:103-190)."""
    host_index = int(sys.argv[1])
    driver_addr = sys.argv[2]
    driver_port = int(sys.argv[3])
    secret = hconfig.env_str("HOROVOD_SECRET_KEY", "").encode()
    server = TaskServer(host_index, driver_addr, driver_port, secret)
    sys.exit(server.serve_forever())


if __name__ == "__main__":
    task_main()
