"""hvdtpurun CLI + local/ssh launch drivers
(reference: horovod/run/run.py:295-483 + bin/horovodrun).

Unlike the reference, there is no mpirun at the bottom: the task
servers spawn the training processes directly and the controller
coordinates, so the whole stack is ours.
"""

from __future__ import annotations

import argparse
import json
import os
import secrets as _secrets
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common import config as hconfig
from horovod_tpu.run.services import DriverService, local_addresses


class HostCheckCache:
    """Cached host-reachability results, one hour by default
    (reference: run/util/cache.py — the 60-minute ``~/.horovod`` result
    cache keyed by check parameters; ``--disable-cache`` bypasses it).
    Only successes are cached: a host that was down may come back, so
    failures are always re-probed."""

    def __init__(self, path: Optional[str] = None, ttl_s: float = 3600.0):
        base = hconfig.env_str("HOROVOD_TPU_CACHE_DIR", "~/.horovod_tpu")
        self._path = path or os.path.join(
            os.path.expanduser(base), "hostcheck.json")
        self._ttl = ttl_s
        self._data: Dict[str, dict] = {}
        try:
            with open(self._path) as f:
                self._data = json.load(f)
        except (OSError, ValueError):
            pass

    def get(self, key: str) -> Optional[bool]:
        ent = self._data.get(key)
        if ent and ent.get("ok") and time.time() - ent["t"] < self._ttl:
            return True
        return None

    def put_all(self, results: Dict[str, bool]) -> None:
        """Record a batch of results and persist once. Call from ONE
        thread after the probe threads have joined — the store is not
        synchronized."""
        for key, ok in results.items():
            if ok:
                self._data[key] = {"ok": True, "t": time.time()}
            else:
                self._data.pop(key, None)
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            tmp = f"{self._path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(self._data, f)
            os.replace(tmp, self._path)
        except OSError:
            pass


def _local_hosts() -> set:
    return {"localhost", "127.0.0.1", socket.gethostname()}


def _ssh_base(ssh_port: Optional[int],
              connect_timeout: Optional[float] = None) -> List[str]:
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if connect_timeout is not None:
        cmd += ["-o", f"ConnectTimeout={max(1, int(connect_timeout))}"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    return cmd


def _default_ssh_check(host: str, ssh_port: Optional[int],
                       timeout: float) -> bool:
    cmd = _ssh_base(ssh_port, connect_timeout=timeout) + [host, "true"]
    try:
        return subprocess.run(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=timeout + 5).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def check_hosts_reachable(hosts: List[Tuple[str, int]],
                          ssh_port: Optional[int] = None,
                          timeout: float = 10.0,
                          check_fn=None,
                          cache: Optional[HostCheckCache] = None) -> None:
    """Threaded ssh reachability pre-check before anything is spawned
    (reference: run/run.py:44-100 — parallel `ssh true` probes): a dead
    host fails fast with a per-host message instead of surfacing later
    as a generic registration timeout. ``check_fn(host) -> bool`` is
    injectable for tests; successes are cached (see HostCheckCache).
    Cache reads/writes happen on this thread only — probe threads just
    run the checks."""
    to_check = [h for h, _ in hosts if h not in _local_hosts()]
    if not to_check:
        return
    check = check_fn or (
        lambda h: _default_ssh_check(h, ssh_port, timeout))
    results: Dict[str, bool] = {}
    need_probe = []
    for h in to_check:
        if cache is not None and cache.get(f"{h}:{ssh_port or 22}"):
            results[h] = True
        else:
            need_probe.append(h)

    def _probe(h: str) -> None:
        results[h] = bool(check(h))

    threads = [threading.Thread(target=_probe, args=(h,), daemon=True)
               for h in need_probe]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 10)
    if cache is not None and need_probe:
        cache.put_all({f"{h}:{ssh_port or 22}": results.get(h, False)
                       for h in need_probe})
    dead = [h for h in to_check if not results.get(h)]
    if dead:
        raise RuntimeError(
            f"host(s) unreachable over ssh: {', '.join(dead)} — verify "
            f"connectivity (`ssh {dead[0]} true`), the -H host list, "
            f"and --ssh-port, then retry.")


def parse_hosts(spec: str) -> List[Tuple[str, int]]:
    """'a:4,b:4' -> [('a', 4), ('b', 4)]
    (reference: run/run.py -H format)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.rsplit(":", 1)
            out.append((host, int(slots)))
        else:
            out.append((part, 1))
    return out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def abort_grace_seconds() -> float:
    """Seconds the launcher waits, after a rank dies, for survivors to
    fail themselves through the coordinator-mediated abort protocol
    (heartbeats + ABORT fan-out in common/controller.py) before the
    mpirun-style hard kill. The grace turns "launcher murdered me" into
    a clean Python-level WorldAbortedError in every surviving rank's
    training script; the kill stays as the backstop for survivors too
    wedged to run the protocol."""
    return hconfig.env_float("HOROVOD_TPU_ABORT_GRACE", 5.0)


def reap_with_grace(procs) -> int:
    """Wait for every child; on the first nonzero exit, give the
    survivors ``abort_grace_seconds()`` to fail themselves through the
    in-band ABORT protocol, then SIGTERM the stragglers (mpirun-style
    kill-on-first-exit, softened). Polls only these children — a bare
    ``os.wait()`` would reap unrelated subprocesses of an embedding
    process. Returns the FIRST nonzero returncode, preserving signal
    deaths (negative values) — never folds them back to success."""
    exit_code = 0
    pending = list(procs)
    grace_deadline = None
    killed = False
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is None:
                continue
            pending.remove(p)
            if rc != 0:
                exit_code = exit_code or rc
                if grace_deadline is None:
                    grace_deadline = (time.monotonic()
                                      + abort_grace_seconds())
        if pending and not killed and grace_deadline is not None \
                and time.monotonic() >= grace_deadline:
            killed = True
            for q in pending:
                try:
                    q.terminate()
                except OSError:
                    pass
        if pending:
            time.sleep(0.05)
    return exit_code


def run_local(np_: int, command: List[str],
              env: Optional[Dict[str, str]] = None,
              start_timeout: float = 30.0) -> int:
    """Spawn np_ ranks on this host (the ``-H`` -less fast path; the
    reference always shells out to mpirun even locally — we don't
    need to)."""
    port = _free_port()
    procs = []
    for rank in range(np_):
        penv = dict(os.environ)
        if env:
            penv.update(env)
        penv["HOROVOD_RANK"] = str(rank)
        penv["HOROVOD_SIZE"] = str(np_)
        penv["HOROVOD_CONTROLLER_ADDR"] = "127.0.0.1"
        penv["HOROVOD_CONTROLLER_PORT"] = str(port)
        penv.setdefault("HOROVOD_START_TIMEOUT", str(start_timeout))
        procs.append(subprocess.Popen(command, env=penv))

    exit_code = 0
    try:
        # One rank failing still tears the world down like mpirun
        # does, but only after the abort-propagation grace window: the
        # in-band ABORT protocol usually fails the survivors cleanly
        # first, so they exit with a structured error, not a SIGTERM.
        exit_code = reap_with_grace(procs)
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        exit_code = 130
    finally:
        deadline = time.monotonic() + 10.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
    return exit_code


class HostBlacklist:
    """Per-slot failure ledger with exponential backoff — the
    launcher-side half of elastic mode (upstream analog: Elastic
    Horovod's host blacklist). A slot whose worker died waits
    ``base * 2^(failures-1)`` seconds (capped) before its respawn
    rejoins at the next rendezvous barrier; a slot that keeps dying
    past ``retries`` is blacklisted for good."""

    def __init__(self, base_s: Optional[float] = None,
                 cap_s: float = 60.0, retries: Optional[int] = None):
        self.base_s = base_s if base_s is not None else \
            hconfig.env_float("HOROVOD_TPU_ELASTIC_BACKOFF", 1.0)
        self.cap_s = cap_s
        self.retries = retries if retries is not None else \
            hconfig.env_int("HOROVOD_TPU_ELASTIC_RETRIES", 3)
        self._failures: Dict[int, int] = {}
        self._until: Dict[int, float] = {}

    def record_failure(self, slot: int, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        n = self._failures.get(slot, 0) + 1
        self._failures[slot] = n
        self._until[slot] = now + min(
            self.cap_s, self.base_s * (2.0 ** (n - 1)))

    def permanently_dead(self, slot: int) -> bool:
        return self._failures.get(slot, 0) > self.retries

    def ready_to_retry(self, slot: int,
                       now: Optional[float] = None) -> bool:
        if self.permanently_dead(slot):
            return False
        now = time.monotonic() if now is None else now
        return now >= self._until.get(slot, 0.0)

    def backlog(self) -> Dict[int, int]:
        """slot -> failure count, for logs and the launcher summary."""
        return dict(self._failures)


def run_local_elastic(np_: int, command: List[str],
                      env: Optional[Dict[str, str]] = None,
                      start_timeout: float = 30.0,
                      min_np: int = 1,
                      max_np: Optional[int] = None,
                      spawn_fn=None,
                      blacklist: Optional[HostBlacklist] = None,
                      poll_s: float = 0.1,
                      restarts: Optional[int] = None) -> int:
    """Elastic local launch (``hvdtpurun --elastic``): spawn ``np_``
    ranks, then SUPERVISE instead of killing the world on the first
    death. A dead worker's slot goes on the blacklist with exponential
    backoff; once its backoff expires it is respawned as a JOINER
    (HOROVOD_ELASTIC_JOIN=1) that rejoins the running world at the
    next rendezvous barrier. The in-process elastic machinery
    (common/elastic.py) keeps the surviving ranks training throughout;
    this loop only manages processes. Every slot's elastic listener
    port is launcher-reserved so a respawn can always dial SOME live
    member (any member redirects a joiner to the current coordinator).

    ``spawn_fn(slot, env, joiner) -> Popen-like`` is injectable for
    tests. Returns 0 when every live worker exits cleanly; the first
    nonzero exit code when the world is lost.

    ``restarts`` (env HOROVOD_TPU_ELASTIC_RESTARTS, default 0): when
    the whole world is lost — below the floor with nothing left to
    respawn — restart up to that many FRESH worlds of ``np_`` ranks
    instead of giving up. With async checkpoints armed
    (HOROVOD_SELFOP_CKPT_DIR, common/selfop.py) each restart resumes
    from state seconds old; fault specs are stripped from restarted
    worlds (the injected failure already did its job)."""
    max_np = max_np or np_
    blacklist = blacklist or HostBlacklist()
    restarts = restarts if restarts is not None else \
        hconfig.env_int("HOROVOD_TPU_ELASTIC_RESTARTS", 0)
    port = _free_port()
    elastic_ports = [_free_port() for _ in range(max_np)]
    restarted_world = False

    def _spawn(slot: int, joiner: bool):
        penv = dict(os.environ)
        if env:
            penv.update(env)
        penv["HOROVOD_ELASTIC"] = "1"
        penv["HOROVOD_ELASTIC_MIN_WORLD"] = str(min_np)
        penv["HOROVOD_TPU_ELASTIC_PORT"] = str(elastic_ports[slot])
        penv.setdefault("HOROVOD_START_TIMEOUT", str(start_timeout))
        if restarted_world:
            penv.pop("HOROVOD_FAULT_SPEC", None)
        if joiner:
            # Point the joiner at any LIVE member's elastic listener;
            # whoever answers redirects it to the current coordinator.
            alive = [s for s in procs if procs[s].poll() is None
                     and s != slot]
            anchor = alive[0] if alive else 0
            penv["HOROVOD_ELASTIC_JOIN"] = "1"
            penv["HOROVOD_ELASTIC_JOIN_ADDR"] = "127.0.0.1"
            penv["HOROVOD_ELASTIC_JOIN_PORT"] = \
                str(elastic_ports[anchor])
            penv.pop("HOROVOD_RANK", None)
            penv.pop("HOROVOD_SIZE", None)
            # An injected fault already did its job killing the first
            # incarnation; the respawn must not re-arm it.
            penv.pop("HOROVOD_FAULT_SPEC", None)
        else:
            penv["HOROVOD_RANK"] = str(slot)
            penv["HOROVOD_SIZE"] = str(np_)
        penv["HOROVOD_CONTROLLER_ADDR"] = "127.0.0.1"
        penv["HOROVOD_CONTROLLER_PORT"] = str(port)
        if spawn_fn is not None:
            return spawn_fn(slot, penv, joiner)
        return subprocess.Popen(command, env=penv)

    procs: Dict[int, object] = {}
    while True:
        for slot in range(np_):
            procs[slot] = _spawn(slot, joiner=False)
        pending_respawn: set = set()
        exit_code = 0
        clean_exits = 0
        interrupted = False
        try:
            while True:
                for slot, p in list(procs.items()):
                    rc = p.poll()
                    if rc is None:
                        continue
                    del procs[slot]
                    if rc == 0:
                        clean_exits += 1
                        continue  # finished training: never respawned
                    exit_code = exit_code or rc
                    blacklist.record_failure(slot)
                    if blacklist.permanently_dead(slot):
                        print(f"hvdtpurun: slot {slot} failed "
                              f"{blacklist.backlog()[slot]} times — "
                              f"blacklisted for good", file=sys.stderr)
                    else:
                        pending_respawn.add(slot)
                for slot in sorted(pending_respawn):
                    if len(procs) >= max_np or not procs:
                        break
                    if blacklist.ready_to_retry(slot):
                        pending_respawn.discard(slot)
                        procs[slot] = _spawn(slot, joiner=True)
                if not procs:
                    break
                if len(procs) < min_np and not pending_respawn \
                        and clean_exits == 0:
                    # Below the floor with nothing left to respawn and
                    # nobody finishing normally: the in-process
                    # min-world check aborts the survivors; we just
                    # stop supervising. (With clean exits the job is
                    # simply draining — lockstep training finishes
                    # everywhere at once, so keep reaping until empty.)
                    break
                time.sleep(poll_s)
        except KeyboardInterrupt:
            exit_code = 130
            interrupted = True
        finally:
            deadline = time.monotonic() + abort_grace_seconds() + 10.0
            for p in procs.values():
                try:
                    p.terminate()
                except OSError:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=max(0.1,
                                       deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        # A world that ended with every (surviving) worker clean is a
        # success even if some workers died and were replaced on the
        # way.
        if clean_exits > 0 and exit_code != 0 and not procs \
                and clean_exits >= min_np:
            return 0
        if exit_code == 0 or interrupted or restarts <= 0:
            return exit_code
        # World lost, restart budget left: start a FRESH world of np_
        # ranks. Async checkpoints (common/selfop.py) make this resume
        # from state seconds old rather than step 0; a fresh blacklist
        # gives every slot a clean ledger in the new world.
        restarts -= 1
        restarted_world = True
        procs.clear()
        blacklist = HostBlacklist(base_s=blacklist.base_s,
                                  cap_s=blacklist.cap_s,
                                  retries=blacklist.retries)
        print(f"hvdtpurun: world lost (exit {exit_code}) — "
              f"restarting a fresh world ({restarts} restart(s) "
              f"left)", file=sys.stderr)


def _ssh_spawn(host: str, ssh_port: Optional[int], remote_cmd: str,
               env_to_forward: Dict[str, str]) -> subprocess.Popen:
    """ssh-launch a task server on ``host``
    (reference: run/run.py:103-190 _launch_task_servers)."""
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in env_to_forward.items())
    cmd = _ssh_base(ssh_port) + [host, f"{exports} {remote_cmd}"]
    return subprocess.Popen(cmd)


def run_multihost(hosts: List[Tuple[str, int]], command: List[str],
                  ssh_port: Optional[int] = None,
                  env: Optional[Dict[str, str]] = None,
                  start_timeout: float = 60.0,
                  spawn_fn=None, host_check_fn=None,
                  disable_cache: bool = False) -> int:
    """Driver flow: ssh reachability pre-check → start DriverService →
    launch task servers (ssh by default; ``spawn_fn(host_index,
    driver_addr, driver_port, env)`` is injectable for tests) →
    registration → ring probe → rank assignment → launch → collect
    exits (reference: run/run.py:193-264 _driver_fn; pre-check
    run/run.py:44-100)."""
    # Injected check_fns (tests) must never write fabricated results
    # into the real ssh-check cache under real-looking keys.
    use_cache = not disable_cache and host_check_fn is None
    check_hosts_reachable(
        hosts, ssh_port=ssh_port, check_fn=host_check_fn,
        cache=HostCheckCache() if use_cache else None)
    secret = hconfig.env_str("HOROVOD_SECRET_KEY") or \
        _secrets.token_hex(16)
    driver = DriverService(len(hosts), secret=secret.encode())
    driver_addr = local_addresses()[0]

    forward_env = {"HOROVOD_SECRET_KEY": secret}
    if env:
        forward_env.update(env)

    spawned = []
    try:
        for i, (host, _slots) in enumerate(hosts):
            if spawn_fn is not None:
                spawned.append(spawn_fn(i, driver_addr, driver.port,
                                        forward_env))
            else:
                remote = (f"{shlex.quote(sys.executable)} -m "
                          f"horovod_tpu.run.services {i} {driver_addr} "
                          f"{driver.port}")
                spawned.append(_ssh_spawn(host, ssh_port, remote,
                                          forward_env))

        driver.wait_for_registration(timeout=start_timeout)
        driver.ring_probe()
        slots = [s for _, s in hosts]
        assignments = driver.assign_ranks(slots)
        controller = driver.controller_endpoint()
        driver.launch(assignments, command, forward_env, controller)
        codes = driver.wait_for_exit()
        # First nonzero wins: max() would fold a signal death
        # (negative returncode) back to 0 when another host is clean.
        return next((c for c in codes if c != 0), 0)
    finally:
        driver.shutdown()
        for p in spawned:
            if hasattr(p, "poll") and p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        prog="hvdtpurun",
        description="Launch a horovod_tpu training job "
                    "(reference: horovodrun).")
    parser.add_argument("-np", "--num-proc", type=int, required=True,
                        help="total number of training processes")
    parser.add_argument("--elastic", action="store_true",
                        help="supervise instead of kill-on-first-exit: "
                             "dead workers are blacklisted with "
                             "backoff and respawned to rejoin the "
                             "running world (HOROVOD_ELASTIC=1 on "
                             "every rank; docs/fault_tolerance.md)")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic world floor: abort for real "
                             "below this many members (env "
                             "HOROVOD_ELASTIC_MIN_WORLD; default 1)")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic world ceiling for rejoins "
                             "(default: -np)")
    parser.add_argument("--restarts", type=int, default=None,
                        help="elastic only: restart up to this many "
                             "fresh worlds after a total world loss "
                             "(env HOROVOD_TPU_ELASTIC_RESTARTS; "
                             "default 0). Pair with "
                             "HOROVOD_SELFOP_CKPT_DIR so restarts "
                             "resume from the async checkpoints")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host1:slots,host2:slots (default: local)")
    parser.add_argument("-p", "--ssh-port", type=int, default=None)
    parser.add_argument("--start-timeout", type=float, default=None,
                        help="seconds to wait for ranks/hosts to start "
                             "(env HOROVOD_START_TIMEOUT)")
    parser.add_argument("--disable-cache", action="store_true",
                        help="re-probe ssh reachability of every host "
                             "even if a recent check succeeded "
                             "(reference: horovodrun --disable-cache)")
    parser.add_argument("--metrics", action="store_true",
                        help="arm the metrics plane on every rank "
                             "(env HOROVOD_TPU_METRICS; docs/metrics.md)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="rank-0 Prometheus /metrics port (implies "
                             "--metrics; 0 = ephemeral; env "
                             "HOROVOD_TPU_METRICS_PORT)")
    parser.add_argument("--metrics-interval", type=float, default=None,
                        help="seconds between world metric folds (env "
                             "HOROVOD_TPU_METRICS_INTERVAL)")
    parser.add_argument("--metrics-log", default=None,
                        help="rank-0 JSONL snapshot file (implies "
                             "--metrics; env HOROVOD_TPU_METRICS_LOG)")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="arm the world trace plane on every rank "
                             "and write the merged clock-aligned "
                             "Chrome trace to PATH on rank 0 (env "
                             "HOROVOD_TPU_TRACE; docs/tracing.md)")
    parser.add_argument("--trace-interval", type=float, default=None,
                        help="seconds between trace-span shipments "
                             "up the control tree (env "
                             "HOROVOD_TPU_TRACE_INTERVAL)")
    parser.add_argument("--service", action="store_true",
                        help="run the fleet as a long-lived collective "
                             "SERVICE (env HOROVOD_TPU_SERVICE; "
                             "docs/multitenancy.md): rank 0 opens the "
                             "tenant gate so jobs attach/detach and "
                             "pull parameter snapshots without the "
                             "fleet re-rendezvousing. With no "
                             "training command, runs the built-in "
                             "warm host (horovod_tpu.run.service_host)")
    parser.add_argument("--service-port", type=int, default=None,
                        help="fixed port for the rank-0 service gate "
                             "(0 = ephemeral; env "
                             "HOROVOD_TPU_SERVICE_PORT)")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        if args.service:
            # Warm-fleet default: an idle service host per slot that
            # inits the world and serves until terminated.
            command = [sys.executable, "-m",
                       "horovod_tpu.run.service_host"]
        else:
            parser.error("no training command given")

    if args.verbose:
        os.environ.setdefault("HOROVOD_LOG_LEVEL", "debug")
    start_timeout = args.start_timeout or \
        hconfig.env_float("HOROVOD_START_TIMEOUT", 30.0)

    # Metrics-plane knobs, plumbed to every spawned rank (workers read
    # them through Config.from_env; the flags win over inherited env).
    metrics_env: Dict[str, str] = {}
    if args.metrics or args.metrics_port is not None \
            or args.metrics_log is not None:
        metrics_env["HOROVOD_TPU_METRICS"] = "1"
    if args.metrics_port is not None:
        metrics_env["HOROVOD_TPU_METRICS_PORT"] = str(args.metrics_port)
    if args.metrics_interval is not None:
        metrics_env["HOROVOD_TPU_METRICS_INTERVAL"] = \
            str(args.metrics_interval)
    if args.metrics_log is not None:
        metrics_env["HOROVOD_TPU_METRICS_LOG"] = args.metrics_log
    # World trace plane + flight recorder knobs, same plumbing. The
    # trace path must reach EVERY rank (workers collect spans; rank 0
    # writes the merged file).
    if args.trace is not None:
        metrics_env["HOROVOD_TPU_TRACE"] = args.trace
    if args.trace_interval is not None:
        metrics_env["HOROVOD_TPU_TRACE_INTERVAL"] = \
            str(args.trace_interval)
    # Service mode: every rank learns the knob (rank 0 opens the
    # gate); the port pin keeps the attach endpoint stable.
    if args.service or args.service_port is not None:
        metrics_env["HOROVOD_TPU_SERVICE"] = "1"
    if args.service_port is not None:
        metrics_env["HOROVOD_TPU_SERVICE_PORT"] = \
            str(args.service_port)
    # Multihost task servers forward only an explicit env set; carry
    # env-configured metrics/trace/flight knobs across hosts too,
    # not just flags.
    for key in ("HOROVOD_TPU_METRICS", "HOROVOD_TPU_METRICS_PORT",
                "HOROVOD_TPU_METRICS_INTERVAL",
                "HOROVOD_TPU_METRICS_LOG", "HOROVOD_TPU_TRACE",
                "HOROVOD_TPU_TRACE_INTERVAL", "HOROVOD_TPU_FLIGHT",
                "HOROVOD_TPU_FLIGHT_EVENTS",
                "HOROVOD_TPU_FLIGHT_DIR", "HOROVOD_TPU_SERVICE",
                "HOROVOD_TPU_SERVICE_PORT", "HOROVOD_SELFOP",
                "HOROVOD_SELFOP_CKPT_DIR",
                "HOROVOD_SELFOP_CKPT_INTERVAL",
                "HOROVOD_PREEMPT_GRACE", "HOROVOD_PREEMPT_NOTICE"):
        if key in os.environ:
            metrics_env.setdefault(key, os.environ[key])

    if not args.hosts or all(
            h in _local_hosts() for h, _ in parse_hosts(args.hosts)):
        if args.hosts:
            total = sum(s for _, s in parse_hosts(args.hosts))
            if total != args.num_proc:
                parser.error(f"-np {args.num_proc} != total slots {total}")
        if args.elastic:
            sys.exit(run_local_elastic(
                args.num_proc, command, env=metrics_env,
                start_timeout=start_timeout,
                min_np=args.min_np or 1,
                max_np=args.max_np,
                restarts=args.restarts))
        sys.exit(run_local(args.num_proc, command, env=metrics_env,
                           start_timeout=start_timeout))

    if args.elastic:
        parser.error("--elastic currently drives the local launch "
                     "path only; run one elastic launcher per host or "
                     "drop -H (remote supervision is tracked in "
                     "ROADMAP item 1)")
    hosts = parse_hosts(args.hosts)
    total = sum(s for _, s in hosts)
    if total != args.num_proc:
        parser.error(f"-np {args.num_proc} != total slots {total}")
    try:
        sys.exit(run_multihost(hosts, command, ssh_port=args.ssh_port,
                               env=metrics_env,
                               start_timeout=start_timeout,
                               disable_cache=args.disable_cache))
    except RuntimeError as e:
        print(f"hvdtpurun: {e}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
