"""Version of horovod_tpu (reference: horovod/__init__.py:1)."""

__version__ = "0.3.0"
