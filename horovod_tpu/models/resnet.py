"""ResNet v1.5 in flax — the benchmark workhorse.

Fills the role of ``torchvision.models.resnet50`` /
``keras.applications.ResNet50`` in the reference's synthetic benchmarks
(reference: examples/pytorch_synthetic_benchmark.py:28-30,
examples/tensorflow_synthetic_benchmark.py, docs/benchmarks.md:12-27).

TPU notes: NHWC layout (XLA's native conv layout on TPU), bf16 compute
with fp32 BatchNorm statistics, stride-2 in the 3x3 conv of bottleneck
blocks (the "v1.5" variant every modern benchmark measures).
``axis_name`` syncs BatchNorm statistics across the data-parallel mesh
axis when training under shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    axis_name: Optional[str] = None  # sync-BN across this mesh axis

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       axis_name=self.axis_name if train else None)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm,
                                   act=self.act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="head")(x.astype(jnp.float32))
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
