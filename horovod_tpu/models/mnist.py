"""Small MNIST convnet — the e2e example model.

Role-equivalent of the Net in the reference's MNIST examples
(reference: examples/pytorch_mnist.py:42-60,
examples/tensorflow_mnist.py conv_model, examples/keras_mnist.py).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistConvNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
