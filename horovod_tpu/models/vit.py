"""Vision Transformer — second vision family beside ResNet.

No reference analog (the reference ships no models; its vision story is
the ResNet/Inception benchmarks, docs/benchmarks.md). Included because
a TPU-native framework's model zoo should cover the two standard
vision shapes: convolutional (models/resnet.py) and patch-transformer.

TPU notes: bf16 compute with fp32 LayerNorm/softmax-sensitive parts,
patchify as a single strided conv (one big MXU matmul), learned
positional embeddings, mean-pool head (no CLS token — simpler and
equally standard). Works with data parallelism, `fsdp_sharding` (its
generic largest-free-dim rule needs no ViT-specific rules), and
`spmd.zero_optimizer` out of the box.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    embed_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16


class _EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(dtype=cfg.dtype, name=name,
                                       param_dtype=jnp.float32)
        y = ln("ln1")(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=cfg.num_heads, dtype=cfg.dtype,
            name="attn")(y, y)
        x = x + y
        y = ln("ln2")(x)
        y = nn.Dense(cfg.mlp_ratio * cfg.embed_dim, dtype=cfg.dtype,
                     name="up")(y)
        y = nn.gelu(y)
        y = nn.Dense(cfg.embed_dim, dtype=cfg.dtype, name="down")(y)
        return x + y


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        """images: [B, H, W, 3] → logits [B, num_classes] fp32."""
        cfg = self.cfg
        p = cfg.patch_size
        x = nn.Conv(cfg.embed_dim, (p, p), strides=(p, p),
                    padding="VALID", dtype=cfg.dtype,
                    name="patch_embed")(images.astype(cfg.dtype))
        b, h, w, d = x.shape
        x = x.reshape(b, h * w, d)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, h * w, d), jnp.float32)
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = _EncoderBlock(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="ln_f")(x)
        x = jnp.mean(x, axis=1)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


def ViT_S16(**kw):
    return ViT(ViTConfig(embed_dim=384, num_layers=12, num_heads=6,
                         **kw))


def ViT_B16(**kw):
    return ViT(ViTConfig(**kw))
