"""Decoder-only Transformer LM — the flagship long-context model.

The reference has no model code of its own; this model exists so the
framework's parallelism extensions (tensor parallelism, sequence/ring
attention — horovod_tpu.parallel) have a first-class workload, and it
is the model behind ``__graft_entry__.py``.

TPU-first choices:
- bf16 activations/weights with fp32 softmax and layernorm statistics;
- pre-norm blocks, GELU MLP at 4x width (MXU-friendly 128-multiples);
- rotary position embeddings (no learned position table to shard);
- a pluggable ``attention_fn`` so sequence parallelism can substitute
  ring attention (horovod_tpu/parallel/ring_attention.py) without
  touching the module tree;
- no python-level control flow on data — the whole step jits to one
  XLA program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    rope_theta: float = 10000.0
    # attention_fn(q, k, v, causal) -> out; None = local causal attention.
    attention_fn: Optional[Callable] = None
    # Mixture-of-experts: 0 = dense MLP everywhere; E > 0 replaces the
    # MLP of every ``moe_every``-th block with a Switch-style top-1
    # MoE of E experts (expert parallelism: horovod_tpu.parallel
    # shards the leading expert dim over a mesh axis).
    num_experts: int = 0
    moe_every: int = 2
    expert_capacity_factor: float = 1.25
    moe_top_k: int = 1  # 1 = Switch; 2 = GShard-style top-2 gating

    @property
    def embed_dim(self) -> int:
        return self.num_heads * self.head_dim


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary embeddings. x: [B, S, H, D]; positions: [B, S]."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(
        -jnp.log(theta) * jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,S,1,half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def causal_attention(q, k, v, causal: bool = True):
    """Plain fused-softmax causal attention. q,k,v: [B, S, H, D].
    fp32 logits/softmax, bf16 everywhere else."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.float32(d))
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def best_attention(q, k, v, causal: bool = True):
    """Default attention: the pallas flash kernel on TPU (O(S²) logits
    never touch HBM — horovod_tpu/parallel/flash_attention.py), dense
    fused-softmax elsewhere. Both produce identical math."""
    import jax
    if jax.default_backend() in ("tpu", "axon") and causal:
        from horovod_tpu.parallel.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    return causal_attention(q, k, v, causal)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, use_bias=False, dtype=cfg.dtype, name=name)
        q = dense((cfg.num_heads, cfg.head_dim), "q")(x)
        k = dense((cfg.num_heads, cfg.head_dim), "k")(x)
        v = dense((cfg.num_heads, cfg.head_dim), "v")(x)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        attn = cfg.attention_fn or best_attention
        out = attn(q, k, v, True)
        return nn.DenseGeneral(cfg.embed_dim, axis=(-2, -1), use_bias=False,
                               dtype=cfg.dtype, name="o")(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        hidden = cfg.mlp_ratio * cfg.embed_dim
        h = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                        name="down")(h)


class MoEMLP(nn.Module):
    """Switch-style top-1 mixture-of-experts MLP (the public
    GShard / Switch Transformer pattern): fp32 router, one-hot
    dispatch/combine einsums with a fixed per-expert capacity so the
    whole layer is static-shaped and jit-friendly. Expert weights
    carry a leading expert dimension that the sharding rules
    (parallel/sharding.py moe rules) place on a mesh axis — GSPMD then
    inserts the token all-to-alls that an NCCL-based expert-parallel
    implementation would hand-code. The load-balancing auxiliary term
    is sowed under ``intermediates/moe_aux`` (see
    ``moe_aux_loss``)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        E = cfg.num_experts
        if cfg.moe_top_k > E:
            raise ValueError(
                f"moe_top_k={cfg.moe_top_k} exceeds num_experts={E}; "
                f"a token cannot be routed to more experts than exist")
        B, S, D = x.shape
        H = cfg.mlp_ratio * cfg.embed_dim
        # GShard-style token GROUPS (one per batch row): capacity and
        # the dispatch one-hots scale with S, not B*S, keeping the
        # layer's memory linear in the token count.
        C = max(1, int(cfg.expert_capacity_factor * S / E))

        # Router in fp32: softmax over experts must not quantize.
        gate_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                               name="router")(x.astype(jnp.float32))
        probs = jax.nn.softmax(gate_logits, axis=-1)          # (B,S,E)

        # Top-k choice loop (k=1: Switch; k=2: GShard). Each choice
        # masks out the experts already chosen; gates renormalize over
        # the chosen set; capacity positions continue per expert across
        # choices (GShard's choice-major packing: all first choices
        # claim capacity before any second choice).
        left = probs
        onehots, gates = [], []
        for _ in range(max(1, cfg.moe_top_k)):
            idx = jnp.argmax(left, axis=-1)                   # (B,S)
            oh = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # (B,S,E)
            onehots.append(oh)
            gates.append(jnp.sum(probs * oh, axis=-1))        # (B,S)
            left = left * (1.0 - oh)
        if cfg.moe_top_k > 1:
            # GShard renormalizes over the chosen pair; Switch (k=1)
            # keeps the raw router probability as the gate.
            denom = sum(gates) + 1e-9
            gates = [g / denom for g in gates]

        # Load-balance aux over the FIRST choice (the Switch term).
        self.sow("intermediates", "moe_aux",
                 E * jnp.sum(jnp.mean(onehots[0], axis=(0, 1))
                             * jnp.mean(probs, axis=(0, 1))))

        # Per-choice positions within each expert's capacity buffer
        # (per group); overflow tokens are dropped (contribute zero).
        disp = jnp.zeros(x.shape[:2] + (E, C), jnp.float32)   # (B,S,E,C)
        combine = jnp.zeros_like(disp)
        claimed = jnp.zeros(x.shape[:1] + (1, E), jnp.float32)  # (B,1,E)
        for oh, gate in zip(onehots, gates):
            pos = (jnp.cumsum(oh, axis=1) - 1.0 + claimed) * oh
            keep = ((pos >= 0) & (pos < C)).astype(jnp.float32) * oh
            choice_disp = jax.nn.one_hot(
                pos.astype(jnp.int32), C, dtype=jnp.float32) \
                * keep[..., None]
            disp = disp + choice_disp
            combine = combine + choice_disp * gate[..., None, None]
            claimed = claimed + jnp.sum(oh, axis=1, keepdims=True)

        expert_in = jnp.einsum("bsec,bsd->becd",
                               disp.astype(cfg.dtype),
                               x.astype(cfg.dtype))           # (B,E,C,D)
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, D, H), jnp.float32).astype(cfg.dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, H, D), jnp.float32).astype(cfg.dtype)
        h = nn.gelu(jnp.einsum("becd,edh->bech", expert_in, w1))
        expert_out = jnp.einsum("bech,ehd->becd", h, w2)      # (B,E,C,D)

        return jnp.einsum("bsec,becd->bsd", combine.astype(cfg.dtype),
                          expert_out)


def moe_aux_loss(intermediates) -> jnp.ndarray:
    """Sum of the sowed Switch load-balancing terms; add
    ``alpha * moe_aux_loss(...)`` (alpha ~ 0.01) to the task loss when
    training MoE configs (apply with ``mutable=['intermediates']``)."""
    leaves = [v for path, v in
              jax.tree_util.tree_flatten_with_path(intermediates)[0]
              if "moe_aux" in "/".join(str(p) for p in path)]
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        total = total + jnp.sum(jnp.asarray(leaf))
    return total


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(use_bias=False, use_scale=True,
                                       dtype=cfg.dtype, name=name,
                                       param_dtype=jnp.float32)
        x = x + Attention(cfg, name="attn")(ln("ln1")(x), positions)
        if self.use_moe:
            x = x + MoEMLP(cfg, name="moe")(ln("ln2")(x))
        else:
            x = x + MLP(cfg, name="mlp")(ln("ln2")(x))
        return x


class TransformerLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden=False):
        """tokens: [B, S] int32 → logits [B, S, vocab] fp32.

        ``return_hidden=True`` returns the pre-head hidden states
        [B, S, D] (after ln_f, cfg.dtype) instead — the input to
        :func:`lm_loss_from_hidden`'s chunked cross-entropy, which
        avoids ever materializing the full [B, S, vocab] fp32 logits
        (multi-GB at vocab 32k and long context). XLA dead-code
        eliminates the unbuilt head."""
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32)[None],
                tokens.shape)
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype,
                     name="embed")(tokens)
        for i in range(cfg.num_layers):
            use_moe = (cfg.num_experts > 0
                       and i % cfg.moe_every == cfg.moe_every - 1)
            x = Block(cfg, use_moe=use_moe, name=f"block_{i}")(
                x, positions)
        x = nn.LayerNorm(use_bias=False, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        if return_hidden:
            return x
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=jnp.float32, name="lm_head")(
                              x.astype(jnp.float32))
        return logits


def lm_loss(logits, tokens):
    """Next-token cross-entropy, mean over all predicted positions."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def lm_loss_from_hidden(hidden, head_kernel, tokens, chunk: int = 1024):
    """Chunked next-token cross-entropy from pre-head hidden states.

    Identical math to ``lm_loss(model(tokens), tokens)`` but the
    [B, S, vocab] fp32 logits are never materialized: the head matmul
    + log-softmax run per sequence chunk inside a rematerialized scan,
    so peak logits memory is B × chunk × vocab in both forward and
    backward (the backward recomputes each chunk's logits). At vocab
    32k, seq 4096, batch 8 this turns 2 × 3.9 GB of fp32 logits
    buffers into 2 × ~1 GB at chunk=1024 (scaling linearly in chunk).

    hidden: [B, S, D] as returned by ``model(tokens,
    return_hidden=True)``; head_kernel: the lm_head kernel
    ``params["lm_head"]["kernel"]`` [D, vocab] fp32.
    """
    targets = tokens[:, 1:]
    hid = hidden[:, :-1]
    b, s, d = hid.shape
    chunk = min(chunk, s)
    pad = (-s) % chunk
    mask = jnp.ones((b, s), jnp.float32)
    if pad:
        hid = jnp.pad(hid, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (s + pad) // chunk
    hid = hid.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    targets = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    mask = mask.reshape(b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_ll(h, t, m):
        logits = h.astype(jnp.float32) @ head_kernel
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        return jnp.sum(ll * m)

    def body(carry, xs):
        h, t, m = xs
        return carry + chunk_ll(h, t, m), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0),
                            (hid, targets, mask))
    return -total / (b * s)
