"""Model zoo for benchmarks and examples.

The reference ships no model library — its examples lean on framework
zoos (`torchvision.models.resnet50`, `keras.applications.ResNet50`,
reference: examples/pytorch_synthetic_benchmark.py:28-30,
examples/keras_imagenet_resnet50.py). A TPU-native framework has no
such zoo to lean on, so the models the reference's examples and
benchmarks require are provided here in flax, bf16-friendly and
MXU-shaped.
"""

from horovod_tpu.models.resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101
from horovod_tpu.models.transformer import TransformerConfig, TransformerLM
from horovod_tpu.models.mnist import MnistConvNet
from horovod_tpu.models.vit import ViT, ViTConfig, ViT_S16, ViT_B16

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
    "TransformerConfig", "TransformerLM", "MnistConvNet",
    "ViT", "ViTConfig", "ViT_S16", "ViT_B16",
]
