"""Collective-path benchmarks on a multi-process CPU world.

What the reference publishes as its value proposition is collective
efficiency (docs/benchmarks.md; README.md:66-70 scaling efficiency).
This bench measures THIS framework's full control+data path — enqueue →
negotiate (TCP controller) → fuse → execute → callback — with no
shortcuts, across its three host data planes:

  * ``shm``   — shared-memory segment, the default for same-host worlds
                (the TPU deployment shape: one process per chip);
  * ``star``  — TCP socket gather→sum@0→broadcast, the universal
                fallback (reference analog: MPI CPU ops);
  * ``ring``  — 2-phase TCP ring for large payloads on multi-host
                worlds (reference analog: MPI's internal ring
                algorithms inside MPI_Allreduce).

Timings are **medians** over ALLREDUCE_ITERS ops (p25/p75 recorded):
this host is a 1-vCPU VM with bursty external interference, and means
are dominated by the bad windows.

IMPORTANT CONTEXT FOR THE SCALING NUMBERS: with ``os.cpu_count() == 1``
an np=8 world time-shares one core, so the classic efficiency metric
steps_N / steps_1 is bounded above by cores/np (12.5% at np=8) for any
framework, with zero communication cost — 8x the compute now shares
one core. RESULTS_cpu.json therefore reports, alongside the raw
number:

  * ``timeshare_ideal`` = min(cores, np)/np — the ceiling the metric
    has on this machine;
  * ``efficiency_vs_achievable`` = raw / ideal — how close the
    framework gets to that ceiling (this is the number comparable to
    the reference's published 90%, which was measured with one GPU
    per rank, i.e. compute actually parallel);
  * a ``fixed_compute`` scenario where the per-step compute is a
    sleep (parallelizable even on one core, like real accelerator
    compute) and only the gradient exchange costs CPU — isolating the
    framework's communication overhead the way a real cluster would.

Run with no arguments to orchestrate everything (spawns the worlds,
writes benchmarks/RESULTS_cpu.json):

    python benchmarks/collective_bench.py [--np 8]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALLREDUCE_SIZES = [4 << 10, 64 << 10, 1 << 20, 4 << 20, 16 << 20]
FUSED_COUNT, FUSED_BYTES = 32, 128 << 10
ALLREDUCE_ITERS = 21
TRAIN_STEPS = 30
FIXED_COMPUTE_S = 0.100  # simulated per-step compute (parallelizable)

VARIANTS = {
    # name -> extra env for the world
    "shm": {},
    "star": {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1"},
    "ring": {"HOROVOD_TPU_SHM": "0",
             "HOROVOD_TPU_RING_THRESHOLD": "32768"},
}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _quantiles(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 4], xs[n // 2], xs[(3 * n) // 4]


# ---------------------------------------------------------------------------
# worker halves (run in subprocesses)
# ---------------------------------------------------------------------------

def worker_allreduce(rank: int, size: int) -> None:
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    results = []
    for nbytes in ALLREDUCE_SIZES:
        n = nbytes // 4
        x = np.full((n,), float(rank + 1), np.float32)
        for i in range(3):
            hvd.allreduce(x, average=False, name=f"warm.{nbytes}.{i}")
        hvd.barrier(name=f"bar.{nbytes}")
        times = []
        for i in range(ALLREDUCE_ITERS):
            t0 = time.perf_counter()
            out = hvd.allreduce(x, average=False,
                                name=f"ar.{nbytes}.{i}")
            times.append(time.perf_counter() - t0)
        assert abs(float(out[0]) - sum(range(1, size + 1))) < 1e-4
        p25, med, p75 = _quantiles(times)
        algbw = nbytes / med
        results.append({
            "bytes": nbytes,
            "us_per_op": round(med * 1e6, 1),
            "us_p25": round(p25 * 1e6, 1),
            "us_p75": round(p75 * 1e6, 1),
            "algbw_MBps": round(algbw / 1e6, 2),
            # ring-equivalent bus bandwidth (nccl-tests convention)
            "busbw_MBps": round(algbw * 2 * (size - 1) / size / 1e6, 2),
        })

    # fused batch: FUSED_COUNT tensors submitted together ride one
    # negotiated cycle / fused response
    xs = [np.full((FUSED_BYTES // 4,), float(rank + 1), np.float32)
          for _ in range(FUSED_COUNT)]
    for rep in range(2):
        handles = [hvd.allreduce_async(x, average=False,
                                       name=f"fw.{rep}.{i}")
                   for i, x in enumerate(xs)]
        for h in handles:
            hvd.synchronize(h)
    hvd.barrier(name="bar.fused")
    times = []
    for rep in range(ALLREDUCE_ITERS):
        t0 = time.perf_counter()
        handles = [hvd.allreduce_async(x, average=False,
                                       name=f"f.{rep}.{i}")
                   for i, x in enumerate(xs)]
        for h in handles:
            hvd.synchronize(h)
        times.append(time.perf_counter() - t0)
    total = FUSED_COUNT * FUSED_BYTES
    _, med, _ = _quantiles(times)
    fused = {
        "bytes": total, "tensors": FUSED_COUNT,
        "us_per_batch": round(med * 1e6, 1),
        "algbw_MBps": round(total / med / 1e6, 2),
        "busbw_MBps": round(
            total / med * 2 * (size - 1) / size / 1e6, 2),
    }
    if rank == 0:
        print("RESULT " + json.dumps(
            {"allreduce": results, "fused": fused}), flush=True)
    hvd.shutdown()


def worker_train(rank: int, size: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import horovod_tpu.jax as hvd

    hvd.init()
    rng = np.random.RandomState(42)  # same data shape on every rank
    w_sizes = [(256, 512), (512, 512), (512, 256)]
    params = [jnp.asarray(rng.randn(*s) * 0.01, jnp.float32)
              for s in w_sizes]
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = optax.sgd(0.01)
    opt_state = tx.init(params)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)

    @jax.jit
    def loss_grads(params, x):
        def loss_fn(ps):
            h = x
            for w in ps:
                h = jnp.tanh(h @ w)
            return (h ** 2).mean()
        return jax.value_and_grad(loss_fn)(params)

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def step(params, opt_state):
        loss, grads = loss_grads(params, x)
        # the framework's out-of-jit gradient path: enqueue every leaf,
        # negotiate, fuse, execute, synchronize
        grads = hvd.allreduce_gradients(grads)
        params, opt_state = apply(params, opt_state, grads)
        return params, opt_state, loss

    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
    float(loss)
    hvd.barrier(name="bar.train")
    times = []
    for _ in range(TRAIN_STEPS):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state)
        float(loss)
        times.append(time.perf_counter() - t0)
    _, med, _ = _quantiles(times)
    if rank == 0:
        print("RESULT " + json.dumps(
            {"steps_per_sec": round(1.0 / med, 2)}), flush=True)
    hvd.shutdown()


def worker_fixed_compute(rank: int, size: int) -> None:
    """Per-step compute is a sleep — parallelizable across ranks even on
    one core, like real accelerator compute — so the measured slowdown
    vs np=1 is purely the framework's communication overhead."""
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    grads = [np.full((256, 512), 0.1 * (rank + 1), np.float32),
             np.full((512, 512), 0.2 * (rank + 1), np.float32),
             np.full((512, 256), 0.3 * (rank + 1), np.float32)]

    def step(i):
        time.sleep(FIXED_COMPUTE_S)
        handles = [hvd.allreduce_async(g, average=True,
                                       name=f"fc.{i}.{j}")
                   for j, g in enumerate(grads)]
        for h in handles:
            hvd.synchronize(h)

    for i in range(3):
        step(-1 - i)
    hvd.barrier(name="bar.fc")
    times = []
    for i in range(TRAIN_STEPS):
        t0 = time.perf_counter()
        step(i)
        times.append(time.perf_counter() - t0)
    _, med, _ = _quantiles(times)
    if rank == 0:
        print("RESULT " + json.dumps(
            {"steps_per_sec": round(1.0 / med, 2)}), flush=True)
    hvd.shutdown()


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _run_world(mode: str, size: int, timeout: float = 600.0,
               extra_env=None) -> dict:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # The TPU plugin's sitecustomize (gated on this knob) overrides
    # jax_platforms to "axon,cpu" at interpreter start — workers would
    # silently compute on the tunneled TPU with ~100 ms round trips.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["HOROVOD_CONTROLLER_ADDR"] = "127.0.0.1"
    env["HOROVOD_CONTROLLER_PORT"] = str(port)
    env["HOROVOD_SIZE"] = str(size)
    env.setdefault("HOROVOD_CYCLE_TIME", "1")
    if extra_env:
        env.update(extra_env)
    procs = []
    for rank in range(size):
        e = dict(env)
        e["HOROVOD_RANK"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", mode, "--rank", str(rank), "--size", str(size)],
            cwd=REPO, env=e, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise RuntimeError(f"{mode} np={size} rank {rank} timed out")
        outs.append(out.decode())
        if p.returncode != 0:
            raise RuntimeError(
                f"{mode} np={size} rank {rank} exited {p.returncode}:\n"
                + outs[-1])
    for line in outs[0].splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"no RESULT line from rank 0:\n{outs[0]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=8)
    ap.add_argument("--worker",
                    choices=["allreduce", "train", "fixed_compute"])
    ap.add_argument("--rank", type=int)
    ap.add_argument("--size", type=int)
    ap.add_argument("--skip-variants", action="store_true",
                    help="only bench the default (shm) data plane")
    args = ap.parse_args()

    if args.worker:
        {"allreduce": worker_allreduce,
         "train": worker_train,
         "fixed_compute": worker_fixed_compute}[args.worker](
             args.rank, args.size)
        return

    np_ = args.np
    cores = os.cpu_count() or 1

    sweeps = {}
    variant_names = ["shm"] if args.skip_variants else list(VARIANTS)
    for variant in variant_names:
        print(f"== allreduce medians (np={np_}, data plane: {variant}) "
              f"==", flush=True)
        coll = _run_world("allreduce", np_, extra_env=VARIANTS[variant])
        for row in coll["allreduce"]:
            print(f"  {row['bytes']:>9} B  {row['us_per_op']:>10} us  "
                  f"(p25 {row['us_p25']:>9} / p75 {row['us_p75']:>9})  "
                  f"bus {row['busbw_MBps']:>8} MB/s", flush=True)
        f = coll["fused"]
        print(f"  fused {f['tensors']}x{f['bytes'] // f['tensors']} B  "
              f"{f['us_per_batch']} us/batch  bus {f['busbw_MBps']} MB/s")
        sweeps[variant] = coll

    def _median_world(mode, size, runs=3):
        """Whole-world repeats: a single world can land entirely inside
        one of this host's multi-second stall windows (see module
        docstring), so the scaling legs take the median of three."""
        vals = [_run_world(mode, size)["steps_per_sec"]
                for _ in range(runs)]
        return {"steps_per_sec": sorted(vals)[len(vals) // 2],
                "runs": vals}

    print(f"== scaling (data-parallel MLP, real compute on "
          f"{cores} core(s)) ==", flush=True)
    t1 = _median_world("train", 1)
    tn = _median_world("train", np_)
    eff = tn["steps_per_sec"] / t1["steps_per_sec"]
    ideal = min(cores, np_) / np_
    print(f"  np=1: {t1['steps_per_sec']} steps/s   "
          f"np={np_}: {tn['steps_per_sec']} steps/s   "
          f"raw efficiency {eff:.1%}   "
          f"(ceiling on this host: {ideal:.1%} — compute time-shares "
          f"{cores} core(s); vs-achievable {min(eff / ideal, 1.0):.1%})",
          flush=True)

    print(f"== scaling (fixed {FIXED_COMPUTE_S * 1e3:.0f} ms compute — "
          f"parallelizable, isolates comm overhead) ==", flush=True)
    f1 = _median_world("fixed_compute", 1)
    fn = _median_world("fixed_compute", np_)
    fc_eff = fn["steps_per_sec"] / f1["steps_per_sec"]
    print(f"  np=1: {f1['steps_per_sec']} steps/s   "
          f"np={np_}: {fn['steps_per_sec']} steps/s   "
          f"efficiency {fc_eff:.1%}", flush=True)

    out = {
        "world_size": np_,
        "cpu_count": cores,
        "allreduce": sweeps["shm"]["allreduce"],
        "fused": sweeps["shm"]["fused"],
        "allreduce_variants": {
            v: sweeps[v]["allreduce"] for v in sweeps},
        "train_steps_per_sec": {"1": t1["steps_per_sec"],
                                str(np_): tn["steps_per_sec"]},
        "scaling_efficiency": round(eff, 4),
        "timeshare_ideal": round(ideal, 4),
        "efficiency_vs_achievable": round(min(eff / ideal, 1.0), 4),
        "fixed_compute_ms": FIXED_COMPUTE_S * 1e3,
        "fixed_compute_steps_per_sec": {
            "1": f1["steps_per_sec"], str(np_): fn["steps_per_sec"]},
        "fixed_compute_scaling_efficiency": round(fc_eff, 4),
        "note": (
            "cpu_count==1 hosts time-share all ranks' compute on one "
            "core, capping steps_N/steps_1 at timeshare_ideal for ANY "
            "framework; fixed_compute_scaling_efficiency isolates the "
            "framework's communication overhead with parallelizable "
            "compute, and is the number comparable to the reference's "
            "published scaling efficiencies (one GPU per rank). The "
            "host additionally burst-throttles sustained CPU/memory "
            "load after ~1-2 s, which hits the 16 MiB shm/star legs "
            "specifically, so those rows vary several-fold between runs "
            "(e.g. shm 16 MiB medians of ~160-650 ms across "
            "sweeps); the ring's lower CPU intensity makes its "
            "16 MiB row the most stable, ~230-290 ms across runs."),
    }
    path = os.path.join(REPO, "benchmarks", "RESULTS_cpu.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
