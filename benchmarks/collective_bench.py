"""Collective-path microbenchmarks on a multi-process CPU world.

What the reference publishes as its value proposition is collective
efficiency (docs/benchmarks.md; README.md:66-70 scaling efficiency).
This bench measures THIS framework's full control+data path — enqueue →
negotiate (TCP controller) → fuse → execute (socket backend) →
callback — with no shortcuts:

1. **allreduce bus bandwidth vs message size**: per-op wall time and
   algorithm/bus bandwidth for single-tensor allreduces from 4 KiB to
   16 MiB, plus a fused-batch point (32 x 128 KiB in one cycle —
   exercising tensor fusion).
2. **scaling efficiency**: steps/sec of a synthetic data-parallel
   train step (MLP on CPU jax, gradients averaged through the
   framework) at world size 1 vs N; efficiency = steps_N / steps_1
   (global throughput per chip vs ideal).

Run with no arguments to orchestrate everything (spawns the worlds,
writes benchmarks/RESULTS_cpu.json):

    python benchmarks/collective_bench.py [--np 8]

The numbers stand in for BASELINE.json's multi-chip north star in this
single-chip environment: the control-plane + fusion overheads measured
here are exactly what bounds scaling efficiency on real pods.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALLREDUCE_SIZES = [4 << 10, 64 << 10, 1 << 20, 4 << 20, 16 << 20]
FUSED_COUNT, FUSED_BYTES = 32, 128 << 10
ALLREDUCE_ITERS = 20
TRAIN_STEPS = 30


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# worker halves (run in subprocesses)
# ---------------------------------------------------------------------------

def worker_allreduce(rank: int, size: int) -> None:
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    results = []
    for nbytes in ALLREDUCE_SIZES:
        n = nbytes // 4
        x = np.full((n,), float(rank + 1), np.float32)
        for i in range(3):
            hvd.allreduce(x, average=False, name=f"warm.{nbytes}.{i}")
        hvd.barrier(name=f"bar.{nbytes}")
        t0 = time.perf_counter()
        for i in range(ALLREDUCE_ITERS):
            out = hvd.allreduce(x, average=False,
                                name=f"ar.{nbytes}.{i}")
        dt = time.perf_counter() - t0
        assert abs(float(out[0]) - sum(range(1, size + 1))) < 1e-4
        per_op = dt / ALLREDUCE_ITERS
        algbw = nbytes / per_op
        results.append({
            "bytes": nbytes,
            "us_per_op": round(per_op * 1e6, 1),
            "algbw_MBps": round(algbw / 1e6, 2),
            # ring-equivalent bus bandwidth (nccl-tests convention)
            "busbw_MBps": round(algbw * 2 * (size - 1) / size / 1e6, 2),
        })

    # fused batch: FUSED_COUNT tensors submitted together ride one
    # negotiated cycle / fused response
    xs = [np.full((FUSED_BYTES // 4,), float(rank + 1), np.float32)
          for _ in range(FUSED_COUNT)]
    for rep in range(2):
        handles = [hvd.allreduce_async(x, average=False,
                                       name=f"fw.{rep}.{i}")
                   for i, x in enumerate(xs)]
        for h in handles:
            hvd.synchronize(h)
    hvd.barrier(name="bar.fused")
    t0 = time.perf_counter()
    for rep in range(ALLREDUCE_ITERS):
        handles = [hvd.allreduce_async(x, average=False,
                                       name=f"f.{rep}.{i}")
                   for i, x in enumerate(xs)]
        for h in handles:
            hvd.synchronize(h)
    dt = time.perf_counter() - t0
    total = FUSED_COUNT * FUSED_BYTES
    per_op = dt / ALLREDUCE_ITERS
    fused = {
        "bytes": total, "tensors": FUSED_COUNT,
        "us_per_batch": round(per_op * 1e6, 1),
        "algbw_MBps": round(total / per_op / 1e6, 2),
        "busbw_MBps": round(
            total / per_op * 2 * (size - 1) / size / 1e6, 2),
    }
    if rank == 0:
        print("RESULT " + json.dumps(
            {"allreduce": results, "fused": fused}), flush=True)
    hvd.shutdown()


def worker_train(rank: int, size: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import horovod_tpu.jax as hvd

    hvd.init()
    rng = np.random.RandomState(42)  # same data shape on every rank
    w_sizes = [(256, 512), (512, 512), (512, 256)]
    params = [jnp.asarray(rng.randn(*s) * 0.01, jnp.float32)
              for s in w_sizes]
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = optax.sgd(0.01)
    opt_state = tx.init(params)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)

    @jax.jit
    def loss_grads(params, x):
        def loss_fn(ps):
            h = x
            for w in ps:
                h = jnp.tanh(h @ w)
            return (h ** 2).mean()
        return jax.value_and_grad(loss_fn)(params)

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def step(params, opt_state):
        loss, grads = loss_grads(params, x)
        # the framework's out-of-jit gradient path: enqueue every leaf,
        # negotiate, fuse, execute, synchronize
        grads = hvd.allreduce_gradients(grads)
        params, opt_state = apply(params, opt_state, grads)
        return params, opt_state, loss

    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
    float(loss)
    hvd.barrier(name="bar.train")
    t0 = time.perf_counter()
    for _ in range(TRAIN_STEPS):
        params, opt_state, loss = step(params, opt_state)
    float(loss)
    dt = time.perf_counter() - t0
    if rank == 0:
        print("RESULT " + json.dumps(
            {"steps_per_sec": round(TRAIN_STEPS / dt, 2)}), flush=True)
    hvd.shutdown()


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _run_world(mode: str, size: int, timeout: float = 300.0) -> dict:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # The TPU plugin's sitecustomize (gated on this knob) overrides
    # jax_platforms to "axon,cpu" at interpreter start — workers would
    # silently compute on the tunneled TPU with ~100 ms round trips.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["HOROVOD_CONTROLLER_ADDR"] = "127.0.0.1"
    env["HOROVOD_CONTROLLER_PORT"] = str(port)
    env["HOROVOD_SIZE"] = str(size)
    env.setdefault("HOROVOD_CYCLE_TIME", "1")
    procs = []
    for rank in range(size):
        e = dict(env)
        e["HOROVOD_RANK"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", mode, "--rank", str(rank), "--size", str(size)],
            cwd=REPO, env=e, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise RuntimeError(f"{mode} np={size} rank {rank} timed out")
        outs.append(out.decode())
        if p.returncode != 0:
            raise RuntimeError(
                f"{mode} np={size} rank {rank} exited {p.returncode}:\n"
                + outs[-1])
    for line in outs[0].splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"no RESULT line from rank 0:\n{outs[0]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=8)
    ap.add_argument("--worker", choices=["allreduce", "train"])
    ap.add_argument("--rank", type=int)
    ap.add_argument("--size", type=int)
    args = ap.parse_args()

    if args.worker:
        {"allreduce": worker_allreduce,
         "train": worker_train}[args.worker](args.rank, args.size)
        return

    np_ = args.np
    print(f"== allreduce bus bandwidth (np={np_}, socket backend, "
          f"full negotiate->fuse->execute) ==", flush=True)
    coll = _run_world("allreduce", np_)
    for row in coll["allreduce"]:
        print(f"  {row['bytes']:>9} B  {row['us_per_op']:>9} us  "
              f"alg {row['algbw_MBps']:>8} MB/s  "
              f"bus {row['busbw_MBps']:>8} MB/s")
    f = coll["fused"]
    print(f"  fused {f['tensors']}x{f['bytes'] // f['tensors']} B  "
          f"{f['us_per_batch']} us/batch  bus {f['busbw_MBps']} MB/s")

    print(f"== scaling efficiency (data-parallel MLP, out-of-jit "
          f"gradient path) ==", flush=True)
    t1 = _run_world("train", 1)
    tn = _run_world("train", np_)
    eff = tn["steps_per_sec"] / t1["steps_per_sec"]
    print(f"  np=1: {t1['steps_per_sec']} steps/s   "
          f"np={np_}: {tn['steps_per_sec']} steps/s   "
          f"efficiency {eff:.1%}")

    out = {
        "world_size": np_,
        "allreduce": coll["allreduce"],
        "fused": coll["fused"],
        "train_steps_per_sec": {"1": t1["steps_per_sec"],
                                str(np_): tn["steps_per_sec"]},
        "scaling_efficiency": round(eff, 4),
    }
    path = os.path.join(REPO, "benchmarks", "RESULTS_cpu.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
