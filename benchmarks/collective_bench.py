"""Collective-path benchmarks on a multi-process CPU world.

What the reference publishes as its value proposition is collective
efficiency (docs/benchmarks.md; README.md:66-70 scaling efficiency).
This bench measures THIS framework's full control+data path — enqueue →
negotiate (TCP controller) → fuse → execute → callback — with no
shortcuts, across its three host data planes:

  * ``shm``   — shared-memory segment, the default for same-host worlds
                (the TPU deployment shape: one process per chip);
  * ``star``  — TCP socket gather→sum@0→broadcast, the universal
                fallback (reference analog: MPI CPU ops);
  * ``ring``  — 2-phase TCP ring for large payloads on multi-host
                worlds (reference analog: MPI's internal ring
                algorithms inside MPI_Allreduce).

Timings are **medians** over ALLREDUCE_ITERS ops (p25/p75 recorded):
this host is a 1-vCPU VM with bursty external interference, and means
are dominated by the bad windows.

IMPORTANT CONTEXT FOR THE SCALING NUMBERS: with ``os.cpu_count() == 1``
an np=8 world time-shares one core, so the classic efficiency metric
steps_N / steps_1 is bounded above by cores/np (12.5% at np=8) for any
framework, with zero communication cost — 8x the compute now shares
one core. RESULTS_cpu.json therefore reports, alongside the raw
number:

  * ``timeshare_ideal`` = min(cores, np)/np — the ceiling the metric
    has on this machine;
  * ``efficiency_vs_achievable`` = raw / ideal — how close the
    framework gets to that ceiling (this is the number comparable to
    the reference's published 90%, which was measured with one GPU
    per rank, i.e. compute actually parallel);
  * a ``fixed_compute`` scenario where the per-step compute is a
    sleep (parallelizable even on one core, like real accelerator
    compute) and only the gradient exchange costs CPU — isolating the
    framework's communication overhead the way a real cluster would.

Run with no arguments to orchestrate everything (spawns the worlds,
writes benchmarks/RESULTS_cpu.json):

    python benchmarks/collective_bench.py [--np 8]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALLREDUCE_SIZES = [4 << 10, 64 << 10, 1 << 20, 4 << 20, 16 << 20]
FUSED_COUNT, FUSED_BYTES = 32, 128 << 10
ALLREDUCE_ITERS = 21
TRAIN_STEPS = 30
FIXED_COMPUTE_S = 0.100  # simulated per-step compute (parallelizable)

VARIANTS = {
    # name -> extra env for the world
    "shm": {},
    "star": {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1"},
    "ring": {"HOROVOD_TPU_SHM": "0",
             "HOROVOD_TPU_RING_THRESHOLD": "32768"},
}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _quantiles(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 4], xs[n // 2], xs[(3 * n) // 4]


# ---------------------------------------------------------------------------
# worker halves (run in subprocesses)
# ---------------------------------------------------------------------------

def worker_allreduce(rank: int, size: int) -> None:
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    results = []
    for nbytes in ALLREDUCE_SIZES:
        n = nbytes // 4
        x = np.full((n,), float(rank + 1), np.float32)
        for i in range(3):
            hvd.allreduce(x, average=False, name=f"warm.{nbytes}.{i}")
        hvd.barrier(name=f"bar.{nbytes}")
        times = []
        for i in range(ALLREDUCE_ITERS):
            t0 = time.perf_counter()
            out = hvd.allreduce(x, average=False,
                                name=f"ar.{nbytes}.{i}")
            times.append(time.perf_counter() - t0)
        assert abs(float(out[0]) - sum(range(1, size + 1))) < 1e-4
        p25, med, p75 = _quantiles(times)
        algbw = nbytes / med
        results.append({
            "bytes": nbytes,
            "us_per_op": round(med * 1e6, 1),
            "us_p25": round(p25 * 1e6, 1),
            "us_p75": round(p75 * 1e6, 1),
            "algbw_MBps": round(algbw / 1e6, 2),
            # ring-equivalent bus bandwidth (nccl-tests convention)
            "busbw_MBps": round(algbw * 2 * (size - 1) / size / 1e6, 2),
        })

    # fused batch: FUSED_COUNT tensors submitted together ride one
    # negotiated cycle / fused response
    xs = [np.full((FUSED_BYTES // 4,), float(rank + 1), np.float32)
          for _ in range(FUSED_COUNT)]
    for rep in range(2):
        handles = [hvd.allreduce_async(x, average=False,
                                       name=f"fw.{rep}.{i}")
                   for i, x in enumerate(xs)]
        for h in handles:
            hvd.synchronize(h)
    hvd.barrier(name="bar.fused")
    times = []
    for rep in range(ALLREDUCE_ITERS):
        t0 = time.perf_counter()
        handles = [hvd.allreduce_async(x, average=False,
                                       name=f"f.{rep}.{i}")
                   for i, x in enumerate(xs)]
        for h in handles:
            hvd.synchronize(h)
        times.append(time.perf_counter() - t0)
    total = FUSED_COUNT * FUSED_BYTES
    _, med, _ = _quantiles(times)
    fused = {
        "bytes": total, "tensors": FUSED_COUNT,
        "us_per_batch": round(med * 1e6, 1),
        "algbw_MBps": round(total / med / 1e6, 2),
        "busbw_MBps": round(
            total / med * 2 * (size - 1) / size / 1e6, 2),
    }
    if rank == 0:
        print("RESULT " + json.dumps(
            {"allreduce": results, "fused": fused}), flush=True)
    hvd.shutdown()


def worker_train(rank: int, size: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import horovod_tpu.jax as hvd

    hvd.init()
    rng = np.random.RandomState(42)  # same data shape on every rank
    w_sizes = [(256, 512), (512, 512), (512, 256)]
    params = [jnp.asarray(rng.randn(*s) * 0.01, jnp.float32)
              for s in w_sizes]
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = optax.sgd(0.01)
    opt_state = tx.init(params)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)

    @jax.jit
    def loss_grads(params, x):
        def loss_fn(ps):
            h = x
            for w in ps:
                h = jnp.tanh(h @ w)
            return (h ** 2).mean()
        return jax.value_and_grad(loss_fn)(params)

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def step(params, opt_state):
        loss, grads = loss_grads(params, x)
        # the framework's out-of-jit gradient path: enqueue every leaf,
        # negotiate, fuse, execute, synchronize
        grads = hvd.allreduce_gradients(grads)
        params, opt_state = apply(params, opt_state, grads)
        return params, opt_state, loss

    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
    float(loss)
    hvd.barrier(name="bar.train")
    times = []
    for _ in range(TRAIN_STEPS):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state)
        float(loss)
        times.append(time.perf_counter() - t0)
    _, med, _ = _quantiles(times)
    if rank == 0:
        print("RESULT " + json.dumps(
            {"steps_per_sec": round(1.0 / med, 2)}), flush=True)
    hvd.shutdown()


def worker_fixed_compute(rank: int, size: int) -> None:
    """Per-step compute is a sleep — parallelizable across ranks even on
    one core, like real accelerator compute — so the measured slowdown
    vs np=1 is purely the framework's communication overhead."""
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    grads = [np.full((256, 512), 0.1 * (rank + 1), np.float32),
             np.full((512, 512), 0.2 * (rank + 1), np.float32),
             np.full((512, 256), 0.3 * (rank + 1), np.float32)]

    def step(i):
        time.sleep(FIXED_COMPUTE_S)
        handles = [hvd.allreduce_async(g, average=True,
                                       name=f"fc.{i}.{j}")
                   for j, g in enumerate(grads)]
        for h in handles:
            hvd.synchronize(h)

    for i in range(3):
        step(-1 - i)
    hvd.barrier(name="bar.fc")
    times = []
    for i in range(TRAIN_STEPS):
        t0 = time.perf_counter()
        step(i)
        times.append(time.perf_counter() - t0)
    _, med, _ = _quantiles(times)
    if rank == 0:
        print("RESULT " + json.dumps(
            {"steps_per_sec": round(1.0 / med, 2)}), flush=True)
    hvd.shutdown()


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def worker_overhead(rank: int, size: int) -> None:
    """Isolate the per-step control-plane cost: a BARRIER is a pure
    negotiate+dispatch round (no payload), and a 4 KiB allreduce adds
    only a trivial payload — their medians are the framework overhead a
    training step pays on top of compute, the quantity that bounds
    pod-scale efficiency (the data-plane bytes ride ICI on real
    hardware and overlap with backward)."""
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    for i in range(5):
        hvd.barrier(name=f"warm.{i}")
    ts_bar = []
    for i in range(ALLREDUCE_ITERS * 2):
        t0 = time.perf_counter()
        hvd.barrier(name=f"ov.bar.{i}")
        ts_bar.append(time.perf_counter() - t0)
    x = np.full((1024,), float(rank + 1), np.float32)
    ts_small = []
    for i in range(ALLREDUCE_ITERS * 2):
        t0 = time.perf_counter()
        out = hvd.allreduce(x, average=False, name=f"ov.ar.{i}")
        ts_small.append(time.perf_counter() - t0)
    assert abs(float(out[0]) - sum(range(1, size + 1))) < 1e-4
    _, bar_med, _ = _quantiles(ts_bar)
    _, small_med, _ = _quantiles(ts_small)
    if rank == 0:
        print("RESULT " + json.dumps({
            "barrier_us": round(bar_med * 1e6, 1),
            "small_allreduce_us": round(small_med * 1e6, 1),
        }), flush=True)
    hvd.shutdown()


ELASTIC_BENCH_STEPS = 400      # total steady allreduce steps
ELASTIC_BENCH_KILL_OP = 150    # victim's SIGKILL lands mid-run


def worker_elastic(rank: int, size: int) -> None:
    """Elastic recovery section: a steady single-tensor loop at ws=N;
    the highest rank is SIGKILLed mid-run by fault injection
    (HOROVOD_FAULT_SPEC, set by the section driver) and the survivors
    re-rendezvous into ws=N-1 and finish. The surviving rank 0 reports
    steady-state us/op BEFORE the kill, the re-rendezvous GAP (the one
    step interval that contains detection + barrier + re-init +
    resync), and us/op AFTER the shrink — the recovery-time budget is
    asserted against 2x the heartbeat timeout by the driver."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import config as hconfig
    from horovod_tpu.common import elastic

    hvd.init()
    launch_rank = hconfig.env_int("HOROVOD_RANK", rank)
    x = np.full(16384, float(rank + 1), np.float32)  # 64 KiB payload
    state = elastic.State(batch=0)
    stamps = []  # (t_after_step, world_size)

    @elastic.run
    def train(state):
        while state.batch < ELASTIC_BENCH_STEPS:
            hvd.allreduce(x, average=False, name="el.bench")
            state.batch += 1
            state.commit()
            stamps.append((time.monotonic(), hvd.size()))

    train(state)
    if launch_rank != 0:
        hvd.shutdown()
        return
    pre, post, gap = [], [], None
    for (t0, ws0), (t1, ws1) in zip(stamps, stamps[1:]):
        dt = t1 - t0
        if ws0 == size and ws1 == size:
            pre.append(dt)
        elif ws0 == size - 1 and ws1 == size - 1:
            post.append(dt)
        else:
            gap = dt  # the transition step: detection + re-rendezvous
    ctx = elastic.context()
    _, pre_med, _ = _quantiles(pre)
    _, post_med, _ = _quantiles(post)
    print("RESULT " + json.dumps({
        "world": size,
        "steps": ELASTIC_BENCH_STEPS,
        "pre_kill_us_per_op": round(pre_med * 1e6, 1),
        "post_shrink_us_per_op": round(post_med * 1e6, 1),
        "rendezvous_gap_ms": round((gap or 0.0) * 1e3, 1),
        "barrier_ms": round(ctx.last_rendezvous_s * 1e3, 1),
        "generation": ctx.membership.generation,
    }), flush=True)
    hvd.shutdown()


def _elastic_bench_section(np_: int) -> dict:
    """`--elastic`: steady us/op before the kill, the re-rendezvous
    gap, and us/op after the shrink, with the recovery time asserted
    under 2x the heartbeat timeout."""
    hb_timeout = 2.0
    r = _run_world(
        "elastic", np_, timeout=300.0,
        extra_env={
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_WINDOW": "10",
            "HOROVOD_HEARTBEAT_INTERVAL": "0.2",
            "HOROVOD_HEARTBEAT_TIMEOUT": str(hb_timeout),
            "HOROVOD_TPU_SHM": "0",
            "HOROVOD_FAULT_SPEC":
                f"rank={np_ - 1}:kill:op={ELASTIC_BENCH_KILL_OP}",
        },
        allow_rc={np_ - 1: -9})
    r["heartbeat_timeout_s"] = hb_timeout
    r["recovery_budget_ms"] = round(2 * hb_timeout * 1e3, 1)
    r["recovery_within_budget"] = \
        r["rendezvous_gap_ms"] < 2 * hb_timeout * 1e3
    assert r["recovery_within_budget"], (
        f"re-rendezvous gap {r['rendezvous_gap_ms']} ms exceeded the "
        f"2x-heartbeat budget {r['recovery_budget_ms']} ms")
    return r


SELFOP_SYNC_KEYS = 1024        # model-shaped state: many tensors...
SELFOP_SYNC_KEY_ELEMS = 16384  # ...of 64 KiB f32 each = 64 MiB total
SELFOP_SYNC_ITERS = 3


def worker_selfop_sync(rank: int, size: int) -> None:
    """Rejoin-sync section: time ``State.sync()`` over a 1024-tensor,
    64 MiB model-shaped state — exactly what a rejoiner or a
    post-resize world pays before its first step. Run in pairs by the
    driver: the chunked tree-pipelined fast path (HOROVOD_SELFOP_SYNC=1,
    common/selfop.py) vs the legacy one-shot-per-key negotiated
    broadcast (=0). The fast leg also reports the
    hvd_data_copies_total delta across its syncs — the zero-copy
    claim: no sync byte ever pays a Python bytes-object copy."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import config as hconfig
    from horovod_tpu.common import elastic

    hvd.init()
    vals = {}
    for i in range(SELFOP_SYNC_KEYS):
        if rank == 0:
            vals[f"p{i:03d}"] = np.full(SELFOP_SYNC_KEY_ELEMS,
                                        float(i + 1), np.float32)
        else:
            vals[f"p{i:03d}"] = np.zeros(SELFOP_SYNC_KEY_ELEMS,
                                         np.float32)
    state = elastic.State(batch=0, **vals)

    def copies():
        return hvd.metrics()["local"].get(
            "hvd_data_copies_total", {}).get("v", 0)

    hvd.barrier(name="ss.warm")
    c0 = copies()
    times = []
    for _ in range(SELFOP_SYNC_ITERS):
        hvd.barrier(name="ss.bar")
        t0 = time.perf_counter()
        state.sync()
        times.append(time.perf_counter() - t0)
    c1 = copies()
    # every member now holds rank 0's values bit-for-bit
    for i in range(SELFOP_SYNC_KEYS):
        v = state._values[f"p{i:03d}"]
        assert float(v[0]) == float(i + 1) and float(v[-1]) == \
            float(i + 1), (i, v[0], v[-1])
    _, med, _ = _quantiles(times)
    if rank == 0:
        ctx = elastic.context()
        fast_on = hconfig.env_bool("HOROVOD_SELFOP_SYNC", True)
        print("RESULT " + json.dumps({
            "world": size,
            "state_mib": round(SELFOP_SYNC_KEYS * SELFOP_SYNC_KEY_ELEMS
                               * 4 / 2**20, 1),
            "keys": SELFOP_SYNC_KEYS,
            "sync_ms": round(med * 1e3, 1),
            "fast_path": bool(fast_on),
            "fast_syncs": ctx.syncs if ctx is not None else 0,
            "data_copies_delta": int(c1 - c0),
        }), flush=True)
    hvd.shutdown()


def _selfop_bench_section(np_: int) -> dict:
    """`--selfop`: the rejoin-sync A/B — chunked tree-pipelined
    fast path vs the legacy per-key negotiated broadcast, same
    64 MiB state, socket plane (the multi-host shape where rejoin
    cost actually matters)."""
    base = {
        "HOROVOD_ELASTIC": "1",
        "HOROVOD_ELASTIC_WINDOW": "10",
        "HOROVOD_TPU_SHM": "0",
        "HOROVOD_TPU_METRICS": "1",
        # The legacy leg is 1024 back-to-back broadcasts — enough
        # telemetry for the supervision policy to demote whichever
        # rank habitually arrives last. Benching, not training:
        # park the demotion trigger out of reach.
        "HOROVOD_SELFOP_DEMOTE_WINDOW": "1000000000",
    }
    fast = _run_world(
        "selfop_sync", np_, timeout=300.0,
        extra_env=dict(base, HOROVOD_SELFOP_SYNC="1"))
    legacy = _run_world(
        "selfop_sync", np_, timeout=600.0,
        extra_env=dict(base, HOROVOD_SELFOP_SYNC="0"))
    assert fast["fast_syncs"] >= SELFOP_SYNC_ITERS, fast
    assert legacy["fast_syncs"] == 0, legacy
    speedup = round(legacy["sync_ms"] / max(fast["sync_ms"], 1e-9), 2)
    return {
        "world": np_,
        "state_mib": fast["state_mib"],
        "keys": fast["keys"],
        "fast_sync_ms": fast["sync_ms"],
        "legacy_sync_ms": legacy["sync_ms"],
        "speedup": speedup,
        "meets_3x": speedup >= 3.0,
        "fast_data_copies_delta": fast["data_copies_delta"],
        "zero_copy_clean": fast["data_copies_delta"] == 0,
    }


CACHE_BENCH_TENSORS = 64       # 4 KiB grads per steady-state step
CACHE_BENCH_STEPS = 100
CACHE_BENCH_GAP_S = 0.005      # simulated per-step compute (backward)


def worker_cache(rank: int, size: int) -> None:
    """Negotiation-overhead section: a steady-state training-shaped
    loop — the SAME 64 x 4 KiB gradient bucket every step (one
    grouped_allreduce_async, the way a DDP-style integration submits a
    gradient bucket), with a short think-time between steps standing
    in for the backward pass. This is exactly the traffic the
    bit-vector response cache (HOROVOD_CACHE_*) turns into one fused
    bitmask+data round per step. Run in on/off pairs by the
    orchestrator (cache on / HOROVOD_CACHE_ENABLED=0): us_per_op is a
    4 KiB allreduce's share of the median step latency (submit ->
    drained, think-time excluded). Reports the hit-rate and
    cached/fused-cycle counters measured AFTER warmup (acceptance
    bar: >= 99% hits over the 100-step loop)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b

    hvd.init()
    n = (4 << 10) // 8
    xs = [np.full(n, float(rank + 1) * (i + 1), np.float64)
          for i in range(CACHE_BENCH_TENSORS)]
    ssum = sum(range(1, size + 1))

    def step():
        hs = hvd.grouped_allreduce_async(xs, average=False, name="cb")
        for h in hs:
            hvd.synchronize(h)

    for _ in range(5):
        step()
        time.sleep(CACHE_BENCH_GAP_S)
    hvd.barrier(name="cb.bar")
    rt = _b.runtime()
    s0 = rt.negotiation_cache_stats()
    c0 = rt._cycle_count
    m0 = hvd.metrics()["local"]
    times = []
    for _ in range(CACHE_BENCH_STEPS):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
        time.sleep(CACHE_BENCH_GAP_S)
    s1 = rt.negotiation_cache_stats()
    c1 = rt._cycle_count
    m1 = hvd.metrics()["local"]
    # correctness spot check of the steady-state values
    out = hvd.grouped_allreduce(xs, average=False, name="cb")
    for i in range(CACHE_BENCH_TENSORS):
        assert abs(float(np.asarray(out[i])[0])
                   - ssum * (i + 1)) < 1e-6
    _, med, _ = _quantiles(times)
    report = {
        "tensors_per_step": CACHE_BENCH_TENSORS,
        "bytes_per_tensor": 4 << 10,
        "steps": CACHE_BENCH_STEPS,
        "us_per_step": round(med * 1e6, 1),
        "us_per_op": round(med * 1e6 / CACHE_BENCH_TENSORS, 1),
        # the full per-step series, for paired estimators: a
        # simultaneous A/B pair's step k on each side shares the
        # same wall-clock throttle state, so index-paired ratios
        # cancel the common-mode noise that swamps sub-percent
        # effects (--trace-overhead)
        "step_times_us": [round(t * 1e6, 1) for t in times],
        "cycles_per_step": round((c1 - c0) / CACHE_BENCH_STEPS, 2),
        "cache_enabled": bool(s1.get("enabled")),
    }
    if m1:  # metrics armed: steady-bucket copies (zero-copy contract)
        report["data_copies"] = int(
            m1.get("hvd_data_copies_total", {"v": 0.0})["v"]
            - m0.get("hvd_data_copies_total", {"v": 0.0})["v"])
    if s1.get("enabled"):
        d_hits = s1["hits"] - s0["hits"]
        d_misses = s1["misses"] - s0["misses"]
        report["hit_rate"] = round(
            d_hits / max(1, d_hits + d_misses), 4)
        report["cached_cycles"] = (s1["cached_cycles"]
                                   - s0["cached_cycles"])
        report["fused_spec_cycles"] = (s1["spec_cycles"]
                                       - s0["spec_cycles"])
        report["native_steady_cycles"] = (
            s1.get("native_steady_cycles", 0)
            - s0.get("native_steady_cycles", 0))
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


TRACE_TOGGLE_BLOCKS = 16   # ABBA-ordered on/off block pairs
TRACE_TOGGLE_BLOCK_STEPS = 24


def worker_trace_toggle(rank: int, size: int) -> None:
    """Within-process A/B for the trace-overhead section: the same
    steady bucket as worker_cache, but alternating short armed/dark
    blocks INSIDE one world by re-pointing the runtime's recorder/
    collector hooks between blocks. Adjacent blocks share the host's
    throttle state at the ~100ms scale and everything else — the
    processes, the negotiated world, the cache state — is literally
    identical, so the paired block ratios resolve the sub-percent
    costs that process-level A/B noise swamps on this box.
    ``HVD_TRACE_TOGGLE`` picks what toggles: ``flight`` (the
    default-on ring writes alone) or ``trace`` (flight + span
    collection + TAG_TRACE shipping + rank 0's arrival stamps — the
    whole plane)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common import trace as htrace

    hvd.init()
    which = os.environ.get("HVD_TRACE_TOGGLE", "flight")
    pairs = int(os.environ.get("HVD_TOGGLE_BLOCKS",
                               TRACE_TOGGLE_BLOCKS))
    block_steps = int(os.environ.get("HVD_TOGGLE_STEPS",
                                     TRACE_TOGGLE_BLOCK_STEPS))
    n = (4 << 10) // 8
    xs = [np.full(n, float(rank + 1) * (i + 1), np.float64)
          for i in range(CACHE_BENCH_TENSORS)]
    ssum = sum(range(1, size + 1))

    def step():
        hs = hvd.grouped_allreduce_async(xs, average=False, name="tt")
        for h in hs:
            hvd.synchronize(h)

    for _ in range(8):
        step()
        time.sleep(CACHE_BENCH_GAP_S)
    hvd.barrier(name="tt.bar")
    rt = _b.runtime()
    ctl = rt.controller
    armed = (rt._flight, rt._trace, ctl._on_arrivals)

    def _arm(on: bool) -> None:
        # plain attribute stores: atomic under the GIL, read fresh by
        # the background loop each round (runtime.py keeps the
        # toggled paths NameError-safe by construction)
        rt._flight = armed[0] if on else htrace.NOOP_RECORDER
        if which == "trace":
            rt._trace = armed[1] if on else htrace.NOOP_TRACE
            rt._trace_on = on
            ctl._on_arrivals = armed[2] if on else None

    on_times, off_times = [], []
    on_cycles = off_cycles = 0
    for p in range(pairs):
        # ABBA ordering: alternate which mode runs first within a
        # pair, so a drift that consistently favors the second block
        # of a pair cancels across pairs instead of biasing the
        # median
        order = (True, False) if p % 2 == 0 else (False, True)
        for on in order:
            _arm(on)
            k0 = rt._cycle_count
            t0 = time.perf_counter()
            for _ in range(block_steps):
                step()
            dt = time.perf_counter() - t0
            if on:
                on_times.append(dt)
                on_cycles += rt._cycle_count - k0
            else:
                off_times.append(dt)
                off_cycles += rt._cycle_count - k0
            time.sleep(CACHE_BENCH_GAP_S)
    _arm(True)
    out = hvd.grouped_allreduce(xs, average=False, name="tt.chk")
    for i in range(CACHE_BENCH_TENSORS):
        assert abs(float(np.asarray(out[i])[0])
                   - ssum * (i + 1)) < 1e-6
    pair_pcts = sorted(
        (a / b - 1.0) * 100 for a, b in zip(on_times, off_times))
    _, med_on, _ = _quantiles(on_times)
    _, med_off, _ = _quantiles(off_times)
    div = block_steps * CACHE_BENCH_TENSORS
    # absolute enabled-path cost per negotiation round, the
    # world-size-independent quantity the orchestrator scales into
    # the target bucket's geometry (block MEDIANS absorb the burst
    # blocks that poison per-pair ratios)
    rounds_per_block = ((on_cycles + off_cycles)
                        / max(1, len(on_times) + len(off_times)))
    delta_us_per_round = ((med_on - med_off) * 1e6
                          / max(1.0, rounds_per_block))
    report = {
        "toggled": which,
        "blocks_per_mode": pairs,
        "steps_per_block": block_steps,
        "on_us_per_op": round(med_on * 1e6 / div, 2),
        "off_us_per_op": round(med_off * 1e6 / div, 2),
        "rounds_per_block": round(rounds_per_block, 1),
        "delta_us_per_round": round(delta_us_per_round, 3),
        "block_pair_overhead_pct": [round(p, 2) for p in pair_pcts],
        "overhead_pct": round(
            (med_on / med_off - 1.0) * 100, 2),
    }
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def _cache_bench_section(np_: int) -> dict:
    """A/B the negotiation fast path at world_size=np_ on the CPU
    socket backend (shm/ring off so the data plane is socket in both
    runs and only the control protocol differs). This host's
    scheduler throttles in multi-second bursts, so sequential on/off
    runs are drift-dominated; instead run each on/off pair
    SIMULTANEOUSLY — both worlds experience the identical machine at
    every instant, which makes the per-pair ratio stable — and report
    the median of the per-pair ratios."""
    import threading
    cache_env = {"HOROVOD_TPU_SHM": "0",
                 "HOROVOD_TPU_RING_THRESHOLD": "-1"}
    off_env = dict(cache_env, HOROVOD_CACHE_ENABLED="0")

    ons, offs, ratios = [], [], []
    for rep in range(3):
        pair = {}

        def _go(key, env):
            pair[key] = _run_world("cache", np_, timeout=600.0,
                                   extra_env=env)

        ta = threading.Thread(target=_go, args=("on", cache_env))
        tb = threading.Thread(target=_go, args=("off", off_env))
        ta.start()
        tb.start()
        ta.join()
        tb.join()
        ons.append(pair["on"])
        offs.append(pair["off"])
        ratios.append(pair["off"]["us_per_op"]
                      / pair["on"]["us_per_op"])
    ons.sort(key=lambda d: d["us_per_op"])
    offs.sort(key=lambda d: d["us_per_op"])
    ratios.sort()
    return {"world_size": np_,
            "cache_on": ons[len(ons) // 2],
            "cache_off": offs[len(offs) // 2],
            "pair_ratios": [round(r, 2) for r in ratios],
            "speedup": round(ratios[len(ratios) // 2], 2)}


def _zero_copy_bench_section(np_: int) -> dict:
    """Zero-copy native data plane A/B on the PR 3 steady bucket:
    both legs run the full fast path (cache + fused speculative
    cycle, socket star); the off leg sets HOROVOD_TPU_ZERO_COPY=0,
    which restores the PR 3 byte-copy paths (Python serialization,
    bytes recv, bytearray copies) while keeping the wire format
    identical.

    TWO protocols, both recorded:

    * SIMULTANEOUS pairs (the cache section's protocol — immune to
      this host's multi-second throttle bursts). Caveat it inherits
      on a host whose core count is below 2 x world_size: the two
      worlds serialize through one run queue, so the fast world's
      measured step absorbs the slow world's CPU share and the pair
      ratio is CAPPED near (1+k)/k regardless of the true gap (~1.5x
      observed ceiling on the 1-core reference box even with the fast
      leg's data plane made nearly free).
    * ISOLATED alternating legs (on/off/on/off...): each world owns
      the machine; adjacent runs see similar throttle states, and the
      median of adjacent ratios is the undistorted data-plane
      speedup. This is the headline number on hosts where the pair
      cannot genuinely run side by side."""
    import threading
    base_env = {"HOROVOD_TPU_SHM": "0",
                "HOROVOD_TPU_RING_THRESHOLD": "-1"}
    off_env = dict(base_env, HOROVOD_TPU_ZERO_COPY="0")

    ons, offs, ratios = [], [], []
    for rep in range(3):
        pair = {}

        def _go(key, env):
            pair[key] = _run_world("cache", np_, timeout=600.0,
                                   extra_env=env)

        ta = threading.Thread(target=_go, args=("on", base_env))
        tb = threading.Thread(target=_go, args=("off", off_env))
        ta.start()
        tb.start()
        ta.join()
        tb.join()
        ons.append(pair["on"])
        offs.append(pair["off"])
        ratios.append(pair["off"]["us_per_op"]
                      / pair["on"]["us_per_op"])
    iso_ons, iso_offs, iso_ratios = [], [], []
    for rep in range(3):
        a = _run_world("cache", np_, timeout=600.0,
                       extra_env=base_env)
        b = _run_world("cache", np_, timeout=600.0,
                       extra_env=off_env)
        iso_ons.append(a)
        iso_offs.append(b)
        iso_ratios.append(b["us_per_op"] / a["us_per_op"])
    ons.sort(key=lambda d: d["us_per_op"])
    offs.sort(key=lambda d: d["us_per_op"])
    ratios.sort()
    iso_ons.sort(key=lambda d: d["us_per_op"])
    iso_offs.sort(key=lambda d: d["us_per_op"])
    iso_ratios.sort()
    return {"world_size": np_,
            "cores": os.cpu_count(),
            "zero_copy_on": ons[len(ons) // 2],
            "zero_copy_off": offs[len(offs) // 2],
            "pair_ratios": [round(r, 2) for r in ratios],
            "speedup": round(ratios[len(ratios) // 2], 2),
            "isolated_on": iso_ons[len(iso_ons) // 2],
            "isolated_off": iso_offs[len(iso_offs) // 2],
            "isolated_ratios": [round(r, 2) for r in iso_ratios],
            "isolated_speedup": round(
                iso_ratios[len(iso_ratios) // 2], 2)}


OVERLAP_BENCH_TENSORS = 16
OVERLAP_BENCH_BUCKETS = 4
OVERLAP_BENCH_STEPS = 50
# 256 KiB/tensor -> 4 MiB/step: payload work (HMAC + memcpy) must
# dominate the fixed per-round protocol cost, or bucketing's extra
# rounds eat the overlap on a 1-core host (measured crossover ~64 KiB).
OVERLAP_BENCH_ELEMS = 65536


def worker_multitenant(rank: int, size: int) -> None:
    """Multi-tenant section (docs/multitenancy.md): one or two
    tenants spanning the whole fleet run an identical per-tenant
    workload from separate threads. Two program shapes:

    * ``paced`` (HVD_BENCH_THINK_MS) — a training-shaped loop: one
      64 KiB allreduce then a think-time sleep (compute stand-in;
      releases the GIL like device compute). The shared-fleet leg's
      per-tenant throughput vs the isolated leg measures co-tenancy
      overhead.
    * ``saturated`` (HVD_BENCH_SATURATE=1) — a 4-deep async pipeline
      with no think time: both lanes stay backlogged, so the
      QoS-weighted interleave is the binding constraint and the
      cycle share at the first tenant's completion measures it.

    Reports per-tenant elapsed/ops_per_s plus lane stats (cycles,
    deferrals) and — with two tenants — the second tenant's completed
    cycles at the moment the first finishes."""
    import threading
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    nten = int(os.environ.get("HVD_BENCH_TENANTS", "2"))
    weights = [float(w) for w in
               os.environ.get("HVD_BENCH_WEIGHTS", "1,1").split(",")]
    think_s = float(os.environ.get("HVD_BENCH_THINK_MS", "5")) / 1e3
    saturate = os.environ.get("HVD_BENCH_SATURATE") == "1"
    steps = int(os.environ.get("HVD_BENCH_STEPS", "150"))
    names = ["jobA", "jobB"][:nten]
    tenants = [hvd.create_tenant(n, list(range(size)), weight=w)
               for n, w in zip(names, weights)]
    x = np.full(16384, float(rank + 1), np.float32)  # 64 KiB
    ssum = float(sum(range(1, size + 1)))
    out: dict = {}

    def run(t, key, first):
        t0 = time.monotonic()
        if saturate:
            depth, pend = 4, []
            for i in range(steps):
                pend.append(t.allreduce_async(
                    x, average=False, name=f"{key}.g{i % depth}"))
                if len(pend) >= depth:
                    assert float(np.asarray(
                        t.synchronize(pend.pop(0)))[0]) == ssum
            while pend:
                t.synchronize(pend.pop(0))
        else:
            for _ in range(steps):
                r = t.allreduce(x, average=False, name=f"{key}.g")
                assert float(np.asarray(r)[0]) == ssum
                if think_s:
                    time.sleep(think_s)
        out[key] = {"elapsed_s": time.monotonic() - t0}
        if first and len(tenants) > 1:
            out["peer_cycles_at_first_done"] = \
                tenants[1].lane_stats()["cycles"]

    threads = [threading.Thread(target=run, args=(t, k, i == 0))
               for i, (t, k) in enumerate(zip(tenants, names))]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    result = {"size": size, "steps": steps, "tenants": {}}
    for t, key in zip(tenants, names):
        stats = t.lane_stats()
        result["tenants"][key] = {
            "elapsed_s": round(out[key]["elapsed_s"], 4),
            "ops_per_s": round(steps / out[key]["elapsed_s"], 2),
            "cycles": stats["cycles"],
            "deferrals": stats["deferrals"],
            "weight": stats["weight"],
        }
    if "peer_cycles_at_first_done" in out:
        result["peer_cycles_at_first_done"] = \
            out["peer_cycles_at_first_done"]
        result["first_cycles"] = \
            result["tenants"][names[0]]["cycles"]
    for t in tenants:
        t.shutdown()
    if rank == 0:
        print("RESULT " + json.dumps(result), flush=True)
    hvd.shutdown()


def _multitenant_bench_section(np_: int) -> dict:
    """Shared-fleet throughput (isolated-leg protocol, alternating
    reps so adjacent runs share this throttling host's phase) and the
    priority-weight cycle-share shift (saturated legs, equal weights
    vs 3:1)."""
    reps = 2
    iso_rates, shared = [], []
    for _ in range(reps):
        iso = _run_world("multitenant", np_, timeout=300.0,
                         extra_env={"HVD_BENCH_TENANTS": "1"})
        iso_rates.append(iso["tenants"]["jobA"]["ops_per_s"])
        sh = _run_world("multitenant", np_, timeout=300.0,
                        extra_env={"HVD_BENCH_TENANTS": "2"})
        shared.append(sh)
    iso_rate = _quantiles(iso_rates)[1]
    ratios_a = [s["tenants"]["jobA"]["ops_per_s"] / iso_rate
                for s in shared]
    ratios_b = [s["tenants"]["jobB"]["ops_per_s"] / iso_rate
                for s in shared]
    ratio_a = _quantiles(ratios_a)[1]
    ratio_b = _quantiles(ratios_b)[1]

    def _share(weights: str) -> dict:
        r = _run_world("multitenant", np_, timeout=300.0,
                       extra_env={"HVD_BENCH_TENANTS": "2",
                                  "HVD_BENCH_WEIGHTS": weights,
                                  "HVD_BENCH_SATURATE": "1",
                                  "HVD_BENCH_STEPS": "400"})
        peer = max(1, r["peer_cycles_at_first_done"])
        return {"first_cycles": r["first_cycles"],
                "peer_cycles_at_first_done": peer,
                "share": round(r["first_cycles"] / peer, 3),
                "light_deferrals":
                    r["tenants"]["jobB"]["deferrals"]}

    equal = _share("1,1")
    skewed = _share("3,1")
    shift = round(skewed["share"] / max(0.01, equal["share"]), 3)
    return {
        "np": np_,
        "protocol": "isolated-leg alternating reps; 64KiB f32 op + "
                    "5ms think per step (paced legs); saturated "
                    "4-deep async pipeline for the share legs",
        "isolated_ops_per_s": iso_rate,
        "shared_ops_per_s": {
            "jobA": _quantiles(
                [s["tenants"]["jobA"]["ops_per_s"]
                 for s in shared])[1],
            "jobB": _quantiles(
                [s["tenants"]["jobB"]["ops_per_s"]
                 for s in shared])[1]},
        "shared_vs_isolated": {"jobA": round(ratio_a, 3),
                               "jobB": round(ratio_b, 3)},
        "min_tenant_fraction": round(min(ratio_a, ratio_b), 3),
        "meets_60pct": bool(min(ratio_a, ratio_b) >= 0.6),
        "cycle_share_equal_weights": equal,
        "cycle_share_3to1": skewed,
        "share_shift_3to1_vs_equal": shift,
        "weights_shift_share": bool(shift > 1.15
                                    and skewed["light_deferrals"] > 0),
    }


def worker_overlap(rank: int, size: int) -> None:
    """Overlap-tier section: a steady training-shaped loop whose
    backward pass is modeled by injected compute (sleep — it releases
    the GIL exactly like device compute does) producing gradient
    BUCKETS progressively. Two program shapes, selected by
    OVERLAP_BENCH_MODE:

    * ``bucketed`` — the overlap tier's contract: each bucket is
      submitted the moment its compute slice ends (ready-order
      dispatch), so its cycle negotiates + reduces on the in-flight
      runner while later slices still compute. Step time tends to
      compute + one bucket's wire time.
    * ``flat`` — today's synchronous pattern: the single grouped
      submission needs the WHOLE gradient set, so it happens after
      all compute and the full wire time lands on the critical path.

    Identical tensors, bytes and injected compute either way
    (OVERLAP_BENCH_COMPUTE_US total per step, calibrated by the
    orchestrator to the measured wire time — the regime the tier
    targets). Reports median step time plus the engagement counters
    (overlap cycles, mean hvd_overlap_fraction, data copies, wire
    bytes saved)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b

    hvd.init()
    mode = os.environ.get("OVERLAP_BENCH_MODE", "bucketed")
    compute_us = int(os.environ.get("OVERLAP_BENCH_COMPUTE_US", "0"))
    k = OVERLAP_BENCH_BUCKETS
    per = OVERLAP_BENCH_TENSORS // k
    xs = [np.full(OVERLAP_BENCH_ELEMS, float(rank + 1) * (i + 1),
                  np.float32)
          for i in range(OVERLAP_BENCH_TENSORS)]
    buckets = [xs[i * per:(i + 1) * per] for i in range(k)]
    slice_s = compute_us / 1e6 / k
    ssum = sum(range(1, size + 1))

    def step():
        handles = []
        if mode == "bucketed":
            for i, bucket in enumerate(buckets):
                if slice_s:
                    time.sleep(slice_s)  # bucket i's backward slice
                handles.extend(hvd.grouped_allreduce_async(
                    bucket, average=False, name=f"ov{i}"))
        else:
            for _ in range(k):
                if slice_s:
                    time.sleep(slice_s)  # same producer timeline
            handles.extend(hvd.grouped_allreduce_async(
                xs, average=False, name="ovf"))
        for h in handles:
            hvd.synchronize(h)

    for _ in range(8):
        step()
    hvd.barrier(name="ovb.bar")
    rt = _b.runtime()
    s0 = rt.negotiation_cache_stats()
    m0 = hvd.metrics()["local"]
    times = []
    for _ in range(OVERLAP_BENCH_STEPS):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    s1 = rt.negotiation_cache_stats()
    m1 = hvd.metrics()["local"]
    # correctness spot check of the steady-state values
    out = hvd.grouped_allreduce(xs, average=False, name="ovchk")
    for i in range(OVERLAP_BENCH_TENSORS):
        assert abs(float(np.asarray(out[i])[0])
                   - ssum * (i + 1)) < 1e-3

    def _delta(name):
        return (m1.get(name, {"v": 0.0})["v"]
                - m0.get(name, {"v": 0.0})["v"])

    frac = m1.get("hvd_overlap_fraction")
    f0 = m0.get("hvd_overlap_fraction")
    mean_frac = None
    if frac and frac.get("count", 0) > (f0 or {}).get("count", 0):
        dc = frac["count"] - (f0 or {"count": 0, "sum": 0.0})["count"]
        ds = frac["sum"] - (f0 or {"count": 0, "sum": 0.0})["sum"]
        mean_frac = round(ds / max(1, dc), 3)
    _, med, _ = _quantiles(times)
    report = {
        "mode": mode,
        "tensors_per_step": OVERLAP_BENCH_TENSORS,
        "buckets": k if mode == "bucketed" else 1,
        "bytes_per_tensor": OVERLAP_BENCH_ELEMS * 4,
        "compute_us_per_step": compute_us,
        "steps": OVERLAP_BENCH_STEPS,
        "us_per_step": round(med * 1e6, 1),
        "overlap_cycles": (s1.get("overlap_cycles", 0)
                           - s0.get("overlap_cycles", 0)),
        "native_steady_cycles": (s1.get("native_steady_cycles", 0)
                                 - s0.get("native_steady_cycles", 0)),
        "spec_cycles": s1["spec_cycles"] - s0["spec_cycles"],
        "overlap_fraction_mean": mean_frac,
        "data_copies": int(_delta("hvd_data_copies_total")),
        "wire_bytes_saved": int(_delta("hvd_wire_bytes_saved_total")),
    }
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def _overlap_bench_section(np_: int) -> dict:
    """`--overlap`: A/B the overlap tier against the synchronous
    steady path with injected per-step compute CALIBRATED to the
    measured wire time (the acceptance regime: compute comparable to
    comm). Protocols as for --steady-only: isolated alternating legs
    (the honest number on a host that cannot truly run two worlds
    side by side) plus simultaneous pairs, and one compressed leg
    proving compression + chunked transfer stay engaged per bucket."""
    import threading
    on_env = {"HOROVOD_TPU_SHM": "0",
              "HOROVOD_TPU_RING_THRESHOLD": "-1",
              "HOROVOD_TPU_METRICS": "1",
              "HOROVOD_OVERLAP_INFLIGHT": "2",
              "OVERLAP_BENCH_MODE": "bucketed"}
    off_env = dict(on_env, HOROVOD_OVERLAP_INFLIGHT="0",
                   OVERLAP_BENCH_MODE="flat")

    # Calibrate: the flat leg's step with zero injected compute IS
    # the steady wire+protocol time; inject that much compute.
    probe = _run_world("overlap", np_, timeout=600.0,
                       extra_env=dict(off_env,
                                      OVERLAP_BENCH_COMPUTE_US="0"))
    compute_us = max(500, int(probe["us_per_step"]))
    on_env["OVERLAP_BENCH_COMPUTE_US"] = str(compute_us)
    off_env["OVERLAP_BENCH_COMPUTE_US"] = str(compute_us)

    iso_ons, iso_offs, iso_ratios = [], [], []
    for rep in range(3):
        a = _run_world("overlap", np_, timeout=600.0, extra_env=on_env)
        b = _run_world("overlap", np_, timeout=600.0,
                       extra_env=off_env)
        iso_ons.append(a)
        iso_offs.append(b)
        iso_ratios.append(b["us_per_step"] / a["us_per_step"])
    ons, offs, ratios = [], [], []
    for rep in range(2):
        pair = {}

        def _go(key, env):
            pair[key] = _run_world("overlap", np_, timeout=600.0,
                                   extra_env=env)

        ta = threading.Thread(target=_go, args=("on", on_env))
        tb = threading.Thread(target=_go, args=("off", off_env))
        ta.start()
        tb.start()
        ta.join()
        tb.join()
        ons.append(pair["on"])
        offs.append(pair["off"])
        ratios.append(pair["off"]["us_per_step"]
                      / pair["on"]["us_per_step"])
    comp = _run_world(
        "overlap", np_, timeout=600.0,
        extra_env=dict(on_env, HOROVOD_COMPRESSION="bf16",
                       HOROVOD_OVERLAP_CHUNK_BYTES="4096"))
    iso_ons.sort(key=lambda d: d["us_per_step"])
    iso_offs.sort(key=lambda d: d["us_per_step"])
    iso_ratios.sort()
    ratios.sort()
    med_on = iso_ons[len(iso_ons) // 2]
    sec = {"world_size": np_,
           "cores": os.cpu_count(),
           "compute_us_per_step": compute_us,
           "wire_probe_us_per_step": probe["us_per_step"],
           "overlap_on": med_on,
           "overlap_off": iso_offs[len(iso_offs) // 2],
           "isolated_ratios": [round(r, 2) for r in iso_ratios],
           "isolated_speedup": round(
               iso_ratios[len(iso_ratios) // 2], 2),
           "pair_ratios": [round(r, 2) for r in ratios],
           "pair_speedup": round(
               sorted(ratios)[len(ratios) // 2], 2) if ratios else None,
           "compressed_on": comp,
           "overlap_fraction": med_on.get("overlap_fraction_mean"),
           "zero_copies": med_on.get("data_copies") == 0,
           "meets_1_3x": None,
           "meets_fraction_50pct": None}
    sec["meets_1_3x"] = sec["isolated_speedup"] >= 1.3
    f = sec["overlap_fraction"]
    sec["meets_fraction_50pct"] = (f is not None and f >= 0.5)
    return sec


def _metrics_bench_section(np_: int) -> dict:
    """Metrics-plane overhead A/B on the PR 3 steady bucket (the
    worker_cache loop: 64 x 4 KiB grouped allreduce per step, cache
    on): HOROVOD_TPU_METRICS off (the default — this leg must hold
    the recorded negotiation_cache.cache_on baseline within the <2%
    acceptance bar, since the disabled path installs only no-op
    hooks) vs on (pricing the armed counters/histograms + the
    per-interval world fold). Same simultaneous-pair protocol as the
    cache section: this host throttles in multi-second bursts, so
    only per-pair ratios are stable."""
    import threading
    base_env = {"HOROVOD_TPU_SHM": "0",
                "HOROVOD_TPU_RING_THRESHOLD": "-1"}
    on_env = dict(base_env, HOROVOD_TPU_METRICS="1",
                  HOROVOD_TPU_METRICS_INTERVAL="1")

    offs, ons, ratios = [], [], []
    for rep in range(3):
        pair = {}

        def _go(key, env):
            pair[key] = _run_world("cache", np_, timeout=600.0,
                                   extra_env=env)

        ta = threading.Thread(target=_go, args=("off", base_env))
        tb = threading.Thread(target=_go, args=("on", on_env))
        ta.start()
        tb.start()
        ta.join()
        tb.join()
        offs.append(pair["off"])
        ons.append(pair["on"])
        ratios.append(pair["on"]["us_per_op"]
                      / pair["off"]["us_per_op"])
    offs.sort(key=lambda d: d["us_per_op"])
    ons.sort(key=lambda d: d["us_per_op"])
    ratios.sort()
    med_ratio = ratios[len(ratios) // 2]
    return {"world_size": np_,
            "metrics_off": offs[len(offs) // 2],
            "metrics_on": ons[len(ons) // 2],
            "pair_overhead_pct": [round((r - 1) * 100, 2)
                                  for r in ratios],
            "enabled_overhead_pct": round((med_ratio - 1) * 100, 2)}


def _trace_bench_section(np_: int) -> dict:
    """World-trace-plane overhead on the PR 3 steady bucket
    (`--trace-overhead`, docs/tracing.md). Two quantities, each
    measured two ways:

    * FLIGHT: the default-on flight recorder alone (one ring write
      per negotiation round). Acceptance: <= 1% — the price every
      production job pays.
    * TRACE: the whole plane armed — flight + span collection +
      TAG_TRACE shipping + rank 0's arrival stamps and merged-file
      writer. Acceptance: <= 5%. Its pair leg runs with metrics on,
      so it also re-proves the zero-copy steady contract
      (data_copies == 0): span batching never touches payload bytes.

    Protocols: the simultaneous-pair A/B (same as --metrics-only,
    recorded for cross-section comparability) — and, as the
    HEADLINE the pass bools gate on, the within-process TOGGLE
    (worker_trace_toggle): a ws=2 world alternates ~2s armed/dark
    blocks by re-pointing the runtime's hooks, so both modes share
    one process set and adjacent blocks share throttle state. The
    toggle resolves the ABSOLUTE per-round cost (a quantity process-
    level A/B cannot see under this host's noise floor — the same
    caveat the zero-copy section documents for its pair protocol);
    that cost is world-size independent, so the headline scales it
    into the np_ bucket's measured rounds-per-step and step latency
    from the pair baseline."""
    import threading
    base_env = {"HOROVOD_TPU_SHM": "0",
                "HOROVOD_TPU_RING_THRESHOLD": "-1"}
    off_env = dict(base_env, HOROVOD_TPU_FLIGHT="0")
    flight_env = dict(base_env)  # flight recorder default-on
    trace_env = dict(base_env, HOROVOD_TPU_METRICS="1",
                     HOROVOD_TPU_METRICS_INTERVAL="1",
                     HOROVOD_TPU_TRACE=os.path.join(
                         tempfile.mkdtemp(prefix="hvdtrace_bench"),
                         "world_trace.json"),
                     HOROVOD_TPU_TRACE_INTERVAL="0.5")

    def _pairs(on_env):
        # The --metrics-only protocol, recorded for comparability;
        # the pass bools gate on the toggle worlds below instead
        # (two worlds timesharing this box's core cannot resolve
        # sub-percent effects — observed pair spread is +/- several
        # percent). Alongside the whole-run ratios, each pair also
        # records the median of index-paired step ratios.
        offs, ons, run_ratios, paired = [], [], [], []
        for rep in range(3):
            pair = {}

            def _go(key, env):
                pair[key] = _run_world("cache", np_, timeout=600.0,
                                       extra_env=env)

            ta = threading.Thread(target=_go, args=("off", off_env))
            tb = threading.Thread(target=_go, args=("on", on_env))
            ta.start()
            tb.start()
            ta.join()
            tb.join()
            offs.append(pair["off"])
            ons.append(pair["on"])
            run_ratios.append(pair["on"]["us_per_op"]
                              / pair["off"]["us_per_op"])
            rs = sorted(a / b for a, b in
                        zip(pair["on"]["step_times_us"],
                            pair["off"]["step_times_us"]))
            paired.append(rs[len(rs) // 2])
        offs.sort(key=lambda d: d["us_per_op"])
        ons.sort(key=lambda d: d["us_per_op"])
        run_ratios.sort()
        paired.sort()
        med_off = dict(offs[len(offs) // 2])
        med_on = dict(ons[len(ons) // 2])
        med_off.pop("step_times_us", None)  # keep RESULTS readable
        med_on.pop("step_times_us", None)
        return (med_off, med_on,
                [round((r - 1) * 100, 2) for r in run_ratios],
                round((paired[len(paired) // 2] - 1) * 100, 2))

    f_off, f_on, f_pcts, f_paired = _pairs(flight_env)
    t_off, t_on, t_pcts, t_paired = _pairs(trace_env)
    # The precision instrument: within-process armed/dark toggling in
    # a ws=2 world, whose low scheduling noise (2 processes, ~2s
    # blocks) resolves the absolute per-round cost; that cost —
    # world-size independent, it is the same ring write / span append
    # everywhere — is then scaled into the np_ steady bucket's
    # measured geometry (rounds per step, step latency) from the
    # pair baseline above.
    tgl_env = {"HVD_TOGGLE_BLOCKS": "8", "HVD_TOGGLE_STEPS": "800"}
    tgl_flight = _run_world(
        "trace_toggle", 2, timeout=600.0,
        extra_env=dict(base_env, HVD_TRACE_TOGGLE="flight",
                       **tgl_env))
    tgl_trace = _run_world(
        "trace_toggle", 2, timeout=600.0,
        extra_env=dict(base_env, HVD_TRACE_TOGGLE="trace",
                       HOROVOD_TPU_TRACE=os.path.join(
                           tempfile.mkdtemp(prefix="hvdtrace_tgl"),
                           "world_trace.json"),
                       HOROVOD_TPU_TRACE_INTERVAL="0.25",
                       **tgl_env))

    def _scaled_pct(tgl, baseline):
        return round(max(0.0, tgl["delta_us_per_round"])
                     * baseline["cycles_per_step"]
                     / baseline["us_per_step"] * 100, 3)

    f_pct = _scaled_pct(tgl_flight, f_off)
    t_pct = _scaled_pct(tgl_trace, t_off)
    return {"world_size": np_,
            "flight_overhead_pct": f_pct,
            "flight_within_1pct": f_pct <= 1.0,
            "trace_overhead_pct": t_pct,
            "trace_within_5pct": t_pct <= 5.0,
            "flight_toggle": tgl_flight,
            "trace_toggle": tgl_trace,
            "baseline": f_off,
            "flight_on": f_on,
            "flight_pair_overhead_pct": f_pcts,
            "flight_paired_step_pct": f_paired,
            "trace_baseline": t_off,
            "trace_on": t_on,
            "trace_pair_overhead_pct": t_pcts,
            "trace_paired_step_pct": t_paired,
            "trace_data_copies": t_on.get("data_copies"),
            "zero_copies_with_trace":
                t_on.get("data_copies") == 0}


AUTOTUNE_VALUE_TENSORS = 24
AUTOTUNE_VALUE_BYTES = 32 << 10
AUTOTUNE_VALUE_STEPS = 40


COMP_BENCH_STEPS = 30
COMP_BENCH_GAP_S = 0.002


def worker_compression(rank: int, size: int) -> None:
    """Compression/algorithm grid leg (ISSUE 9): a steady
    single-tensor allreduce loop at the bucket size in
    HVD_BENCH_BYTES, with wire dtype and algorithm selected by the
    section driver through the production knobs (HOROVOD_COMPRESSION,
    HOROVOD_TWO_LEVEL, HOROVOD_TPU_RING_THRESHOLD, HOROVOD_TPU_SHM) —
    the grid measures exactly what an operator would deploy.
    ``us_per_op`` is the median steady step latency; values are
    bf16-exact small integers so every wire dtype is spot-checkable."""
    import numpy as np
    import horovod_tpu as hvd

    nbytes = int(os.environ.get("HVD_BENCH_BYTES", str(1 << 20)))
    steps = int(os.environ.get("HVD_BENCH_STEPS",
                               str(COMP_BENCH_STEPS)))
    hvd.init()
    n = max(1, nbytes // 4)
    x = np.full(n, float(rank + 1), np.float32)
    ssum = float(sum(range(1, size + 1)))

    out = None
    for _ in range(5):
        out = hvd.allreduce(x, average=False, name="cg")
        time.sleep(COMP_BENCH_GAP_S)
    assert abs(float(np.asarray(out)[0]) - ssum) < 1e-3
    hvd.barrier(name="cg.bar")
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        hvd.allreduce(x, average=False, name="cg")
        times.append(time.perf_counter() - t0)
        time.sleep(COMP_BENCH_GAP_S)
    out = hvd.allreduce(x, average=False, name="cg")
    assert abs(float(np.asarray(out)[0]) - ssum) < 1e-3
    _, med, _ = _quantiles(times)
    report = {
        "bytes": nbytes,
        "steps": steps,
        "us_per_op": round(med * 1e6, 1),
        "compression": os.environ.get("HOROVOD_COMPRESSION", "none"),
    }
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def worker_compression_autotune(rank: int, size: int) -> None:
    """Autotuner-convergence leg: the same steady loop under
    HOROVOD_AUTOTUNE=1 — the per-bucket grid phase sweeps
    (algorithm x wire dtype) live, the BO phase settles
    threshold x cycle, and the post-convergence median latency is
    what the section compares against the best hand-picked grid
    point (acceptance: >= 90% of its throughput)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common import wire_dtype as _wd
    from horovod_tpu.common.parameter_manager import bucket_of

    nbytes = int(os.environ.get("HVD_BENCH_BYTES", str(1 << 20)))
    hvd.init()
    rt = _b.runtime()
    pm = rt.parameter_manager
    assert pm is not None
    n = max(1, nbytes // 4)
    x = np.full(n, float(rank + 1), np.float32)
    converged = False
    for i in range(6000):
        hvd.allreduce(x, average=False, name="ca")
        if i % 5 != 4:
            # Back-to-back ops keep the tuner's score windows DENSE
            # (an op-starved window scores noise); the world-consistent
            # convergence probe only needs to run every few steps.
            continue
        flag = 0.0 if rank != 0 else (0.0 if pm.tuning else 1.0)
        done = hvd.broadcast(np.asarray([flag]), root_rank=0,
                             name=f"ca.done/{i}")
        if float(done[0]) == 1.0:
            converged = True
            break
    hvd.barrier(name="ca.bar")
    times = []
    for _ in range(COMP_BENCH_STEPS):
        t0 = time.perf_counter()
        hvd.allreduce(x, average=False, name="ca")
        times.append(time.perf_counter() - t0)
        time.sleep(COMP_BENCH_GAP_S)
    _, med, _ = _quantiles(times)
    report = {"converged": converged,
              "us_per_op": round(med * 1e6, 1),
              "ops_to_converge": i}
    if rank == 0:
        alg, cap = pm.bucket_plan()[bucket_of(nbytes)]
        report["tuned"] = {
            "algorithm": _wd.ALG_NAMES[alg],
            "wire": "-" if cap is None else _wd.WIRE_NAMES[cap]}
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def _compression_bench_section(np_: int) -> dict:
    """The ISSUE 9 acceptance grid at world_size=np_ on a fake
    multi-host topology (np_//2 hosts x 2 ranks): (algorithm x wire
    dtype x size bucket) medians with ISOLATED legs (3 reps on the
    headline >= 1 MiB bucket), a SIMULTANEOUS star none/bf16 pair
    (the throttle-immune protocol), and the autotuner-convergence
    run. Records:

    * ``bf16_star_speedup`` — median of ADJACENT isolated star
      none/bf16 leg ratios on the >= 1 MiB bucket (acceptance:
      >= 1.5x; the simultaneous pairs are recorded alongside);
    * ``twolevel_vs_best_flat_none`` / ``_bf16`` — best flat
      (star/ring) latency over two-level at the SAME wire dtype;
      the pass bit gates on the NONE ratio (the algorithm
      comparison, acceptance > 1.0) — see the loopback caveat in
      ``twolevel_note`` for why the bf16 column can invert on a
      one-host CI box;
    * ``autotune.frac_of_best`` — throughput fraction of the best
      grid combo (re-measured adjacent in time) the tuned config
      reaches (acceptance: >= 0.9)."""
    import threading

    def hosts(rank: int) -> dict:
        return {"HOROVOD_HOSTNAME": f"bhost{rank // 2}"}

    algs = {
        "star": {"HOROVOD_TPU_SHM": "0",
                 "HOROVOD_TPU_RING_THRESHOLD": "-1"},
        "ring": {"HOROVOD_TPU_SHM": "0",
                 "HOROVOD_TPU_RING_THRESHOLD": "1"},
        "twolevel": {"HOROVOD_TWO_LEVEL": "1"},
    }
    buckets = [64 << 10, 1 << 20]
    big = 1 << 20
    grid = {}
    for nb in buckets:
        for alg, aenv in algs.items():
            for w in ("none", "bf16"):
                env = dict(aenv, HOROVOD_COMPRESSION=w,
                           HVD_BENCH_BYTES=str(nb))
                reps = 3 if nb == big else 1
                runs = sorted(
                    _run_world("compression", np_, timeout=600.0,
                               extra_env=env,
                               per_rank_env=hosts)["us_per_op"]
                    for _ in range(reps))
                key = f"{nb}/{alg}/{w}"
                grid[key] = {"us_per_op": runs[len(runs) // 2],
                             "runs": runs}
                print(f"  {key:>24}: {runs[len(runs) // 2]} us/op "
                      f"{runs}", flush=True)

    # Headline bf16-vs-none ratio, BOTH protocols (the zero_copy
    # section's doctrine for this throttling host):
    # * ISOLATED ALTERNATING legs — none/bf16/none/bf16/...: adjacent
    #   runs see similar throttle states, so the median of ADJACENT
    #   ratios is the undistorted isolated-leg speedup (grouped reps
    #   drift across the multi-second throttle phases);
    # * SIMULTANEOUS pairs — both worlds see the identical machine at
    #   every instant.
    iso_ratios = []
    for _ in range(3):
        a = _run_world("compression", np_, timeout=600.0,
                       extra_env=dict(algs["star"],
                                      HOROVOD_COMPRESSION="none",
                                      HVD_BENCH_BYTES=str(big)),
                       per_rank_env=hosts)
        b = _run_world("compression", np_, timeout=600.0,
                       extra_env=dict(algs["star"],
                                      HOROVOD_COMPRESSION="bf16",
                                      HVD_BENCH_BYTES=str(big)),
                       per_rank_env=hosts)
        iso_ratios.append(a["us_per_op"] / b["us_per_op"])
    iso_ratios.sort()

    pair_ratios = []
    for _ in range(3):
        pair = {}

        def _go(key, w):
            env = dict(algs["star"], HOROVOD_COMPRESSION=w,
                       HVD_BENCH_BYTES=str(big))
            pair[key] = _run_world("compression", np_, timeout=600.0,
                                   extra_env=env, per_rank_env=hosts)

        ta = threading.Thread(target=_go, args=("none", "none"))
        tb = threading.Thread(target=_go, args=("bf16", "bf16"))
        ta.start()
        tb.start()
        ta.join()
        tb.join()
        pair_ratios.append(pair["none"]["us_per_op"]
                           / pair["bf16"]["us_per_op"])
    pair_ratios.sort()

    bf16_star = iso_ratios[len(iso_ratios) // 2]
    # Algorithm comparison at the SAME wire dtype (orthogonal axes):
    # the headline number compares uncompressed algorithms. On this
    # one-host CI box "cross-host" links are loopback, so the star's
    # whole-path bf16 compression can beat two-level's cross-leg-only
    # compression — recorded per-dtype so real-fabric readers can see
    # both; on real DCN the cross links bound everything and the two
    # gains compound.
    tl_vs_flat_none = (
        min(grid[f"{big}/star/none"]["us_per_op"],
            grid[f"{big}/ring/none"]["us_per_op"])
        / grid[f"{big}/twolevel/none"]["us_per_op"])
    tl_vs_flat_bf16 = (
        min(grid[f"{big}/star/bf16"]["us_per_op"],
            grid[f"{big}/ring/bf16"]["us_per_op"])
        / grid[f"{big}/twolevel/bf16"]["us_per_op"])

    # Autotuner-convergence leg: bf16 proposed, shm on (so the
    # two-level candidate is feasible). Sample windows are LONG
    # (steps_per_sample=6, back-to-back ops) — an op-starved window
    # scores scheduler noise and the grid argmax inherits it.
    at = _run_world(
        "compression_autotune", np_, timeout=900.0,
        extra_env={"HOROVOD_AUTOTUNE": "1",
                   "HOROVOD_COMPRESSION": "bf16",
                   "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
                   "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "6",
                   "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "4",
                   "HVD_BENCH_BYTES": str(big)},
        per_rank_env=hosts)
    # The comparison baseline re-runs the grid's best combo ADJACENT
    # in time to the tuned world (same throttle phase) — comparing
    # against a grid number measured minutes earlier mixes machine
    # phases, not configurations.
    best_key = min((k for k in grid if k.startswith(f"{big}/")),
                   key=lambda k: grid[k]["us_per_op"])
    _, best_alg, best_w = best_key.split("/")
    best_adj = _run_world(
        "compression", np_, timeout=600.0,
        extra_env=dict(algs[best_alg], HOROVOD_COMPRESSION=best_w,
                       HVD_BENCH_BYTES=str(big)),
        per_rank_env=hosts)
    best_us = best_adj["us_per_op"]
    frac = best_us / at["us_per_op"] if at["us_per_op"] else 0.0

    return {
        "world_size": np_,
        "hosts": np_ // 2,
        "cores": os.cpu_count(),
        "grid": grid,
        "pair_ratios_star_none_over_bf16":
            [round(r, 2) for r in pair_ratios],
        "isolated_ratios_star_none_over_bf16":
            [round(r, 2) for r in iso_ratios],
        "bf16_star_speedup": round(bf16_star, 2),
        "bf16_star_speedup_pass": bf16_star >= 1.5,
        "twolevel_vs_best_flat_none": round(tl_vs_flat_none, 2),
        "twolevel_vs_best_flat_bf16": round(tl_vs_flat_bf16, 2),
        "twolevel_pass": tl_vs_flat_none > 1.0,
        "twolevel_note": (
            "same-dtype comparison; on this one-host CI box the "
            "cross-host links are loopback, so whole-path star "
            "compression can outrun two-level's cross-leg-only "
            "compression at bf16 — on real DCN the cross links bound "
            "both and the gains compound"),
        "autotune": {**at, "best_grid_us_per_op": best_us,
                     "frac_of_best": round(frac, 3),
                     "meets_90pct": frac >= 0.9},
    }


def worker_ici(rank: int, size: int) -> None:
    """ICI-plane A/B leg (ISSUE 18): a steady single-tensor allreduce
    loop at HVD_BENCH_BYTES with the fused-psum mesh pack toggled by
    the section driver through the production knob (HOROVOD_TPU_ICI
    over a forced multi-device host mesh). Besides the median steady
    latency the report carries the engagement proof the acceptance
    gates on: ici_cycles advancing while ici_compiles stays flat
    (every steady cycle rode the PRE-compiled executable) and a zero
    hvd_data_copies_total delta on the Python side of the mesh leg."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b

    nbytes = int(os.environ.get("HVD_BENCH_BYTES", str(1 << 20)))
    steps = int(os.environ.get("HVD_BENCH_STEPS",
                               str(COMP_BENCH_STEPS)))
    hvd.init()
    n = max(1, nbytes // 4)
    x = np.full(n, float(rank + 1), np.float32)
    ssum = float(sum(range(1, size + 1)))

    out = None
    for _ in range(5):
        out = hvd.allreduce(x, average=False, name="ig")
        time.sleep(COMP_BENCH_GAP_S)
    assert abs(float(np.asarray(out)[0]) - ssum) < 1e-3
    hvd.barrier(name="ig.bar")
    rt = _b.runtime()
    s0 = rt.negotiation_cache_stats()
    c0 = hvd.metrics()["local"].get("hvd_data_copies_total",
                                    {"v": 0.0})["v"]
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        hvd.allreduce(x, average=False, name="ig")
        times.append(time.perf_counter() - t0)
        time.sleep(COMP_BENCH_GAP_S)
    s1 = rt.negotiation_cache_stats()
    c1 = hvd.metrics()["local"].get("hvd_data_copies_total",
                                    {"v": 0.0})["v"]
    out = hvd.allreduce(x, average=False, name="ig")
    assert abs(float(np.asarray(out)[0]) - ssum) < 1e-3
    _, med, _ = _quantiles(times)
    report = {
        "bytes": nbytes,
        "steps": steps,
        "us_per_op": round(med * 1e6, 1),
        "ici": os.environ.get("HOROVOD_TPU_ICI", "0"),
        "compression": os.environ.get("HOROVOD_COMPRESSION", "none"),
        "ici_cycles": s1["ici_cycles"] - s0["ici_cycles"],
        "ici_compiles_delta": s1["ici_compiles"] - s0["ici_compiles"],
        "data_copies_delta": c1 - c0,
    }
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def _ici_bench_section(np_: int) -> dict:
    """The ISSUE 18 acceptance A/B at world_size=np_, each rank
    holding a forced 8-device host mesh: HOROVOD_TPU_ICI on vs off on
    the socket-star steady loop, ISOLATED ALTERNATING legs (adjacent
    runs see similar throttle states) plus one SIMULTANEOUS pair
    (both worlds see the identical machine at every instant). The
    engagement proof — steady cycles riding the pre-compiled
    fused-psum executable with a flat compile count and zero Python-
    side data copies — is recorded from the ON worlds; the latency
    ratio is recorded without a pass threshold (on a CPU loopback
    mesh the device round trip competes with a plain numpy cast; on
    real ICI the pack/cast/reduce runs where the gradients already
    live)."""
    import threading

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags
                 + " --xla_force_host_platform_device_count=8").strip()
    base = {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1",
            "HOROVOD_TPU_METRICS": "1", "XLA_FLAGS": flags}
    on_env = dict(base, HOROVOD_TPU_ICI="1")
    big = 1 << 20

    iso = {"off": [], "on": []}
    iso_ratios = []
    engaged = []
    for _ in range(3):
        a = _run_world("ici", np_, timeout=600.0,
                       extra_env=dict(base, HVD_BENCH_BYTES=str(big)))
        b = _run_world("ici", np_, timeout=600.0,
                       extra_env=dict(on_env,
                                      HVD_BENCH_BYTES=str(big)))
        iso["off"].append(a["us_per_op"])
        iso["on"].append(b["us_per_op"])
        iso_ratios.append(a["us_per_op"] / b["us_per_op"])
        engaged.append(b)
        print(f"  isolated off {a['us_per_op']} us/op vs on "
              f"{b['us_per_op']} us/op  (ici_cycles="
              f"{b['ici_cycles']}, compiles_delta="
              f"{b['ici_compiles_delta']}, copies_delta="
              f"{b['data_copies_delta']})", flush=True)
    iso_ratios.sort()

    pair = {}

    def _go(key, env):
        pair[key] = _run_world(
            "ici", np_, timeout=600.0,
            extra_env=dict(env, HVD_BENCH_BYTES=str(big)))

    ta = threading.Thread(target=_go, args=("off", base))
    tb = threading.Thread(target=_go, args=("on", on_env))
    ta.start()
    tb.start()
    ta.join()
    tb.join()

    # the bf16 mesh leg: prescale+cast fused into the same executable
    comp = _run_world(
        "ici", np_, timeout=600.0,
        extra_env=dict(on_env, HOROVOD_COMPRESSION="bf16",
                       HVD_BENCH_BYTES=str(big)))

    cycles_ok = all(e["ici_cycles"] >= e["steps"] for e in engaged)
    compiles_ok = all(e["ici_compiles_delta"] == 0 for e in engaged)
    copies_ok = all(e["data_copies_delta"] == 0
                    for e in engaged + [comp])
    return {
        "world_size": np_,
        "devices_per_rank": 8,
        "cores": os.cpu_count(),
        "bytes": big,
        "isolated_us_per_op": iso,
        "isolated_ratios_off_over_on":
            [round(r, 2) for r in iso_ratios],
        "isolated_ratio_off_over_on":
            round(iso_ratios[len(iso_ratios) // 2], 2),
        "pair_off_us_per_op": pair["off"]["us_per_op"],
        "pair_on_us_per_op": pair["on"]["us_per_op"],
        "pair_ratio_off_over_on": round(
            pair["off"]["us_per_op"] / pair["on"]["us_per_op"], 2),
        "bf16_on_us_per_op": comp["us_per_op"],
        "steady_cycles_on_plane_pass": cycles_ok,
        "compile_count_flat_pass": compiles_ok,
        "data_copies_zero_pass": copies_ok,
        "note": (
            "CPU loopback mesh: the A/B isolates plumbing overhead, "
            "not ICI bandwidth — the device round trip competes with "
            "a host memcpy here, while on a real slice the fused "
            "executable replaces the host pack AND the cross-rank "
            "reduce"),
    }


def worker_autotune_value(rank: int, size: int) -> None:
    """Autotune VALUE demo (not just mechanics): a fusion-sensitive
    workload — many small allreduces per step — measured under (a)
    well-tuned defaults, (b) deliberately bad defaults (tiny fusion
    threshold: every tensor negotiates and executes alone), and
    (c) the same bad defaults with HOROVOD_AUTOTUNE=1, measured AFTER
    the Bayesian tuner converges. The orchestrator reports how much of
    the well-tuned throughput autotune recovers (scoring intent:
    reference parameter_manager.cc:145-171)."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b

    hvd.init()
    rt = _b.runtime()
    xs = [np.full((AUTOTUNE_VALUE_BYTES // 4,), float(rank + 1),
                  np.float32) for _ in range(AUTOTUNE_VALUE_TENSORS)]

    def step(tag):
        hs = [hvd.allreduce_async(x, average=False,
                                  name=f"av.{tag}.{i}")
              for i, x in enumerate(xs)]
        for h in hs:
            hvd.synchronize(h)

    pm = rt.parameter_manager
    if pm is not None:
        # Drive traffic until the coordinator's tuner converges;
        # rank 0 broadcasts the done flag so every rank exits the
        # loop on the same iteration.
        converged = False
        for i in range(4000):
            step(f"c{i}")
            flag = 0.0 if rank != 0 else (0.0 if pm.tuning else 1.0)
            done = hvd.broadcast(np.asarray([flag]), root_rank=0,
                                 name=f"av.done/{i}")
            if float(done[0]) == 1.0:
                converged = True
                break
        if not converged:
            if rank == 0:
                print("RESULT " + json.dumps(
                    {"error": "autotune did not converge"}), flush=True)
            hvd.shutdown()
            return

    for i in range(3):
        step(f"w{i}")
    hvd.barrier(name="av.bar")
    times = []
    for i in range(AUTOTUNE_VALUE_STEPS):
        t0 = time.perf_counter()
        step(f"m{i}")
        times.append(time.perf_counter() - t0)
    _, med, _ = _quantiles(times)
    if rank == 0:
        out = {"steps_per_sec": round(1.0 / med, 3),
               "us_per_step": round(med * 1e6, 1),
               "tensors_per_step": AUTOTUNE_VALUE_TENSORS,
               "bytes_per_tensor": AUTOTUNE_VALUE_BYTES}
        if pm is not None:
            out["tuned_fusion_threshold_bytes"] = \
                pm.fusion_threshold_bytes()
            out["tuned_cycle_time_ms"] = round(pm.cycle_time_ms(), 2)
        print("RESULT " + json.dumps(out), flush=True)
    hvd.shutdown()


def _coordinator_cpu_bench() -> dict:
    """Pure-Python microbench of the coordinator's per-cycle CPU work —
    parse N RequestLists, count readiness, construct+fuse responses,
    serialize the ResponseList — with NO transport or scheduler in the
    way. This is the per-rank cost that actually grows with world size
    on the rank-0 host, free of the 1-vCPU time-share distortion that
    inflates the world-based overhead numbers."""
    import time as _t
    sys.path.insert(0, REPO)
    from horovod_tpu.common import wire
    from horovod_tpu.common.coordinator import (
        MessageTable, construct_response, fuse_responses)
    from horovod_tpu.common.message import (
        DataType, Request, RequestList, RequestType, ResponseList)

    out = {}
    for n_ranks in (8, 64, 256):
        tensors_per_cycle = 8  # a fused step's worth of requests
        payloads = []
        for r in range(n_ranks):
            reqs = [Request(request_rank=r,
                            request_type=RequestType.ALLREDUCE,
                            tensor_type=DataType.FLOAT32,
                            tensor_name=f"grad.{t}", root_rank=-1,
                            device=-1, tensor_shape=(1024,))
                    for t in range(tensors_per_cycle)]
            payloads.append(
                wire.serialize_request_list(RequestList(reqs)))
        iters = 50
        t0 = _t.perf_counter()
        for _ in range(iters):
            table = MessageTable()
            dtypes, slices = {}, {}
            for data in payloads:
                rl = wire.parse_request_list(data)
                for req in rl.requests:
                    dtypes[req.tensor_name] = req.tensor_type
                    slices[req.tensor_name] = 1
                    table.increment_tensor_count(req, n_ranks)
            responses = [construct_response(table, name, n_ranks)
                         for name in table.pop_ready()]
            fused = fuse_responses(responses, dtypes, 64 << 20, slices)
            wire.serialize_response_list(ResponseList(fused))
        per_cycle_us = (_t.perf_counter() - t0) / iters * 1e6
        out[str(n_ranks)] = {
            "cycle_us": round(per_cycle_us, 1),
            "us_per_rank": round(per_cycle_us / n_ranks, 2),
        }
    return out


# Chips per host assumed for pod-scale projections (a v5e host).
_CHIPS_PER_HOST = 8


def _hier_fanin(n: int, local: int = _CHIPS_PER_HOST) -> int:
    """Coordinator per-cycle fan-in under the hierarchical control
    plane: host-0's local leaves plus one aggregate channel per remote
    host (common/controller.py _setup_hierarchy)."""
    if n <= local:
        return n - 1  # single host: flat
    n_hosts = (n + local - 1) // local
    return (local - 1) + (n_hosts - 1)


def _project_scaling(overheads: dict, hier_overheads: dict,
                     step_budget_ms: float) -> dict:
    """Fit the measured control-plane overhead vs coordinator FAN-IN
    and project data-parallel scaling efficiency at pod scale.

    Model: the data plane rides ICI and overlaps with backward (as the
    reference's NCCL allreduce overlaps), so the per-step cost that
    does NOT parallelize is the negotiation round. What grows with
    scale is the coordinator's serial per-channel work — its fan-in.
    The flat star has fan-in N-1; the hierarchical control plane
    (default on multihost) drops it to local_leaves + n_hosts - 1, the
    same structural move MPI_Gather's tree makes for the reference
    (reference: operations.cc:1044-1065). Fit overhead = a + b*F on
    the flat measurements (F = N-1 at np 2/4/8), estimate the relay
    hop cost from the measured hierarchical worlds' residuals, then

        efficiency(N) ~= budget / (budget + a + b*F_hier(N) + hop)

    with budget the measured single-chip step time from bench.py and
    F_hier(64) = 14 for 8 hosts x 8 chips.
    """
    ns = sorted(int(k) for k in overheads)
    fs = [float(n - 1) for n in ns]  # flat fan-in
    ys = [overheads[str(n)]["barrier_us"] for n in ns]
    mean_f = sum(fs) / len(fs)
    mean_y = sum(ys) / len(ys)
    b = (sum((f - mean_f) * (y - mean_y) for f, y in zip(fs, ys))
         / sum((f - mean_f) ** 2 for f in fs))
    a = mean_y - b * mean_f
    # Relay hop cost: how much a measured hierarchical world exceeds
    # the pure fan-in prediction (extra leaf->root->coordinator hop;
    # on this 1-vCPU host it also absorbs the extra processes'
    # scheduling). The WORST residual is charged — deliberately
    # conservative. Clamp at 0 so noise can't make hierarchy look
    # better than the fan-in model allows.
    residuals = []
    hier_meas = {}
    for layout, d in hier_overheads.items():
        pred = a + b * d["fanin"]
        residuals.append(d["barrier_us"] - pred)
        hier_meas[layout] = {
            "barrier_us": d["barrier_us"], "fanin": d["fanin"],
            "fit_pred_us": round(pred, 1),
        }
    hop = max(0.0, max(residuals)) if residuals else 0.0
    budget_us = step_budget_ms * 1e3
    proj = {}
    for n in (8, 16, 64):
        f_hier = _hier_fanin(n)
        ov = a + b * f_hier + (hop if n > _CHIPS_PER_HOST else 0.0)
        ov_flat = a + b * (n - 1)
        proj[str(n)] = {
            "fanin": f_hier,
            "overhead_us": round(ov, 1),
            "efficiency": round(budget_us / (budget_us + ov), 4),
            "flat_overhead_us": round(ov_flat, 1),
            "flat_efficiency": round(
                budget_us / (budget_us + ov_flat), 4),
        }
    return {
        "measured_overhead_us": {str(n): overheads[str(n)]
                                 for n in ns},
        "measured_hier_overhead_us": hier_meas,
        "fit_us": {"a": round(a, 2), "b_per_channel": round(b, 2),
                   "relay_hop_us": round(hop, 1),
                   "model": ("a + b*fanin (+ relay hop when "
                             "hierarchical); flat fanin = N-1, hier "
                             "fanin = local_leaves + n_hosts - 1")},
        "chips_per_host": _CHIPS_PER_HOST,
        "step_budget_ms": step_budget_ms,
        "projected": proj,
        "note": (
            "overhead measured as a pure negotiation round (barrier) "
            "over the TCP control plane on loopback at np=2/4/8 flat "
            "plus np=8 hierarchical layouts (2x4, 4x2 fake hosts); "
            "the projection assumes the data plane (XLA collectives "
            "on ICI) overlaps with backward as in bench.py's measured "
            "step, so control-plane latency is the non-parallelizing "
            "term. step_budget_ms is bench.py's measured single-chip "
            "ResNet-50 step. Loopback TCP on a 1-vCPU host "
            "overstates per-channel cost vs a real pod's NIC-to-NIC "
            "fabric (and the hierarchical worlds' relay hop runs on "
            "the SAME starved core as every other rank there, where "
            "a real pod gives each host its own CPUs), making the "
            "64-chip number conservative."),
    }


def worker_bcast_render(rank: int, size: int) -> None:
    """Microbench the two XLA broadcast renderings on one process with
    8 virtual devices: masked psum (pre-r4: full allreduce bandwidth)
    vs the binary-tree collective-permute chain (the ncclBcast role,
    reference: nccl_operations.cc:334-351). Reports execution medians
    AND the compiled HLO's bytes-accessed estimate, which is
    machine-independent evidence that the permute rendering moves less
    data."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ndev = 8
    devs = jax.devices()[:ndev]
    mesh = Mesh(np.array(devs), ("p",))
    n = 1 << 20  # 4 MiB fp32 payload per device
    root = 0

    def masked(t):
        idx = jax.lax.axis_index("p")
        return jax.lax.psum(jnp.where(idx == root, t,
                                      jnp.zeros_like(t)), "p")

    def permute(t):
        # binary-tree chain, same shape as ops/xla_ops.py broadcast
        idx = jax.lax.axis_index("p")
        v = (idx - root) % ndev
        cur = t
        k = 1
        while k < ndev:
            perm = [((u + root) % ndev, (u + k + root) % ndev)
                    for u in range(k) if u + k < ndev]
            received = jax.lax.ppermute(cur, "p", perm=perm)
            cur = jnp.where((v >= k) & (v < 2 * k), received, cur)
            k *= 2
        return cur

    x = jax.device_put(
        np.ones((ndev * n,), np.float32),
        NamedSharding(mesh, P("p")))
    report = {"bytes": n * 4, "n_devices": ndev}
    for name, body in (("masked_psum", masked), ("ppermute", permute)):
        fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("p"),
                                   out_specs=P("p"), check_vma=False))
        compiled = fn.lower(x).compile()
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            report[f"{name}_bytes_accessed"] = ca.get("bytes accessed")
        except Exception:
            pass
        jax.block_until_ready(compiled(x))  # warmup
        ts = []
        for _ in range(ALLREDUCE_ITERS):
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(x))
            ts.append((time.perf_counter() - t0) * 1e6)
        _, med, _ = _quantiles(ts)
        report[f"{name}_us"] = round(med, 1)
    if report.get("ppermute_us") and report.get("masked_psum_us"):
        report["speedup"] = round(
            report["masked_psum_us"] / report["ppermute_us"], 3)
    print("RESULT " + json.dumps(report), flush=True)


def worker_ragged_allgather(rank: int, size: int) -> None:
    """The fused variable-dim0 allgather's two renderings under heavy
    rank skew (1 big / 7 tiny), 8 virtual devices, one process: the
    padded all_gather moves N x max(dim0) while the masked-psum
    rendering moves ~2x the TRUE bytes (ops/xla_ops.py skew guard;
    reference behavior target: MPI_Allgatherv,
    mpi_operations.cc:95-173). Reports compiled bytes-accessed and
    execution medians — machine-independent evidence the guard's
    chosen side moves less data."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ndev = 8
    devs = jax.devices()[:ndev]
    mesh = Mesh(np.array(devs), ("p",))
    sn = 64                      # slice numel (row width)
    rows = [4096] + [1] * (ndev - 1)
    m = max(rows)
    # Every device's local shard is padded to max rows (SPMD inputs
    # share one shape); what differs is how much the COLLECTIVE moves.
    x = jax.device_put(np.ones((ndev * m * sn,), np.float32),
                       NamedSharding(mesh, P("p")))

    def padded(t):
        return jnp.ravel(jax.lax.all_gather(t, "p"))

    offs, acc = [], 0
    for r in range(ndev):
        offs.append(acc * sn)
        acc += rows[r]
    total = (acc + m) * sn
    offs_const = np.asarray(offs, np.int32)

    def psum_scatter(t):
        r = jax.lax.axis_index("p")
        buf = jnp.zeros((total,), t.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, t, (jnp.take(jnp.asarray(offs_const), r),))
        return jax.lax.psum(buf, "p")

    report = {"rows": rows, "slice_numel": sn, "n_devices": ndev,
              "true_MB": round(acc * sn * 4 / 1e6, 2),
              "padded_MB": round(ndev * m * sn * 4 / 1e6, 2)}
    for name, body in (("padded_gather", padded),
                       ("psum_scatter", psum_scatter)):
        fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("p"),
                                   out_specs=P(), check_vma=False))
        compiled = fn.lower(x).compile()
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            report[f"{name}_bytes_accessed"] = ca.get("bytes accessed")
        except Exception:
            pass
        jax.block_until_ready(compiled(x))  # warmup
        ts = []
        for _ in range(ALLREDUCE_ITERS):
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(x))
            ts.append((time.perf_counter() - t0) * 1e6)
        _, med, _ = _quantiles(ts)
        report[f"{name}_us"] = round(med, 1)
    pb = report.get("padded_gather_bytes_accessed")
    sb = report.get("psum_scatter_bytes_accessed")
    if pb and sb:
        report["bytes_ratio_padded_over_psum"] = round(pb / sb, 2)
    print("RESULT " + json.dumps(report), flush=True)


# -- kernel-side wire speed (PR 16: batched reactor, int8 codec, -------
# chunked relay) -------------------------------------------------------

KERNEL_GATHER_STEPS = 40
KERNEL_GATHER_BYTES = 16 << 10   # per-rank allgather block
KERNEL_RELAY_STEPS = 30
KERNEL_RELAY_BYTES = 1 << 20     # broadcast payload through the tree


def worker_kernel_gather(rank: int, size: int) -> None:
    """Batched-gather leg: an allgather loop on the socket star at
    ws=8 — every op the coordinator collects one frame from each of
    the other 7 ranks (the N-sequential-recvs pattern the reactor
    turns into one batched submission) and broadcasts the ~128 KiB
    world blob (over the MSG_ZEROCOPY threshold). Run in reactor-on /
    HOROVOD_TPU_REACTOR=0 pairs by the orchestrator; the wire bytes
    are identical, only how readiness is learned differs."""
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    n = KERNEL_GATHER_BYTES // 4
    x = np.full(n, float(rank), np.float32)
    for _ in range(5):
        hvd.allgather(x, name="kg")
    m0 = hvd.metrics()["local"]
    hvd.barrier(name="kg.b0")
    t0 = time.perf_counter()
    for _ in range(KERNEL_GATHER_STEPS):
        out = hvd.allgather(x, name="kg")
    hvd.barrier(name="kg.b1")
    elapsed = time.perf_counter() - t0
    m1 = hvd.metrics()["local"]
    assert np.asarray(out).nbytes == size * KERNEL_GATHER_BYTES
    report = {
        "bytes_per_rank": KERNEL_GATHER_BYTES,
        "steps": KERNEL_GATHER_STEPS,
        "us_per_op": round(elapsed * 1e6 / KERNEL_GATHER_STEPS, 1),
    }

    def _v(m, name):
        rec = m.get(name)
        if rec is None:
            return 0.0
        return rec["v"] if "v" in rec else rec.get("count", 0)

    if m1:
        report["data_copies"] = int(_v(m1, "hvd_data_copies_total")
                                    - _v(m0, "hvd_data_copies_total"))
        report["reactor_batches"] = int(
            _v(m1, "hvd_reactor_batch_size")
            - _v(m0, "hvd_reactor_batch_size"))
        report["zerocopy_sends"] = int(
            _v(m1, "hvd_zerocopy_sends_total")
            - _v(m0, "hvd_zerocopy_sends_total"))
    if os.environ.get("HVD_EXPECT_REACTOR") == "1" and rank == 0 and m1:
        from horovod_tpu import native as _nat
        if _nat.get() is not None:
            assert report.get("reactor_batches", 0) > 0, \
                "batched reactor never engaged (the A/B is vacuous)"
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def worker_kernel_relay(rank: int, size: int) -> None:
    """Chunked-relay leg: a 1 MiB broadcast loop on a 4-fake-host
    hierarchical world — the coordinator's frame reaches each host's
    local root, which forwards it to its leaves. With the reactor on,
    the root cuts through chunk-by-chunk (hvd_relay_frame, 256 KiB
    chunks) instead of store-and-forward; off restores the classic
    buffer-then-send relay, wire bytes identical."""
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    n = KERNEL_RELAY_BYTES // 4
    x = np.full(n, float(rank), np.float32)
    for _ in range(3):
        out = hvd.broadcast(x, root_rank=0, name="kr")
    m0 = hvd.metrics()["local"]
    hvd.barrier(name="kr.b0")
    t0 = time.perf_counter()
    for _ in range(KERNEL_RELAY_STEPS):
        out = hvd.broadcast(x, root_rank=0, name="kr")
    hvd.barrier(name="kr.b1")
    elapsed = time.perf_counter() - t0
    m1 = hvd.metrics()["local"]
    np.testing.assert_allclose(np.asarray(out)[0], 0.0)
    report = {
        "payload_bytes": KERNEL_RELAY_BYTES,
        "steps": KERNEL_RELAY_STEPS,
        "us_per_op": round(elapsed * 1e6 / KERNEL_RELAY_STEPS, 1),
    }
    if m1:
        def _v(m, name):
            rec = m.get(name)
            if rec is None:
                return 0.0
            return rec["v"] if "v" in rec else rec.get("count", 0)
        report["data_copies"] = int(_v(m1, "hvd_data_copies_total")
                                    - _v(m0, "hvd_data_copies_total"))
    if rank == 0:
        print("RESULT " + json.dumps(report), flush=True)
    hvd.shutdown()


def _kernel_codec_leg() -> dict:
    """Native int8 codec vs the numpy reference, in-process (no world
    needed: the codec is rank-local CPU work). Times the fused
    quantize+error-feedback pass and the dequantize pass on a 4 MiB
    f32 gradient against the classic numpy triple / astype-multiply
    round-trip, and spot-checks bit identity while at it."""
    import numpy as np
    sys.path.insert(0, REPO)
    from horovod_tpu import native as _nat
    from horovod_tpu.common import wire_dtype as wd

    if _nat.get() is None or not hasattr(_nat.get(), "hvd_quant8"):
        return {"skipped": "native core unavailable"}
    n = 1 << 20
    rng = np.random.RandomState(5)
    arr = rng.randn(n).astype(np.float32)
    res0 = (rng.randn(n) * 0.01).astype(np.float32)
    buf = np.empty(4 + n, np.uint8)
    out = np.empty(n, np.float32)
    reps = 21

    def _med(f):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    # bit-identity spot check on fresh residual chains
    ref_buf = np.empty_like(buf)
    wd._quantize_numpy((arr + res0), ref_buf)
    nat_buf = np.empty_like(buf)
    r = res0.copy()
    assert _nat.quant8(arr, nat_buf, residual=r, residual_out=r)
    bit_identical = bool(nat_buf.tobytes() == ref_buf.tobytes())

    res_n = res0.copy()
    t_qn = _med(lambda: _nat.quant8(arr, buf, residual=res_n,
                                    residual_out=res_n))
    state = {"res": res0.copy()}

    def _np_triple():
        comp = arr + state["res"]
        wd._quantize_numpy(comp, buf)
        scale = float(buf[:4].view(np.float32)[0])
        sent = buf[4:].view(np.int8).astype(np.float32) \
            * np.float32(scale)
        state["res"] = comp - sent

    t_qp = _med(_np_triple)
    t_dn = _med(lambda: _nat.dequant8(buf, out))

    def _np_deq():
        scale = float(buf[:4].view(np.float32)[0])
        np.multiply(buf[4:].view(np.int8).astype(np.float32),
                    np.float32(scale), out=out)

    t_dp = _med(_np_deq)
    return {
        "elems": n,
        "reps": reps,
        "bit_identical": bit_identical,
        "quant_ef_native_us": round(t_qn * 1e6, 1),
        "quant_ef_numpy_us": round(t_qp * 1e6, 1),
        "quant_speedup": round(t_qp / t_qn, 2),
        "dequant_native_us": round(t_dn * 1e6, 1),
        "dequant_numpy_us": round(t_dp * 1e6, 1),
        "dequant_speedup": round(t_dp / t_dn, 2),
        "roundtrip_speedup": round((t_qp + t_dp) / (t_qn + t_dn), 2),
    }


def _kernel_gather_discipline_leg() -> dict:
    """The batched-submission claim, isolated: ws=8 star fan-in (7
    peer channels) with every peer's 16 KiB TAG_DATA frame already in
    its socket buffer, then time ONE hvd_gather_frames_batched drain
    against the 7 sequential Channel.recv_into calls it replaced (the
    exact reactor-off fallback discipline). Pre-queuing removes the
    peers' own send scheduling — on this one-core host a live world
    measures the scheduler, not the recv discipline — so the ratio is
    pure submission cost: 1 native call + one readiness batch vs 7
    (ctypes call + poll + read chain) round trips. Legs alternate
    rep-by-rep (drift-robust), median reported."""
    import ctypes as ct
    import socket

    import numpy as np
    sys.path.insert(0, REPO)
    from horovod_tpu import native as _nat
    from horovod_tpu.common import network

    lib = _nat.get()
    if lib is None or not hasattr(lib, "hvd_gather_frames_batched"):
        return {"skipped": "native core unavailable"}
    TAG_DATA = 4
    npeers = 7
    frame = 16 << 10
    reps = 41
    pairs = [socket.socketpair() for _ in range(npeers)]
    senders = [network.Channel(a, b"") for a, _ in pairs]
    recv_chans = [network.Channel(b, b"") for _, b in pairs]
    fds = (ct.c_int * npeers)(*[b.fileno() for _, b in pairs])
    payloads = [np.full(frame // 4, float(i), np.float32)
                for i in range(npeers)]
    bufs = [np.empty(frame, np.uint8) for _ in range(npeers)]
    bufptrs = (ct.c_void_p * npeers)(*[b.ctypes.data for b in bufs])
    caps = (ct.c_int64 * npeers)(*[frame] * npeers)
    lens = (ct.c_int64 * npeers)()
    done = (ct.c_uint8 * npeers)()
    arrive = (ct.c_double * npeers)()
    batches = (ct.c_int32 * npeers)()
    nb = ct.c_int(0)
    dev_idx = ct.c_int(-1)
    dev_buf = ct.POINTER(ct.c_uint8)()
    dev_len = ct.c_int64(0)
    dev_tag = ct.c_uint8(0)
    skip = (ct.c_uint8 * 1)(5)  # TAG_PING
    sec = (ct.c_uint8 * 1)()

    def _queue():
        for ch, p in zip(senders, payloads):
            ch.send(p, TAG_DATA)

    def _drain_batched():
        ct.memset(done, 0, npeers)
        nb.value = 0
        rc = lib.hvd_gather_frames_batched(
            fds, npeers, sec, 0, TAG_DATA, bufptrs, caps, lens,
            skip, 1, 5000, -1, _nat.NULL_ON_IDLE, done, arrive,
            batches, ct.byref(nb), ct.byref(dev_idx),
            ct.byref(dev_buf), ct.byref(dev_len), ct.byref(dev_tag))
        assert rc == 0, f"batched gather rc {rc}"

    def _drain_seq():
        for ch, b in zip(recv_chans, bufs):
            tag, n = ch.recv_into(b)
            assert tag == TAG_DATA and n == frame

    tb, ts = [], []
    for _ in range(reps):
        _queue()
        t0 = time.perf_counter()
        _drain_batched()
        tb.append(time.perf_counter() - t0)
        _queue()
        t0 = time.perf_counter()
        _drain_seq()
        ts.append(time.perf_counter() - t0)
    for a, b in pairs:
        a.close()
        b.close()
    tb.sort()
    ts.sort()
    mb, ms = tb[len(tb) // 2], ts[len(ts) // 2]
    flags = _nat.build_flags()
    return {
        "peers": npeers,
        "frame_bytes": frame,
        "reps": reps,
        "backend": "io_uring" if (flags & 0x2) else "poll",
        "batched_us": round(mb * 1e6, 1),
        "sequential_us": round(ms * 1e6, 1),
        "speedup": round(ms / mb, 2),
    }


def _kernel_relay_discipline_leg() -> dict:
    """The cut-through claim, isolated: one local root relaying a
    1 MiB upstream frame to its leaf (the 4-fake-host ws=8 shape) —
    hvd_relay_frame with the production 256 KiB chunks vs the classic
    store-and-forward it replaced (Channel.recv to a fresh bytes,
    then Channel.send per child). Sender and leaf drainers run as
    threads; the measured span covers the full relay op including
    the leaves' receipt. Legs alternate rep-by-rep, median."""
    import ctypes as ct
    import socket
    import threading

    import numpy as np
    sys.path.insert(0, REPO)
    from horovod_tpu import native as _nat
    from horovod_tpu.common import network

    lib = _nat.get()
    if lib is None or not hasattr(lib, "hvd_relay_frame"):
        return {"skipped": "native core unavailable"}
    TAG_DATA = 4
    nchild = 1
    frame = 1 << 20
    chunk = 256 << 10
    reps = 15
    up_a, up_b = socket.socketpair()
    kid_pairs = [socket.socketpair() for _ in range(nchild)]
    up_send = network.Channel(up_a, b"")
    up_recv = network.Channel(up_b, b"")
    relay_kid = [network.Channel(a, b"") for a, _ in kid_pairs]
    kid_recv = [network.Channel(b, b"") for _, b in kid_pairs]
    child_fds = (ct.c_int * nchild)(*[a.fileno() for a, _ in kid_pairs])
    payload = np.random.RandomState(0).randint(0, 255, frame, np.uint8)
    buf = np.empty(frame, np.uint8)
    win = (ct.c_uint8 * frame).from_buffer(buf)
    sec = (ct.c_uint8 * 1)()
    skip = (ct.c_uint8 * 2)(7, 8)  # TAG_METRICS, TAG_TRACE
    out_len = ct.c_int64(0)
    out_tag = ct.c_uint8(0)
    spill = ct.POINTER(ct.c_uint8)()

    def _sender():
        up_send.send(payload, TAG_DATA)

    def _drainer(ch):
        tag, data = ch.recv()
        assert tag == TAG_DATA and len(data) == frame

    def _spawn():
        th = [threading.Thread(target=_sender)]
        th += [threading.Thread(target=_drainer, args=(c,))
               for c in kid_recv]
        for t in th:
            t.start()
        return th

    def _run_cut_through():
        th = _spawn()
        t0 = time.perf_counter()
        rc = lib.hvd_relay_frame(
            up_b.fileno(), child_fds, nchild, TAG_DATA,
            ct.addressof(win), frame, sec, 0, skip, 2, chunk,
            5000, -1, ct.byref(out_len), ct.byref(out_tag),
            ct.byref(spill))
        assert rc == 0, f"relay rc {rc}"
        for t in th:
            t.join()
        return time.perf_counter() - t0

    def _run_classic():
        th = _spawn()
        t0 = time.perf_counter()
        tag, data = up_recv.recv()
        assert tag == TAG_DATA
        for c in relay_kid:
            c.send(data, TAG_DATA)
        for t in th:
            t.join()
        return time.perf_counter() - t0

    tc, tp = [], []
    for _ in range(reps):
        tc.append(_run_cut_through())
        tp.append(_run_classic())
    del win
    up_a.close()
    up_b.close()
    for a, b in kid_pairs:
        a.close()
        b.close()
    tc.sort()
    tp.sort()
    mc, mp = tc[len(tc) // 2], tp[len(tp) // 2]
    return {
        "children": nchild,
        "frame_bytes": frame,
        "chunk_bytes": chunk,
        "reps": reps,
        "cut_through_us": round(mc * 1e6, 1),
        "store_forward_us": round(mp * 1e6, 1),
        "speedup": round(mp / mc, 2),
    }


def _kernel_bench_section(np_: int) -> dict:
    """The PR 16 kernel-wire A/B: batched gather at ws=np_ on the
    socket star and the chunked hierarchical relay on 4 fake hosts,
    each reactor-on vs HOROVOD_TPU_REACTOR=0 (wire bytes identical,
    recv/send discipline differs), plus the in-process int8 codec
    timing. The headline ratios come from the ISOLATED discipline
    legs (pre-queued frames, alternating reps): a one-core host
    schedules one world process at a time, so live-world A/B numbers
    measure the scheduler and sit near 1.0 regardless of recv
    discipline — they are recorded as context. World protocols as
    for --steady-only: isolated alternating legs plus SIMULTANEOUS
    pairs."""
    import threading
    base = {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1",
            "HOROVOD_TPU_METRICS": "1"}
    on_env = dict(base, HOROVOD_TPU_REACTOR="1", HVD_EXPECT_REACTOR="1")
    off_env = dict(base, HOROVOD_TPU_REACTOR="0")

    def _ab(mode, per_rank_env=None, iso_reps=3, pair_reps=2):
        iso_on, iso_off, iso_ratios = [], [], []
        for _ in range(iso_reps):
            a = _run_world(mode, np_, timeout=600.0, extra_env=on_env,
                           per_rank_env=per_rank_env)
            b = _run_world(mode, np_, timeout=600.0, extra_env=off_env,
                           per_rank_env=per_rank_env)
            iso_on.append(a)
            iso_off.append(b)
            iso_ratios.append(b["us_per_op"] / a["us_per_op"])
        ratios = []
        for _ in range(pair_reps):
            pair = {}

            def _go(key, env):
                pair[key] = _run_world(mode, np_, timeout=600.0,
                                       extra_env=env,
                                       per_rank_env=per_rank_env)

            ta = threading.Thread(target=_go, args=("on", on_env))
            tb = threading.Thread(target=_go, args=("off", off_env))
            ta.start()
            tb.start()
            ta.join()
            tb.join()
            ratios.append(pair["off"]["us_per_op"]
                          / pair["on"]["us_per_op"])
        iso_on.sort(key=lambda d: d["us_per_op"])
        iso_off.sort(key=lambda d: d["us_per_op"])
        iso_ratios.sort()
        ratios.sort()
        return {
            "reactor_on": iso_on[len(iso_on) // 2],
            "reactor_off": iso_off[len(iso_off) // 2],
            "isolated_ratios": [round(r, 2) for r in iso_ratios],
            "isolated_speedup": round(
                iso_ratios[len(iso_ratios) // 2], 2),
            "pair_ratios": [round(r, 2) for r in ratios],
        }

    gather_disc = _kernel_gather_discipline_leg()
    relay_disc = _kernel_relay_discipline_leg()
    gather = _ab("kernel_gather")
    relay = _ab("kernel_relay",
                per_rank_env=lambda r: {
                    "HOROVOD_HOSTNAME": f"fakehost{r // (np_ // 4)}"})
    codec = _kernel_codec_leg()
    out = {
        "world_size": np_,
        "cores": os.cpu_count(),
        "batched_gather": {"discipline": gather_disc, "world": gather},
        "int8_codec": codec,
        "hier_chunked_relay": {"discipline": relay_disc,
                               "world": relay},
    }
    if "speedup" in gather_disc:
        out["gather_meets_1_25x"] = gather_disc["speedup"] >= 1.25
    if "speedup" in relay_disc:
        out["relay_meets_1_2x"] = relay_disc["speedup"] >= 1.2
    if "roundtrip_speedup" in codec:
        out["codec_meets_1_3x"] = codec["roundtrip_speedup"] >= 1.3
    return out



def _run_single_proc(worker: str, timeout: float = 300.0) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker",
         worker, "--rank", "0", "--size", "1"],
        cwd=REPO, env=env, capture_output=True, timeout=timeout)
    out = p.stdout.decode()
    if p.returncode != 0:
        raise RuntimeError(f"{worker} exited {p.returncode}:\n"
                           f"{out}\n{p.stderr.decode()}")
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"no RESULT from {worker}:\n{out}")


def _run_world(mode: str, size: int, timeout: float = 600.0,
               extra_env=None, per_rank_env=None,
               allow_rc=None) -> dict:
    """``allow_rc`` maps rank -> expected returncode for ranks that
    are SUPPOSED to die (the elastic section's fault-injected victim
    exits -SIGKILL by design)."""
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # The TPU plugin's sitecustomize (gated on this knob) overrides
    # jax_platforms to "axon,cpu" at interpreter start — workers would
    # silently compute on the tunneled TPU with ~100 ms round trips.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["HOROVOD_CONTROLLER_ADDR"] = "127.0.0.1"
    env["HOROVOD_CONTROLLER_PORT"] = str(port)
    env["HOROVOD_SIZE"] = str(size)
    env.setdefault("HOROVOD_CYCLE_TIME", "1")
    # keep abort-path worlds (the elastic section SIGKILLs one) from
    # littering the checkout with flight-recorder postmortems
    env.setdefault("HOROVOD_TPU_FLIGHT_DIR",
                   tempfile.mkdtemp(prefix="hvd-flight-bench."))
    if extra_env:
        env.update(extra_env)
    procs = []
    for rank in range(size):
        e = dict(env)
        e["HOROVOD_RANK"] = str(rank)
        if per_rank_env:
            e.update(per_rank_env(rank))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", mode, "--rank", str(rank), "--size", str(size)],
            cwd=REPO, env=e, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise RuntimeError(f"{mode} np={size} rank {rank} timed out")
        outs.append(out.decode())
        want = allow_rc.get(rank, 0) if allow_rc else 0
        if p.returncode != want:
            raise RuntimeError(
                f"{mode} np={size} rank {rank} exited {p.returncode}:\n"
                + outs[-1])
    for line in outs[0].splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"no RESULT line from rank 0:\n{outs[0]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=8)
    ap.add_argument("--worker",
                    choices=["allreduce", "train", "fixed_compute",
                             "bcast_render", "ragged_allgather",
                             "overhead", "autotune_value", "cache",
                             "elastic", "compression",
                             "compression_autotune", "overlap",
                             "trace_toggle", "multitenant",
                             "kernel_gather", "kernel_relay",
                             "selfop_sync", "ici"])
    ap.add_argument("--rank", type=int)
    ap.add_argument("--size", type=int)
    ap.add_argument("--skip-variants", action="store_true",
                    help="only bench the default (shm) data plane")
    ap.add_argument("--cache-only", action="store_true",
                    help="run just the negotiation-cache A/B and merge "
                         "it into the existing RESULTS_cpu.json")
    ap.add_argument("--metrics-only", action="store_true",
                    help="run just the metrics-plane overhead A/B and "
                         "merge it into the existing RESULTS_cpu.json")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="run just the world-trace-plane overhead A/B "
                         "(default-on flight recorder, then full "
                         "tracing armed, each vs a dark baseline; "
                         "simultaneous-pair protocol, same as "
                         "--metrics-only) and merge it into the "
                         "existing RESULTS_cpu.json")
    ap.add_argument("--steady-only", action="store_true",
                    help="run just the zero-copy steady-bucket A/B "
                         "(HOROVOD_TPU_ZERO_COPY on/off) and merge it "
                         "into the existing RESULTS_cpu.json")
    ap.add_argument("--elastic", action="store_true",
                    help="run just the elastic recovery section "
                         "(steady us/op before a SIGKILL, the "
                         "re-rendezvous gap, us/op after the shrink; "
                         "recovery asserted < 2x heartbeat timeout) "
                         "and merge it into RESULTS_cpu.json")
    ap.add_argument("--overlap", action="store_true",
                    help="run just the overlap-tier A/B (bucketed "
                         "ready-order dispatch + in-flight cycles vs "
                         "the synchronous steady path, injected "
                         "compute calibrated to wire time; isolated + "
                         "simultaneous-pair protocols) and merge it "
                         "into RESULTS_cpu.json")
    ap.add_argument("--multitenant", action="store_true",
                    help="run just the multi-tenant section (two "
                         "tenants sharing one fleet vs an isolated "
                         "single-tenant baseline, isolated-leg "
                         "protocol, plus the 3:1 priority-weight "
                         "cycle-share shift) and merge it into "
                         "RESULTS_cpu.json")
    ap.add_argument("--kernel", action="store_true",
                    help="run just the kernel-side wire-speed A/B "
                         "(batched reactor gather at ws=np, chunked "
                         "hierarchical relay on np//2 fake hosts, "
                         "each vs HOROVOD_TPU_REACTOR=0; isolated + "
                         "simultaneous-pair protocols; plus the "
                         "in-process native int8 codec timing) and "
                         "merge it into RESULTS_cpu.json")
    ap.add_argument("--selfop", action="store_true",
                    help="run just the self-operation rejoin-sync A/B "
                         "(chunked tree-pipelined State.sync vs the "
                         "legacy per-key negotiated broadcast over "
                         "the same 64 MiB model-shaped state, socket "
                         "plane; zero-copy delta recorded) and merge "
                         "it into RESULTS_cpu.json")
    ap.add_argument("--ici", action="store_true",
                    help="run just the ICI-plane A/B (fused-psum "
                         "steady pack over a forced 8-device host "
                         "mesh, HOROVOD_TPU_ICI on/off; isolated-"
                         "alternating + simultaneous-pair protocols; "
                         "engagement proof: pre-compiled executable "
                         "reuse + zero data copies) and merge it "
                         "into RESULTS_cpu.json")
    ap.add_argument("--compression", action="store_true",
                    help="run just the wire-compression/two-level "
                         "grid ((algorithm x dtype x bucket) medians "
                         "on a fake multi-host world, isolated + "
                         "simultaneous-pair protocols, plus the "
                         "autotuner-convergence run) and merge it "
                         "into RESULTS_cpu.json")
    args = ap.parse_args()

    if args.worker:
        {"allreduce": worker_allreduce,
         "train": worker_train,
         "fixed_compute": worker_fixed_compute,
         "bcast_render": worker_bcast_render,
         "ragged_allgather": worker_ragged_allgather,
         "autotune_value": worker_autotune_value,
         "cache": worker_cache,
         "elastic": worker_elastic,
         "compression": worker_compression,
         "compression_autotune": worker_compression_autotune,
         "overlap": worker_overlap,
         "trace_toggle": worker_trace_toggle,
         "multitenant": worker_multitenant,
         "kernel_gather": worker_kernel_gather,
         "kernel_relay": worker_kernel_relay,
         "selfop_sync": worker_selfop_sync,
         "ici": worker_ici,
         "overhead": worker_overhead}[args.worker](
             args.rank, args.size)
        return

    np_ = args.np
    cores = os.cpu_count() or 1
    results_path = os.path.join(REPO, "benchmarks", "RESULTS_cpu.json")

    if args.elastic:
        print(f"== elastic recovery (np={np_} -> {np_ - 1}, SIGKILL "
              f"at op {ELASTIC_BENCH_KILL_OP}) ==", flush=True)
        el = _elastic_bench_section(np_)
        print(f"  pre-kill {el['pre_kill_us_per_op']} us/op   "
              f"re-rendezvous gap {el['rendezvous_gap_ms']} ms "
              f"(budget {el['recovery_budget_ms']} ms)   "
              f"post-shrink {el['post_shrink_us_per_op']} us/op",
              flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["elastic_recovery"] = el
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged elastic_recovery into {results_path}")
        return

    if args.selfop:
        np_so = min(np_, 4)
        mib = SELFOP_SYNC_KEYS * SELFOP_SYNC_KEY_ELEMS * 4 // 2**20
        print(f"== self-operation rejoin sync A/B (np={np_so}, "
              f"{SELFOP_SYNC_KEYS}-key {mib} MiB state, socket "
              f"plane) ==", flush=True)
        so = _selfop_bench_section(np_so)
        print(f"  fast {so['fast_sync_ms']} ms   legacy "
              f"{so['legacy_sync_ms']} ms   speedup {so['speedup']}x "
              f"(>=3x pass={so['meets_3x']})   data-copies delta "
              f"{so['fast_data_copies_delta']} "
              f"(clean={so['zero_copy_clean']})", flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["selfop"] = so
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged selfop into {results_path}")
        return

    if args.multitenant:
        np_mt = min(np_, 4)  # ws>=4 per acceptance; 2 runtimes/proc
        print(f"== multi-tenant shared fleet (np={np_mt}, two tenants "
              f"spanning all ranks) ==", flush=True)
        mt = _multitenant_bench_section(np_mt)
        print(f"  isolated {mt['isolated_ops_per_s']} ops/s   shared "
              f"A {mt['shared_vs_isolated']['jobA']:.0%} / B "
              f"{mt['shared_vs_isolated']['jobB']:.0%} of isolated "
              f"(>=60% pass={mt['meets_60pct']})   3:1 share shift "
              f"{mt['share_shift_3to1_vs_equal']}x vs equal weights "
              f"(pass={mt['weights_shift_share']})", flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["multitenant"] = mt
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged multitenant into {results_path}")
        return

    if args.kernel:
        print(f"== kernel-side wire speed A/B (np={np_}, "
              f"reactor on/off) ==", flush=True)
        kw = _kernel_bench_section(np_)
        g, r, c = (kw["batched_gather"], kw["hier_chunked_relay"],
                   kw["int8_codec"])
        print(f"  batched gather {g['discipline'].get('speedup', 'n/a')}x "
              f"(>=1.25 pass={kw.get('gather_meets_1_25x')}, "
              f"world {g['world']['isolated_speedup']}x)   "
              f"int8 codec roundtrip "
              f"{c.get('roundtrip_speedup', 'n/a')}x "
              f"(>=1.3 pass={kw.get('codec_meets_1_3x')}, "
              f"bit_identical={c.get('bit_identical')})   "
              f"chunked relay {r['discipline'].get('speedup', 'n/a')}x "
              f"(>=1.2 pass={kw.get('relay_meets_1_2x')}, "
              f"world {r['world']['isolated_speedup']}x)   copies on="
              f"{g['world']['reactor_on'].get('data_copies')}",
              flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["kernel_wire"] = kw
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged kernel_wire into {results_path}")
        return

    if args.compression:
        print(f"== wire compression + two-level grid (np={np_}, "
              f"{np_ // 2} fake hosts) ==", flush=True)
        cp = _compression_bench_section(np_)
        print(f"  bf16 star speedup {cp['bf16_star_speedup']}x "
              f"(>=1.5 pass={cp['bf16_star_speedup_pass']})   "
              f"twolevel vs best flat "
              f"{cp['twolevel_vs_best_flat_none']}x @none / "
              f"{cp['twolevel_vs_best_flat_bf16']}x @bf16 "
              f"(pass={cp['twolevel_pass']})   autotuned "
              f"{cp['autotune']['frac_of_best']:.0%} of best grid "
              f"point (>=90% pass={cp['autotune']['meets_90pct']})",
              flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["compression"] = cp
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged compression into {results_path}")
        return

    if args.ici:
        np_ici = min(np_, 2)  # each rank carries its own 8-dev mesh
        print(f"== ICI fused-psum plane A/B (np={np_ici}, 8 forced "
              f"devices per rank) ==", flush=True)
        ic = _ici_bench_section(np_ici)
        print(f"  isolated off/on ratio "
              f"{ic['isolated_ratio_off_over_on']}x   pair "
              f"{ic['pair_ratio_off_over_on']}x   steady-on-plane "
              f"pass={ic['steady_cycles_on_plane_pass']}   compiles "
              f"flat pass={ic['compile_count_flat_pass']}   copies "
              f"zero pass={ic['data_copies_zero_pass']}", flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["ici"] = ic
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged ici into {results_path}")
        return

    if args.overlap:
        print(f"== overlap tier A/B (np={np_}, compute ~= wire) ==",
              flush=True)
        ov = _overlap_bench_section(np_)
        print(f"  overlap {ov['overlap_on']['us_per_step']} us/step "
              f"vs flat {ov['overlap_off']['us_per_step']} us/step   "
              f"isolated speedup {ov['isolated_speedup']}x "
              f"(>=1.3 pass={ov['meets_1_3x']})   overlap fraction "
              f"{ov['overlap_fraction']} "
              f"(>=0.5 pass={ov['meets_fraction_50pct']})   "
              f"zero copies={ov['zero_copies']}", flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["overlap"] = ov
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged overlap into {results_path}")
        return

    if args.steady_only:
        print(f"== zero-copy native data plane A/B (np={np_}, steady "
              f"bucket) ==", flush=True)
        zc = _zero_copy_bench_section(np_)
        print(f"  zero-copy on {zc['zero_copy_on']['us_per_op']} "
              f"us/op (native steady cycles "
              f"{zc['zero_copy_on'].get('native_steady_cycles')})   "
              f"off {zc['zero_copy_off']['us_per_op']} us/op   "
              f"speedup {zc.get('speedup')}x", flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["zero_copy_steady"] = zc
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged zero_copy_steady into {results_path}")
        return

    if args.trace_overhead:
        print(f"== world-trace-plane overhead A/B (np={np_}, steady "
              f"bucket) ==", flush=True)
        to = _trace_bench_section(np_)
        print(f"  flight recorder (default-on) overhead "
              f"{to['flight_overhead_pct']}% "
              f"(<=1 pass={to['flight_within_1pct']})   full tracing "
              f"armed {to['trace_overhead_pct']}% "
              f"(<=5 pass={to['trace_within_5pct']})   data copies "
              f"with trace={to['trace_data_copies']} "
              f"(zero pass={to['zero_copies_with_trace']})",
              flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["trace_overhead"] = to
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged trace_overhead into {results_path}")
        return

    if args.metrics_only:
        print(f"== metrics-plane overhead A/B (np={np_}, steady "
              f"bucket) ==", flush=True)
        mo = _metrics_bench_section(np_)
        print(f"  metrics off {mo['metrics_off']['us_per_op']} us/op"
              f"   on {mo['metrics_on']['us_per_op']} us/op   "
              f"enabled overhead {mo['enabled_overhead_pct']}%",
              flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["metrics_overhead"] = mo
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged metrics_overhead into {results_path}")
        return

    if args.cache_only:
        print(f"== negotiation cache A/B (np={np_}, socket star) ==",
              flush=True)
        nc = _cache_bench_section(np_)
        print(f"  cache on {nc['cache_on']['us_per_op']} us/op "
              f"(hit rate {nc['cache_on'].get('hit_rate')})   off "
              f"{nc['cache_off']['us_per_op']} us/op   speedup "
              f"{nc.get('speedup')}x", flush=True)
        try:
            with open(results_path) as fh:
                merged = json.load(fh)
        except (OSError, ValueError):
            merged = {}
        merged["negotiation_cache"] = nc
        with open(results_path, "w") as fh:
            json.dump(merged, fh, indent=2)
            fh.write("\n")
        print(f"merged negotiation_cache into {results_path}")
        return

    sweeps = {}
    variant_names = ["shm"] if args.skip_variants else list(VARIANTS)
    for variant in variant_names:
        print(f"== allreduce medians (np={np_}, data plane: {variant}) "
              f"==", flush=True)
        coll = _run_world("allreduce", np_, extra_env=VARIANTS[variant])
        for row in coll["allreduce"]:
            print(f"  {row['bytes']:>9} B  {row['us_per_op']:>10} us  "
                  f"(p25 {row['us_p25']:>9} / p75 {row['us_p75']:>9})  "
                  f"bus {row['busbw_MBps']:>8} MB/s", flush=True)
        f = coll["fused"]
        print(f"  fused {f['tensors']}x{f['bytes'] // f['tensors']} B  "
              f"{f['us_per_batch']} us/batch  bus {f['busbw_MBps']} MB/s")
        sweeps[variant] = coll

    def _median_world(mode, size, runs=3):
        """Whole-world repeats: a single world can land entirely inside
        one of this host's multi-second stall windows (see module
        docstring), so the scaling legs take the median of three."""
        vals = [_run_world(mode, size)["steps_per_sec"]
                for _ in range(runs)]
        return {"steps_per_sec": sorted(vals)[len(vals) // 2],
                "runs": vals}

    print(f"== scaling (data-parallel MLP, real compute on "
          f"{cores} core(s)) ==", flush=True)
    t1 = _median_world("train", 1)
    tn = _median_world("train", np_)
    eff = tn["steps_per_sec"] / t1["steps_per_sec"]
    ideal = min(cores, np_) / np_
    print(f"  np=1: {t1['steps_per_sec']} steps/s   "
          f"np={np_}: {tn['steps_per_sec']} steps/s   "
          f"raw efficiency {eff:.1%}   "
          f"(ceiling on this host: {ideal:.1%} — compute time-shares "
          f"{cores} core(s); vs-achievable {min(eff / ideal, 1.0):.1%})",
          flush=True)

    bc = {}
    if not args.skip_variants:
        print("== broadcast rendering (8 virtual devices, 4 MiB) ==",
              flush=True)
        try:
            bc = _run_single_proc("bcast_render")
            print(f"  masked psum {bc.get('masked_psum_us')} us   "
                  f"ppermute {bc.get('ppermute_us')} us   "
                  f"speedup {bc.get('speedup')}x   bytes accessed "
                  f"{bc.get('masked_psum_bytes_accessed')} -> "
                  f"{bc.get('ppermute_bytes_accessed')}", flush=True)
        except Exception as e:
            # Record, don't abort: the already-measured sweeps must
            # still reach RESULTS_cpu.json.
            bc = {"error": repr(e)}
            print(f"  bcast_render failed: {e!r}", flush=True)

    rag = {}
    if not args.skip_variants:
        print("== ragged allgather skew guard (1 big / 7 tiny, 8 "
              "virtual devices) ==", flush=True)
        try:
            rag = _run_single_proc("ragged_allgather")
            print(f"  padded gather {rag.get('padded_gather_us')} us   "
                  f"psum scatter {rag.get('psum_scatter_us')} us   "
                  f"(true {rag.get('true_MB')} MB vs padded "
                  f"{rag.get('padded_MB')} MB)", flush=True)
        except Exception as e:
            rag = {"error": repr(e)}
            print(f"  ragged_allgather failed: {e!r}", flush=True)

    av = {}
    if not args.skip_variants:
        print("== autotune value (bad defaults -> tuned recovery, "
              "np=4) ==", flush=True)
        try:
            csv_path = os.path.join(REPO, "benchmarks",
                                    "autotune_value.csv")
            well = _run_world("autotune_value", 4, timeout=900.0)
            bad = _run_world("autotune_value", 4, timeout=900.0,
                             extra_env={
                                 "HOROVOD_FUSION_THRESHOLD": "1024"})
            rec = _run_world("autotune_value", 4, timeout=900.0,
                             extra_env={
                                 "HOROVOD_FUSION_THRESHOLD": "1024",
                                 "HOROVOD_AUTOTUNE": "1",
                                 "HOROVOD_AUTOTUNE_LOG": csv_path})
            av = {"well_tuned": well, "bad_defaults": bad,
                  "autotuned_from_bad": rec,
                  "autotune_log": "benchmarks/autotune_value.csv"}
            if "steps_per_sec" in well and "steps_per_sec" in bad:
                av["bad_fraction"] = round(
                    bad["steps_per_sec"] / well["steps_per_sec"], 3)
            if "steps_per_sec" in well and "steps_per_sec" in rec:
                av["recovered_fraction"] = round(
                    rec["steps_per_sec"] / well["steps_per_sec"], 3)
            print(f"  well-tuned {well.get('steps_per_sec')} steps/s   "
                  f"bad {bad.get('steps_per_sec')}   autotuned "
                  f"{rec.get('steps_per_sec')}   recovered "
                  f"{av.get('recovered_fraction')}", flush=True)
        except Exception as e:
            av = {"error": repr(e)}
            print(f"  autotune_value failed: {e!r}", flush=True)

    nc = {}
    if not args.skip_variants:
        print(f"== negotiation cache A/B (np={np_}, socket star) ==",
              flush=True)
        try:
            nc = _cache_bench_section(np_)
            print(f"  cache on {nc['cache_on']['us_per_op']} us/op "
                  f"(hit rate {nc['cache_on'].get('hit_rate')})   off "
                  f"{nc['cache_off']['us_per_op']} us/op   speedup "
                  f"{nc.get('speedup')}x", flush=True)
        except Exception as e:
            nc = {"error": repr(e)}
            print(f"  negotiation cache bench failed: {e!r}",
                  flush=True)

    mo = {}
    if not args.skip_variants:
        print(f"== metrics-plane overhead A/B (np={np_}, steady "
              f"bucket) ==", flush=True)
        try:
            mo = _metrics_bench_section(np_)
            print(f"  metrics off {mo['metrics_off']['us_per_op']} "
                  f"us/op   on {mo['metrics_on']['us_per_op']} us/op"
                  f"   enabled overhead "
                  f"{mo['enabled_overhead_pct']}%", flush=True)
        except Exception as e:
            mo = {"error": repr(e)}
            print(f"  metrics overhead bench failed: {e!r}",
                  flush=True)

    print(f"== scaling (fixed {FIXED_COMPUTE_S * 1e3:.0f} ms compute — "
          f"parallelizable, isolates comm overhead) ==", flush=True)
    f1 = _median_world("fixed_compute", 1)
    fn = _median_world("fixed_compute", np_)
    fc_eff = fn["steps_per_sec"] / f1["steps_per_sec"]
    print(f"  np=1: {f1['steps_per_sec']} steps/s   "
          f"np={np_}: {fn['steps_per_sec']} steps/s   "
          f"efficiency {fc_eff:.1%}", flush=True)

    projection = {}
    if not args.skip_variants:
        print("== control-plane overhead (negotiation round medians) "
              "==", flush=True)
        try:
            overheads = {}
            for n in sorted({2, 4, np_}):
                vals = [_run_world(
                    "overhead", n,
                    extra_env={"HOROVOD_TPU_HIER_CONTROLLER": "0"})
                    for _ in range(3)]
                vals.sort(key=lambda d: d["barrier_us"])
                overheads[str(n)] = vals[1]  # median of world medians
                print(f"  np={n}: barrier "
                      f"{overheads[str(n)]['barrier_us']} us   4KiB "
                      f"allreduce "
                      f"{overheads[str(n)]['small_allreduce_us']} us",
                      flush=True)
            # Hierarchical layouts at np=8: ranks grouped onto fake
            # hosts so leaves relay through their local root. Both
            # layouts have coordinator fan-in 4 (vs 7 flat).
            hier_overheads = {}
            for layout, per_host in (("2x4", 4), ("4x2", 2)):
                n_hosts = np_ // per_host
                fanin = (per_host - 1) + (n_hosts - 1)
                vals = [_run_world(
                    "overhead", np_,
                    extra_env={"HOROVOD_TPU_HIER_CONTROLLER": "1"},
                    per_rank_env=lambda r, ph=per_host: {
                        "HOROVOD_HOSTNAME": f"benchhost{r // ph}"})
                    for _ in range(3)]
                vals.sort(key=lambda d: d["barrier_us"])
                hier_overheads[layout] = dict(vals[1], fanin=fanin)
                print(f"  np={np_} hier {layout} (fan-in {fanin}): "
                      f"barrier {vals[1]['barrier_us']} us", flush=True)
            # step budget = bench.py's most recent single-chip
            # measurement (batch 256 at the reported img/s/chip)
            step_budget_ms = 103.6
            bench_files = sorted(
                f for f in os.listdir(REPO)
                if f.startswith("BENCH_r") and f.endswith(".json"))
            if bench_files:
                try:
                    with open(os.path.join(
                            REPO, bench_files[-1])) as fh:
                        parsed = json.load(fh).get("parsed") or {}
                    if parsed.get("value"):
                        step_budget_ms = round(
                            256.0 / parsed["value"] * 1e3, 2)
                except Exception:
                    pass
            projection = _project_scaling(overheads, hier_overheads,
                                          step_budget_ms)
            try:
                projection["coordinator_cpu"] = _coordinator_cpu_bench()
            except Exception as e:
                # a microbench failure must not discard the projection
                projection["coordinator_cpu"] = {"error": repr(e)}
            print(f"  fit {projection['fit_us']}   projected 64-chip "
                  f"efficiency "
                  f"{projection['projected']['64']['efficiency']:.1%}"
                  f" against a {step_budget_ms} ms step", flush=True)
            cc = projection["coordinator_cpu"]
            if "error" not in cc:
                print("  coordinator CPU (no transport): "
                      + "   ".join(f"np={n}: {v['cycle_us']} us/cycle"
                                   for n, v in cc.items()), flush=True)
        except Exception as e:
            projection = {"error": repr(e)}
            print(f"  overhead projection failed: {e!r}", flush=True)

    out = {
        "world_size": np_,
        "cpu_count": cores,
        "allreduce": sweeps["shm"]["allreduce"],
        "fused": sweeps["shm"]["fused"],
        "allreduce_variants": {
            v: sweeps[v]["allreduce"] for v in sweeps},
        "train_steps_per_sec": {"1": t1["steps_per_sec"],
                                str(np_): tn["steps_per_sec"]},
        "scaling_efficiency": round(eff, 4),
        "timeshare_ideal": round(ideal, 4),
        "efficiency_vs_achievable": round(min(eff / ideal, 1.0), 4),
        "broadcast_rendering": bc,
        "ragged_allgather": rag,
        "autotune_value": av,
        "negotiation_cache": nc,
        "metrics_overhead": mo,
        "projected_scaling": projection,
        "fixed_compute_ms": FIXED_COMPUTE_S * 1e3,
        "fixed_compute_steps_per_sec": {
            "1": f1["steps_per_sec"], str(np_): fn["steps_per_sec"]},
        "fixed_compute_scaling_efficiency": round(fc_eff, 4),
        "note": (
            "cpu_count==1 hosts time-share all ranks' compute on one "
            "core, capping steps_N/steps_1 at timeshare_ideal for ANY "
            "framework; fixed_compute_scaling_efficiency isolates the "
            "framework's communication overhead with parallelizable "
            "compute, and is the number comparable to the reference's "
            "published scaling efficiencies (one GPU per rank). The "
            "host additionally burst-throttles sustained CPU/memory "
            "load after ~1-2 s, which hits the 16 MiB shm/star legs "
            "specifically, so those rows vary several-fold between runs "
            "(e.g. shm 16 MiB medians of ~160-650 ms across "
            "sweeps); the ring's lower CPU intensity makes its "
            "16 MiB row the most stable, ~230-290 ms across runs."),
    }
    path = os.path.join(REPO, "benchmarks", "RESULTS_cpu.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
