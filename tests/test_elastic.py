"""Unit tests for elastic worlds (common/elastic.py): wire codecs,
membership, State semantics, election building blocks, fault-injection
rendezvous triggers, the re-entrant runtime teardown, and the
launcher's blacklist/backoff supervision — everything that doesn't
need a real multi-process world (tests/test_multiprocess.py covers
those)."""

import os
import time

import numpy as np
import pytest

from horovod_tpu.common import elastic, faults, wire
from horovod_tpu.common.config import Config
from horovod_tpu.common.status import WorldAbortedError


@pytest.fixture(autouse=True)
def _clean_elastic_state():
    yield
    elastic.reset()
    faults.clear()


def _cfg(**kw) -> Config:
    c = Config()
    c.elastic_enabled = True
    for k, v in kw.items():
        setattr(c, k, v)
    return c


# -- wire codecs -------------------------------------------------------------

def test_manifest_roundtrip():
    payload = wire.serialize_elastic_manifest(
        elastic.MANIFEST_SURVIVOR, 7, 3, "10.0.0.9", 41234)
    m = wire.parse_elastic_manifest(payload)
    assert m == {"kind": elastic.MANIFEST_SURVIVOR, "gen": 7,
                 "old_rank": 3, "host": "10.0.0.9",
                 "elastic_port": 41234}


def test_verdict_roundtrip_with_lost_and_joined():
    payload = wire.serialize_elastic_verdict(
        elastic.VERDICT_OK, 2, 1, 3, "host-a", 555, "kill",
        lost=["gen:1 rank 2 (h)"], joined=1, coord_elastic_port=777)
    v = wire.parse_elastic_verdict(payload)
    assert v["verdict"] == elastic.VERDICT_OK
    assert (v["gen"], v["rank"], v["size"]) == (2, 1, 3)
    assert (v["addr"], v["port"]) == ("host-a", 555)
    assert v["lost"] == ["gen:1 rank 2 (h)"] and v["joined"] == 1
    assert v["coord_elastic_port"] == 777


@pytest.mark.parametrize("cut", [1, 5, 9, 14])
def test_truncated_elastic_frames_fail_as_transport_errors(cut):
    payload = wire.serialize_elastic_manifest(1, 1, 1, "h", 1)
    with pytest.raises(ConnectionError):
        wire.parse_elastic_manifest(payload[:cut])
    payload = wire.serialize_elastic_verdict(0, 1, 1, 2, "h", 1, "c")
    with pytest.raises(ConnectionError):
        wire.parse_elastic_verdict(payload[:cut])


# -- membership --------------------------------------------------------------

def test_membership_install_and_blacklist_accumulates():
    m = elastic.Membership()
    m.install(1, 3, {0: ("a", 1), 1: ("b", 2), 2: ("c", 3)},
              lost=["gen:0 rank 3 (d)"])
    assert m.generation == 1 and m.size == 3
    m.install(2, 2, {0: ("a", 1), 1: ("c", 3)},
              lost=["gen:1 rank 1 (b)"])
    assert m.blacklist == ["gen:0 rank 3 (d)", "gen:1 rank 1 (b)"]
    assert m.rank_table == {0: ("a", 1), 1: ("c", 3)}


def test_context_world_line_mentions_resize_state():
    ctx = elastic.ensure_context(_cfg(), b"")
    ctx.apply_membership(2, 0, 2, {0: ("a", 1), 1: ("b", 2)},
                         lost=["gen:1 rank 2 (c)"])
    ctx.last_resize_cause = "rank 2 died"
    line = ctx.world_line()
    assert "generation 2" in line and "world size 2" in line
    assert "rank 2 died" in line and "gen:1 rank 2 (c)" in line


def test_generation_seeds_response_cache_epoch():
    from horovod_tpu.common.coordinator import ResponseCache
    assert ResponseCache(4).epoch == 0
    assert ResponseCache(4, epoch0=3 << 32).epoch == 3 << 32


# -- State -------------------------------------------------------------------

def test_state_commit_restore_roundtrip():
    s = elastic.State(params=np.arange(4.0), batch=0)
    s.params = s.params + 10.0
    s.batch = 5
    s.commit()
    s.params = s.params * 0.0
    s.batch = 99
    s.restore()
    np.testing.assert_array_equal(s.params, np.arange(4.0) + 10.0)
    assert s.batch == 5


def test_state_unknown_attribute_raises():
    s = elastic.State(a=1)
    with pytest.raises(AttributeError):
        s.nope


# -- election building blocks ------------------------------------------------

def test_follow_barrier_refused_dial_means_dead():
    import socket
    ctx = elastic.ensure_context(_cfg(), b"")
    # a port with no listener: connection refused == candidate dead
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    ctx.apply_membership(0, 1, 2, {0: ("127.0.0.1", dead_port),
                                   1: ("127.0.0.1", ctx.port)})
    res = elastic._follow_barrier(ctx, 0, time.monotonic() + 1.0)
    assert res == "dead"


def test_rendezvous_without_membership_aborts_for_real():
    ctx = elastic.ensure_context(_cfg(elastic_window_s=0.3), b"")
    ctx.rank = 1  # never installed a table: no candidates at all
    with pytest.raises(WorldAbortedError) as ei:
        elastic.rendezvous(0, "unit test")
    assert "re-rendezvous failed" in str(ei.value)


def test_min_world_floor_aborts_for_real():
    ctx = elastic.ensure_context(
        _cfg(elastic_window_s=0.5, elastic_min_world=3), b"")
    ctx.apply_membership(0, 0, 2, {0: ("127.0.0.1", ctx.port),
                                   1: ("127.0.0.1", 1)})
    with pytest.raises(WorldAbortedError) as ei:
        # rank 0 coordinates; rank 1 is dead; 1 survivor < floor of 3
        elastic.rendezvous(1, "unit test")
    assert "HOROVOD_ELASTIC_MIN_WORLD" in str(ei.value)


# -- config knobs ------------------------------------------------------------

def test_elastic_knobs_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_ELASTIC", "1")
    monkeypatch.setenv("HOROVOD_ELASTIC_WINDOW", "12.5")
    monkeypatch.setenv("HOROVOD_ELASTIC_MIN_WORLD", "2")
    monkeypatch.setenv("HOROVOD_TPU_ELASTIC_PORT", "4100")
    monkeypatch.setenv("HOROVOD_ELASTIC_JOIN", "1")
    monkeypatch.setenv("HOROVOD_ELASTIC_JOIN_ADDR", "10.1.2.3")
    monkeypatch.setenv("HOROVOD_ELASTIC_JOIN_PORT", "4200")
    c = Config.from_env()
    assert c.elastic_enabled and c.elastic_join
    assert c.elastic_window_s == 12.5 and c.elastic_min_world == 2
    assert c.elastic_port == 4100
    assert (c.elastic_join_addr, c.elastic_join_port) == \
        ("10.1.2.3", 4200)


def test_elastic_default_off_means_no_context():
    c = Config.from_env()
    assert not c.elastic_enabled
    assert elastic.context() is None and elastic.generation() == 0


# -- fault-injection rendezvous trigger --------------------------------------

def test_fault_spec_rdzv_trigger_parses():
    (f,) = faults.parse_spec("rank=2:delay:rdzv=1:ms=1")
    assert f.at_rdzv == 1 and f.rank == 2 and f.action == "delay"


def test_fault_needs_exactly_one_trigger():
    with pytest.raises(ValueError):
        faults.Fault("kill", at_cycle=1, at_rdzv=1)
    with pytest.raises(ValueError):
        faults.Fault("kill")
    with pytest.raises(ValueError):
        faults.Fault("sever", at_rdzv=1)  # no channel during rdzv


def test_tick_rendezvous_fires_scoped_fault(monkeypatch):
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    fired = faults.install("delay", rank=4, at_rdzv=1, ms=1)
    other = faults.install("delay", rank=5, at_rdzv=1, ms=1)
    faults.tick_rendezvous(4)
    assert fired.fired and not other.fired


# -- re-entrant runtime teardown (satellite bugfix) --------------------------

def test_runtime_teardown_is_reentrant_and_idempotent():
    import horovod_tpu as hvd
    from horovod_tpu.common import basics
    hvd.init()
    rt = basics.runtime()
    hvd.shutdown()          # first teardown via the background loop
    rt._teardown()          # second entry: must be a clean no-op
    rt._teardown()          # and a third
    assert rt._teardown_started and not rt.alive


def test_handle_ids_unique_across_world_generations():
    """An elastic resize replaces the HandleManager; a stale handle
    from the old world must never collide with a fresh one (it would
    silently return the wrong tensor) — ids continue from a
    process-lifetime watermark instead."""
    from horovod_tpu.common.tensor_table import HandleManager
    old = HandleManager()
    stale = old.allocate()
    new = HandleManager()  # what an elastic re-init builds
    fresh = new.allocate_many(3)
    assert stale not in fresh
    with pytest.raises(ValueError):
        new.wait(stale)
    # the two ValueError cases stay distinguishable: a pre-resize id
    # is provably stale, a never-allocated current-gen id is misuse
    assert new.from_prior_generation(stale)
    assert not new.from_prior_generation(fresh[-1] + 100)


# -- launcher supervision (blacklist + backoff + respawn-as-joiner) ----------

class _FakeProc:
    """Popen-like double the supervision loop can reap."""

    def __init__(self, rc_after=None):
        self.rc_after = rc_after  # (deadline, rc) or None = immortal
        self.terminated = False

    def poll(self):
        if self.terminated:
            return 0
        if self.rc_after and time.monotonic() >= self.rc_after[0]:
            return self.rc_after[1]
        return None

    def terminate(self):
        self.terminated = True
        self.rc_after = (0.0, 0)

    def wait(self, timeout=None):
        return self.poll() or 0

    def kill(self):
        self.terminate()


def test_host_blacklist_backoff_doubles_and_caps():
    from horovod_tpu.run.launch import HostBlacklist
    bl = HostBlacklist(base_s=1.0, cap_s=3.0, retries=3)
    t = 100.0
    bl.record_failure(0, now=t)
    assert not bl.ready_to_retry(0, now=t + 0.5)
    assert bl.ready_to_retry(0, now=t + 1.01)
    bl.record_failure(0, now=t)
    assert not bl.ready_to_retry(0, now=t + 1.5)   # 2s backoff now
    assert bl.ready_to_retry(0, now=t + 2.01)
    bl.record_failure(0, now=t)                     # 3rd failure: 3s cap
    assert bl.ready_to_retry(0, now=t + 3.01)
    bl.record_failure(0, now=t)                     # 4th > retries
    assert bl.permanently_dead(0)
    assert not bl.ready_to_retry(0, now=t + 1000.0)


def test_run_local_elastic_respawns_dead_slot_as_joiner():
    from horovod_tpu.run.launch import HostBlacklist, run_local_elastic
    spawned = []

    def spawn_fn(slot, env, joiner):
        spawned.append((slot, joiner, dict(env)))
        if slot == 2 and not joiner:
            # first incarnation of slot 2 dies quickly
            return _FakeProc(rc_after=(time.monotonic() + 0.2, -9))
        # everyone else (and the respawn) finishes cleanly shortly
        return _FakeProc(rc_after=(time.monotonic() + 1.2, 0))

    rc = run_local_elastic(
        3, ["train.py"], spawn_fn=spawn_fn, min_np=2,
        blacklist=HostBlacklist(base_s=0.1, retries=3), poll_s=0.02)
    assert rc == 0
    joiners = [(s, env) for s, j, env in spawned if j]
    assert len(joiners) == 1 and joiners[0][0] == 2
    env = joiners[0][1]
    assert env["HOROVOD_ELASTIC"] == "1"
    assert env["HOROVOD_ELASTIC_JOIN"] == "1"
    assert env["HOROVOD_ELASTIC_JOIN_ADDR"] == "127.0.0.1"
    assert int(env["HOROVOD_ELASTIC_JOIN_PORT"]) > 0
    assert "HOROVOD_RANK" not in env
    # non-joiner spawns carried the fixed elastic listener ports
    first = [env for s, j, env in spawned if not j and s == 0][0]
    assert first["HOROVOD_TPU_ELASTIC_PORT"].isdigit()


def test_run_local_elastic_blacklists_for_good_after_retries():
    from horovod_tpu.run.launch import HostBlacklist, run_local_elastic
    spawned = []

    def spawn_fn(slot, env, joiner):
        spawned.append((slot, joiner))
        if slot == 1:
            return _FakeProc(rc_after=(time.monotonic() + 0.05, 1))
        return _FakeProc(rc_after=(time.monotonic() + 1.5, 0))

    rc = run_local_elastic(
        2, ["train.py"], spawn_fn=spawn_fn, min_np=1,
        blacklist=HostBlacklist(base_s=0.05, retries=1), poll_s=0.02)
    # slot 1 failed, was respawned once, failed again, got blacklisted
    # for good; slot 0 finished clean -> overall success
    assert rc == 0
    assert [s for s, j in spawned if j] == [1]
    assert spawned.count((1, True)) == 1
