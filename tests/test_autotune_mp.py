"""Autotune under a real multi-process world: the coordinator tunes,
workers adopt the tuned values through the ResponseList trailer, and the
CSV log records the samples (reference: parameter_manager.cc:64-78
SyncParams; HOROVOD_AUTOTUNE_LOG, parameter_manager.cc:93-99). The
single-process unit tests live in test_autotune.py; this is the
integration leg the reference exercises by running under mpirun."""

import os

from tests.test_multiprocess import run_scenario

_MAX_SAMPLES = 3


def test_autotune_two_process_sync_and_log(tmp_path):
    log = str(tmp_path / "autotune.csv")
    run_scenario(
        "autotune", 2, timeout=180.0,
        extra_env={
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_LOG": log,
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "2",
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": str(_MAX_SAMPLES),
        })
    assert os.path.exists(log), "coordinator never wrote the CSV log"
    with open(log) as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    assert lines[0] == ("sample,fusion_threshold_mb,cycle_time_ms,"
                        "score_bytes_per_us")
    rows = lines[1:]
    assert len(rows) >= _MAX_SAMPLES, rows
    for row in rows:
        sample, mb, ms, score = row.split(",")
        assert 0.0 <= float(mb) <= 64.0
        assert 1.0 <= float(ms) <= 100.0
        assert float(score) >= 0.0


def test_autotune_sync_through_hier_controller(tmp_path):
    """Tuned values must reach MIGRATED LEAVES too: with 4 ranks on 2
    fake hosts the ResponseList trailer rides the local root's relay,
    and the adoption assertions inside scenario_autotune run on every
    tier of the hierarchy."""
    log = str(tmp_path / "autotune_hier.csv")
    run_scenario(
        "autotune", 4, timeout=240.0,
        extra_env={
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_LOG": log,
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "2",
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": str(_MAX_SAMPLES),
        },
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})
    assert os.path.exists(log)
