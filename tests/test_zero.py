"""ZeRO-1 sharded optimizer over the virtual 8-device CPU mesh.

Parity model: the sharded update must be bit-comparable (fp32
tolerance) to the unsharded reference — plain optax on the mean
gradient — the way the reference's optimizer tests compare against a
locally computed expectation (reference: test/test_torch.py:802-1003
optimizer-state coverage across optimizer families)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import spmd

from horovod_tpu.compat import jaxshim

N = 8


@pytest.fixture(scope="module")
def mesh():
    return spmd.create_mesh({"data": N})


def _tree_close(a, b, **kw):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), **kw), a, b)


def _params():
    rng = np.random.RandomState(0)
    return {
        "w": rng.randn(3, 5).astype(np.float32),      # 15: pads to 16
        "b": rng.randn(8).astype(np.float32),          # divisible
        "s": np.float32(rng.randn()).reshape(()),      # 0-d: pads to 8
    }


def _per_rank_grads(step=0):
    rng = np.random.RandomState(100 + step)
    p = _params()
    return {k: rng.randn(N, *np.shape(v)).astype(np.float32)
            for k, v in p.items()}


def _build(mesh, ztx, tx):
    """(init_f, step_f, state_specs) with the state crossing the
    shard_map boundary under its real (sharded) specs."""
    specs = spmd.zero_state_specs(tx, _params(), N)
    rep = P()
    grad_specs = jax.tree_util.tree_map(lambda _: P("data"), _params())

    def step(p, state, g_stacked):
        g = jax.tree_util.tree_map(lambda t: t[0], g_stacked)
        updates, state = ztx.update(g, state, p)
        return optax.apply_updates(p, updates), state

    init_f = jax.jit(jaxshim.shard_map(
        ztx.init, mesh=mesh, in_specs=(rep,), out_specs=specs))
    step_f = jax.jit(jaxshim.shard_map(
        step, mesh=mesh, in_specs=(rep, specs, grad_specs),
        out_specs=(rep, specs)))
    return init_f, step_f, specs


def _run_sharded(mesh, tx_factory, n_steps=3, op=spmd.Average):
    """Drive zero_optimizer(tx) for n_steps under shard_map; return the
    final params (identical on every rank) and the optimizer state (a
    global view: each rank's shard concatenated)."""
    params = _params()
    tx = tx_factory()
    ztx = spmd.zero_optimizer(tx, op=op)
    init_f, step_f, _ = _build(mesh, ztx, tx)
    state = init_f(params)
    for i in range(n_steps):
        params, state = step_f(params, state, _per_rank_grads(i))
    return params, state


def _run_reference(tx_factory, n_steps=3, op=spmd.Average):
    params = _params()
    tx = tx_factory()
    state = tx.init(params)
    for i in range(n_steps):
        g = jax.tree_util.tree_map(
            lambda t: np.asarray(
                t.mean(0) if op == spmd.Average else t.sum(0)),
            _per_rank_grads(i))
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    return params, state


@pytest.mark.parametrize("tx_factory", [
    lambda: optax.sgd(0.1),
    lambda: optax.sgd(0.1, momentum=0.9),
    lambda: optax.adam(1e-2),
    lambda: optax.adamw(1e-2, weight_decay=0.01),  # needs params
], ids=["sgd", "sgd_momentum", "adam", "adamw"])
def test_zero_matches_unsharded(mesh, tx_factory):
    got, _ = _run_sharded(mesh, tx_factory)
    want, _ = _run_reference(tx_factory)
    _tree_close(got, want, rtol=1e-5, atol=1e-6)


def test_zero_sum_op(mesh):
    got, _ = _run_sharded(mesh, lambda: optax.sgd(0.01), op=spmd.Sum)
    want, _ = _run_reference(lambda: optax.sgd(0.01), op=spmd.Sum)
    _tree_close(got, want, rtol=1e-5, atol=1e-6)


def test_zero_state_specs_and_sharding(mesh):
    """Moment leaves are P('data')-sharded (global = concatenated
    shards, padded); Adam's step count stays replicated. Per-device
    state memory is 1/N of the padded parameter count."""
    tx = optax.adam(1e-2)
    specs = spmd.zero_state_specs(tx, _params(), N)
    assert specs[0].mu == {"w": P("data"), "b": P("data"),
                           "s": P("data")}
    assert specs[0].count == P()

    _, state = _run_sharded(mesh, lambda: optax.adam(1e-2), n_steps=1)
    mu = state[0].mu
    assert mu["w"].shape == (16,)     # 15 padded to 16, global view
    assert mu["b"].shape == (8,)
    assert mu["s"].shape == (8,)      # 0-d padded to 8
    # each device holds exactly its 1/N shard
    assert mu["w"].sharding.shard_shape(mu["w"].shape) == (2,)
    assert not mu["w"].sharding.is_fully_replicated


def test_zero_state_checkpoint_roundtrip(mesh):
    """Host materialization of the state must capture every rank's
    shard (not silently rank 0's), and restoring it must continue
    training in lockstep with a never-checkpointed run."""
    tx_factory = lambda: optax.adam(1e-2)  # noqa: E731
    tx = tx_factory()
    ztx = spmd.zero_optimizer(tx)
    init_f, step_f, specs = _build(mesh, ztx, tx)

    params = _params()
    state = init_f(params)
    for i in range(2):
        params, state = step_f(params, state, _per_rank_grads(i))

    # checkpoint: pull to host, then restore with the same specs
    host_state = jax.tree_util.tree_map(np.asarray, state)
    host_params = jax.tree_util.tree_map(np.asarray, params)
    restored = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        host_state, specs)

    p1, s1 = step_f(params, state, _per_rank_grads(2))
    p2, s2 = step_f(host_params, restored, _per_rank_grads(2))
    _tree_close(p1, p2, rtol=0, atol=0)
    _tree_close(s1, s2, rtol=0, atol=0)


def test_zero_requires_params(mesh):
    ztx = spmd.zero_optimizer(optax.sgd(0.1))

    def bad(g_stacked):
        g = jax.tree_util.tree_map(lambda t: t[0], g_stacked)
        state = ztx.init(jax.tree_util.tree_map(jnp.zeros_like, g))
        updates, _ = ztx.update(g, state)  # no params
        return updates

    with pytest.raises(ValueError, match="requires params"):
        jax.jit(jaxshim.shard_map(
            bad, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(
                lambda _: P("data"), _params()),),
            out_specs=P()))(_per_rank_grads())


def test_zero_accepts_extra_args(mesh):
    """The ExtraArgs contract: unknown keyword args must be accepted
    and ignored even when the inner tx is a plain transformation."""
    tx = optax.sgd(0.1)
    ztx = spmd.zero_optimizer(tx)
    specs = spmd.zero_state_specs(tx, _params(), N)
    grad_specs = jax.tree_util.tree_map(lambda _: P("data"), _params())

    def step(p, state, g_stacked):
        g = jax.tree_util.tree_map(lambda t: t[0], g_stacked)
        updates, state = ztx.update(g, state, p, value=jnp.float32(1.0))
        return optax.apply_updates(p, updates), state

    params = _params()
    init_f = jax.jit(jaxshim.shard_map(
        ztx.init, mesh=mesh, in_specs=(P(),), out_specs=specs))
    step_f = jax.jit(jaxshim.shard_map(
        step, mesh=mesh, in_specs=(P(), specs, grad_specs),
        out_specs=(P(), specs)))
    p2, _ = step_f(params, init_f(params), _per_rank_grads())
    want, _ = _run_reference(lambda: optax.sgd(0.1), n_steps=1)
    _tree_close(p2, want, rtol=1e-5, atol=1e-6)


def test_zero_rejects_min_max():
    with pytest.raises(ValueError, match="Average/Sum"):
        spmd.zero_optimizer(optax.sgd(0.1), op=spmd.Min)


def test_sharded_clip_matches_full_clip(mesh):
    """zero(chain(sharded_clip, sgd)) == sgd(clip(mean_grad)): the
    psum'd shard norm must reproduce the true global norm."""
    max_norm = 0.05  # small enough that clipping definitely engages

    def sharded_tx():
        return optax.chain(
            spmd.sharded_clip_by_global_norm(max_norm), optax.sgd(0.1))

    got, _ = _run_sharded(mesh, sharded_tx)

    # Reference: full-tree clip on the mean gradient.
    params = _params()
    tx = optax.chain(optax.clip_by_global_norm(max_norm), optax.sgd(0.1))
    state = tx.init(params)
    for i in range(3):
        g = jax.tree_util.tree_map(lambda t: np.asarray(t.mean(0)),
                                   _per_rank_grads(i))
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    _tree_close(got, params, rtol=1e-5, atol=1e-6)


def test_zero_end_to_end_training_step(mesh):
    """A real loss: data-parallel linear regression where the zero
    optimizer's loss decreases and matches the unsharded run."""
    rng = np.random.RandomState(7)
    X = rng.randn(16, 4).astype(np.float32)
    w_true = rng.randn(4).astype(np.float32)
    y = X @ w_true
    params = {"w": np.zeros(4, np.float32)}
    tx = optax.adam(0.1)
    ztx = spmd.zero_optimizer(tx)
    specs = spmd.zero_state_specs(tx, params, N)

    def loss_fn(p, xb, yb):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    def step(p, state, xb, yb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        loss = jax.lax.pmean(loss, "data")
        updates, state = ztx.update(g, state, p)
        return optax.apply_updates(p, updates), state, loss

    rep = P()
    init_f = jax.jit(jaxshim.shard_map(ztx.init, mesh=mesh, in_specs=(rep,),
                                   out_specs=specs))
    step_f = jax.jit(jaxshim.shard_map(
        step, mesh=mesh, in_specs=(rep, specs, P("data"), P("data")),
        out_specs=(rep, specs, rep)))

    state = init_f(params)
    losses = []
    for _ in range(40):
        params, state, loss = step_f(params, state, X, y)
        losses.append(float(loss))
    assert losses[-1] < 0.05 * losses[0]
    np.testing.assert_allclose(np.asarray(params["w"]), w_true,
                               atol=0.25)
