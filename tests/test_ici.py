"""ICI-native pod-scale data plane (ISSUE 18).

Three tiers in one module:

* unit tests of the ALG_ICI verdict plumbing: StaticWirePolicy
  stamping + threshold ordering, the autotune discrete-grid entry,
  the XLA executable-cache key bugfix (verdict in the key + epoch
  eviction), and SteadyPlan.adopt_packed's byte-compat validation;
* in-process IciPlane legs over the conftest-forced 8-device host
  mesh: fused_pack bit-exactness against the numpy host pack,
  compile-count flatness across replays, the pod-mode
  fused_reduce_partials psum, and epoch-bump eviction;
* multi-process legs: the fused-psum steady cycle end to end
  (ici_cycles advancing on a flat compile count, ALG_ICI provably
  stamped, data-copies delta 0), bit-exactness vs an all-socket
  replay, world-consistent degrade in a heterogeneous world, and
  SIGKILL mid-ICI-cycle fail-fast.
"""

import os
import signal

import numpy as np
import pytest

from horovod_tpu.common import wire_dtype as wd
from tests.test_multiprocess import run_scenario

_HB_ENV = {
    "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
    "HOROVOD_HEARTBEAT_TIMEOUT": "3",
}
_SIGKILL_RC = -signal.SIGKILL
_SOCKET_ENV = {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1"}
# The spawned worlds inherit conftest's forced 8-device XLA_FLAGS;
# restating it here keeps the wrappers green under a bare pytest
# invocation that bypassed conftest's env mutation.
_FORCED_DEVS = "--xla_force_host_platform_device_count=8"
_ICI_ENV = {**_SOCKET_ENV,
            "HOROVOD_TPU_ICI": "1",
            "HOROVOD_TPU_METRICS": "1",
            "XLA_FLAGS": _FORCED_DEVS}


# -- verdict plumbing -------------------------------------------------------

class TestStaticPolicyIci:
    def test_stamps_ici_when_world_agreed(self):
        pol = wd.StaticWirePolicy(two_level=False, threshold_bytes=0,
                                  multi_host=False, ici_allowed=True)
        alg, cap = pol.plan(1024)
        assert alg == wd.ALG_ICI
        assert cap is None

    def test_ici_threshold_gates_small_batches(self):
        pol = wd.StaticWirePolicy(two_level=False, threshold_bytes=0,
                                  multi_host=False, ici_allowed=True,
                                  ici_threshold_bytes=4096)
        assert pol.plan(4095)[0] == wd.ALG_DEFAULT
        assert pol.plan(4096)[0] == wd.ALG_ICI

    def test_ici_outranks_two_level(self):
        pol = wd.StaticWirePolicy(two_level=True, threshold_bytes=0,
                                  multi_host=True, shm_enabled=True,
                                  ici_allowed=True)
        assert pol.plan(1 << 20)[0] == wd.ALG_ICI

    def test_without_agreement_two_level_keeps_winning(self):
        pol = wd.StaticWirePolicy(two_level=True, threshold_bytes=0,
                                  multi_host=True, shm_enabled=True,
                                  ici_allowed=False)
        assert pol.plan(1 << 20)[0] == wd.ALG_TWOLEVEL

    def test_config_knobs_parse(self, monkeypatch):
        from horovod_tpu.common.config import Config
        monkeypatch.setenv("HOROVOD_TPU_ICI", "1")
        monkeypatch.setenv("HOROVOD_TPU_ICI_DEVICES", "4")
        monkeypatch.setenv("HOROVOD_TPU_ICI_THRESHOLD", "65536")
        c = Config.from_env()
        assert c.ici_enabled
        assert c.ici_devices == 4
        assert c.ici_threshold_bytes == 65536


class TestAutotuneGridIci:
    def _pm(self):
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.controller import LocalController
        from horovod_tpu.common.parameter_manager import ParameterManager
        cfg = Config()
        cfg.autotune = True
        return ParameterManager(cfg, LocalController())

    def test_grid_includes_ici_when_allowed(self):
        pm = self._pm()
        pm.configure_wire(wd.WIRE_BF16, multi_host=False, world_size=2,
                          ici_allowed=True)
        combos = pm._bucket_tuner._combos
        assert (wd.ALG_ICI, wd.WIRE_NONE) in combos
        assert (wd.ALG_ICI, wd.WIRE_BF16) in combos

    def test_grid_omits_ici_without_world_agreement(self):
        pm = self._pm()
        pm.configure_wire(wd.WIRE_BF16, multi_host=False, world_size=2,
                          ici_allowed=False)
        tuner = pm._bucket_tuner
        combos = tuner._combos if tuner is not None else []
        assert not any(a == wd.ALG_ICI for a, _ in combos)


class TestMeshCacheKeyBugfix:
    """The satellite bugfix: compiled executables must be keyed on the
    NEGOTIATED verdict (wire dtype + algorithm), and evicted on the
    ResponseCache epoch bump."""

    def _backend(self):
        from horovod_tpu.ops.xla_ops import XlaMeshBackend

        class _Ctl:
            rank = 0
            size = 2
        return XlaMeshBackend(_Ctl())

    def test_verdict_in_signature(self):
        from horovod_tpu.common.message import Response
        b = self._backend()
        r1 = Response()
        r1.wire_dtype = wd.WIRE_BF16
        r1.algorithm = wd.ALG_ICI
        r2 = Response()
        r2.wire_dtype = wd.WIRE_NONE
        r2.algorithm = wd.ALG_ICI
        assert b._verdict_sig(r1) != b._verdict_sig(r2)
        r3 = Response()
        r3.wire_dtype = wd.WIRE_BF16
        r3.algorithm = wd.ALG_STAR
        assert b._verdict_sig(r1) != b._verdict_sig(r3)
        assert b._verdict_sig(None) == ()

    def test_epoch_bump_evicts_compiled_cache(self):
        b = self._backend()
        b.note_cache_epoch(0)
        b._cache[("allreduce", (4,), "float32", (), 1, ())] = object()
        b.note_cache_epoch(0)   # same epoch: keep
        assert b._cache
        b.note_cache_epoch(1)   # bump: evict
        assert not b._cache

    def test_operation_manager_fans_epoch_out(self):
        from horovod_tpu.ops.operation_manager import OperationManager

        class _B:
            def __init__(self):
                self.seen = []

            def note_cache_epoch(self, epoch):
                self.seen.append(epoch)

        class _Plain:
            pass

        b = _B()
        om = OperationManager([_Plain(), b])
        om.note_cache_epoch(7)
        assert b.seen == [7]


class TestAdoptPacked:
    def _plan(self):
        import ml_dtypes
        from horovod_tpu.common.arena import FusionArena
        from horovod_tpu.common.message import DataType
        from horovod_tpu.common.steady import SteadyPlan
        return SteadyPlan(
            epoch=3, nslots=8, mask=0b11,
            segments=[(DataType.BFLOAT16, np.dtype(ml_dtypes.bfloat16),
                       64, np.dtype(np.float32)),
                      (DataType.FLOAT32, np.dtype(np.float32), 32,
                       None)],
            arena=FusionArena())

    def test_adopts_byte_compatible_buffers(self):
        import ml_dtypes
        plan = self._plan()
        bufs = [np.zeros(32, ml_dtypes.bfloat16),
                np.zeros(8, np.float32)]
        out = plan.adopt_packed(bufs)
        assert out is not None
        assert out[0] is bufs[0] and out[1] is bufs[1]

    def test_rejects_wrong_dtype_or_size(self):
        import ml_dtypes
        plan = self._plan()
        assert plan.adopt_packed(
            [np.zeros(32, np.float16), np.zeros(8, np.float32)]) is None
        assert plan.adopt_packed(
            [np.zeros(31, ml_dtypes.bfloat16),
             np.zeros(8, np.float32)]) is None
        assert plan.adopt_packed([np.zeros(32, ml_dtypes.bfloat16)]) \
            is None
        assert plan.adopt_packed(
            [None, np.zeros(8, np.float32)]) is None

    def test_makes_noncontiguous_contiguous(self):
        import ml_dtypes
        plan = self._plan()
        wide = np.zeros((32, 2), ml_dtypes.bfloat16)
        out = plan.adopt_packed([wide[:, 0], np.zeros(8, np.float32)])
        assert out is not None
        assert out[0].flags["C_CONTIGUOUS"]


class TestScalingEfficiencyFeed:
    def test_note_and_read_back(self):
        from horovod_tpu.common import metrics as hmetrics
        hmetrics.note_scaling_efficiency(16, 0.42)
        assert hmetrics.scaling_efficiencies()[16] == 0.42

    def test_runtime_exports_gauge_family(self, monkeypatch):
        """An armed runtime registry mirrors the MULTICHIP harness's
        verdicts as hvd_scaling_efficiency{world_size="N"} gauges on
        its next snapshot."""
        monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
        from horovod_tpu.common import metrics as hmetrics
        import horovod_tpu as hvd
        hmetrics.note_scaling_efficiency(4, 0.5)
        hvd.init()
        try:
            snap = hvd.metrics()["local"]
            rec = snap['hvd_scaling_efficiency{world_size="4"}']
            assert rec["v"] == 0.5
        finally:
            hvd.shutdown()


# -- in-process IciPlane over the conftest-forced 8-device mesh -------------

def _plane(max_devices=0):
    jax = pytest.importorskip("jax")
    if len(jax.local_devices()) < 2:
        pytest.skip("needs the forced multi-device host platform")
    from horovod_tpu.ops.xla_ops import IciPlane
    p = IciPlane(max_devices)
    assert p.probe()
    return p


class TestIciPlane:
    @pytest.mark.parametrize("wire,out_np,n", [
        (wd.WIRE_NONE, np.float32, 1000),
        (wd.WIRE_BF16, "bfloat16", 1000),
        (wd.WIRE_FP16, np.float16, 777),
    ])
    def test_fused_pack_bit_exact_vs_host_pack(self, wire, out_np, n):
        import ml_dtypes
        p = _plane()
        rng = np.random.RandomState(7)
        flat = rng.randn(n).astype(np.float32)
        for prescale in (1.0, 0.5):
            got = p.fused_pack((0, 0b1, 0), flat, prescale, wire)
            ref = flat * np.float32(prescale) if prescale != 1.0 \
                else flat
            if wire:
                ref = ref.astype(
                    ml_dtypes.bfloat16 if out_np == "bfloat16"
                    else out_np)
            assert got.dtype == ref.dtype
            assert got.tobytes() == ref.tobytes()
            assert got.flags.writeable

    def test_compile_count_flat_across_replays(self):
        p = _plane()
        flat = np.arange(640, dtype=np.float32)
        p.fused_pack((0, 0b1, 0), flat, 1.0, wd.WIRE_BF16)
        c = p.compiles
        for _ in range(20):
            p.fused_pack((0, 0b1, 0), flat, 1.0, wd.WIRE_BF16)
        assert p.compiles == c
        assert p.cycles >= 21
        # a new signature compiles exactly once more
        p.fused_pack((0, 0b11, 1), flat, 1.0, wd.WIRE_BF16)
        assert p.compiles == c + 1

    def test_fused_reduce_partials_matches_wire_precision_sum(self):
        import ml_dtypes
        p = _plane()
        rng = np.random.RandomState(11)
        parts = rng.randn(p.ndev, 257).astype(np.float32)
        got = p.fused_reduce_partials((1, 0b1, 0), parts, 1.0,
                                      wd.WIRE_NONE)
        np.testing.assert_allclose(
            np.asarray(got, np.float64),
            parts.astype(np.float64).sum(axis=0), rtol=1e-5)
        # wire-precision semantics: rows cast to bf16 BEFORE the sum
        gotc = p.fused_reduce_partials((1, 0b1, 1), parts, 1.0,
                                       wd.WIRE_BF16)
        assert gotc.dtype == np.dtype(ml_dtypes.bfloat16)

    def test_epoch_bump_evicts_compiled_plans(self):
        p = _plane()
        flat = np.arange(64, dtype=np.float32)
        p.note_cache_epoch(0)
        p.fused_pack((0, 0b1, 0), flat, 1.0, wd.WIRE_NONE)
        assert p._cache
        p.note_cache_epoch(0)
        assert p._cache
        p.note_cache_epoch(1)
        assert not p._cache

    def test_declines_unsupported_payloads(self):
        import jax
        p = _plane()
        assert p.fused_pack((0, 1, 0), np.arange(8, dtype=np.int32),
                            1.0, wd.WIRE_NONE) is None
        if not jax.config.jax_enable_x64:
            # f64 would be silently canonicalized to f32 on device —
            # never byte-compatible with the plan, so decline up front
            assert p.fused_pack(
                (0, 1, 0), np.arange(8, dtype=np.float64), 1.0,
                wd.WIRE_NONE) is None
        assert p.fused_pack((0, 1, 0),
                            np.arange(8, dtype=np.float32), 1.0,
                            wd.WIRE_INT8) is None
        assert p.fused_pack((0, 1, 0),
                            np.zeros(0, np.float32), 1.0,
                            wd.WIRE_NONE) is None

    def test_max_devices_caps_the_mesh(self):
        p = _plane(max_devices=2)
        assert p.ndev == 2
        flat = np.arange(11, dtype=np.float32)  # ragged over 2 shards
        got = p.fused_pack((0, 1, 0), flat, 1.0, wd.WIRE_NONE)
        assert got.tobytes() == flat.tobytes()


# -- multi-process legs -----------------------------------------------------

def test_ici_steady_engages_precompiled_plane():
    """ws=2 over forced 8-device meshes: steady cycles ride the
    fused-psum executable (ici_cycles advance, ici_compiles flat),
    ALG_ICI is provably stamped, and the Python side of the mesh leg
    performs zero fallback copies."""
    run_scenario("ici_steady", 2, timeout=150.0, extra_env=_ICI_ENV)


def test_ici_steady_compressed_bit_exact_vs_socket_replay(tmp_path):
    """The acceptance bit-exactness leg: a bf16-compressed ICI world
    and a fresh all-socket world replaying the same submissions must
    produce BYTE-IDENTICAL results — the on-device prescale+cast is
    the same function as the host pack."""
    ici = str(tmp_path / "ici.npy")
    sock = str(tmp_path / "sock.npy")
    run_scenario(
        "ici_steady", 2, timeout=150.0,
        extra_env={**_ICI_ENV, "HOROVOD_COMPRESSION": "bf16",
                   "HVD_ICI_OUT": ici})
    run_scenario(
        "ici_steady", 2, timeout=150.0,
        extra_env={**_SOCKET_ENV, "HOROVOD_TPU_METRICS": "1",
                   "HOROVOD_COMPRESSION": "bf16",
                   "HVD_ICI_EXPECT": "0", "HVD_ICI_OUT": sock})
    a = np.load(ici)
    b = np.load(sock)
    assert a.tobytes() == b.tobytes()


def test_ici_hetero_world_degrades_consistently(tmp_path):
    """One rank without a multi-device runtime (its XLA_FLAGS carry no
    forced device count): controller.agree() must turn the plane off
    WORLD-WIDE — zero ici cycles on every rank — and the degraded run
    stays bit-exact with an all-socket world."""
    mixed = str(tmp_path / "mixed.npy")
    plain = str(tmp_path / "plain.npy")
    run_scenario(
        "ici_steady", 3, timeout=150.0,
        extra_env={**_ICI_ENV, "HVD_ICI_EXPECT": "0",
                   "HVD_ICI_OUT": mixed},
        per_rank_env=lambda rank: (
            {"XLA_FLAGS": ""} if rank == 1 else {}))
    run_scenario(
        "ici_steady", 3, timeout=150.0,
        extra_env={**_SOCKET_ENV, "HOROVOD_TPU_METRICS": "1",
                   "HVD_ICI_EXPECT": "0", "HVD_ICI_OUT": plain})
    a = np.load(mixed)
    b = np.load(plain)
    assert a.tobytes() == b.tobytes()


def test_abort_sigkill_mid_ici_cycle():
    """SIGKILL rank 1 deep in ALG_ICI steady state: survivors must
    still raise WorldAbortedError naming the dead rank within the
    heartbeat deadline — the mesh leg cannot mask the PR 2 fail-fast
    invariant."""
    run_scenario(
        "abort_sigkill_ici_steady", 3, timeout=60.0,
        extra_env={**_HB_ENV, **_ICI_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=40"},
        expect_rc={1: _SIGKILL_RC})
