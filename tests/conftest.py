"""Test configuration.

Force JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so SPMD/mesh tests exercise real multi-device sharding without
TPU hardware (the driver separately dry-runs the multi-chip path; see
__graft_entry__.py)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import tempfile  # noqa: E402

# The flight recorder (common/trace.py) is ON by default and dumps
# into HOROVOD_TPU_FLIGHT_DIR (default: CWD) on every world abort.
# test_multiprocess._base_env already points SPAWNED worlds at a
# throwaway dir, but IN-PROCESS aborts (e.g. test_timeline driving
# WorldAbortedError through Runtime directly) dump from this very
# process — without a default here each such test leaves a pid-unique
# hvd-flight-*.jsonl in the checkout. setdefault keeps any operator-
# or test-provided dir authoritative.
os.environ.setdefault("HOROVOD_TPU_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="hvd-flight-conftest."))

# Share one persistent XLA compilation cache across the whole run —
# including every SPAWNED rank and example subprocess (they inherit
# os.environ). The mp tier pays the same model jits hundreds of times
# in short-lived interpreters; on a loaded single-core CI host those
# recompiles are the difference between fitting the tier-1 wall-time
# budget and timing out. setdefault keeps an operator cache
# authoritative; compiles under jax's default 1 s floor are not
# cached (they are cheaper than the disk round trip).
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      tempfile.mkdtemp(prefix="hvd-xla-cache."))

import pytest  # noqa: E402

# The container's sitecustomize may already have imported jax to register
# the TPU PJRT plugin, in which case the env var above is too late;
# jax.config still wins as long as no backend has been initialized.
# (Guarded: the core runtime is importable without jax, and the
# numpy-only tests must stay runnable on jax-less hosts.)
try:
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass


# Modules whose tests spawn real worker processes (TCP worlds, example
# smoke runs, launchers): the expensive integration tier. Everything
# else is the fast in-process tier (reference precedent: the
# single-process vs mpirun suite split, .travis.yml:109-122).
_MP_MODULES = {
    "test_multiprocess", "test_examples", "test_launcher",
    "test_spark", "test_autotune_mp", "test_timeline",
}


def pytest_configure(config):
    # Build the native core ONCE up front (the zero-copy data plane
    # rides it): with a compiler present a broken build must fail the
    # tier LOUDLY — a silent skip would unhook every native test (and
    # the whole zero-copy plane) from CI forever. Without a compiler
    # the native tests skip with a reason, as before.
    from horovod_tpu import native as _native

    loaded, reason = _native.build_status()
    if not loaded and _native.compiler_available() \
            and not _native.disabled_via_env():
        raise pytest.UsageError(
            f"native core build failed with a compiler present "
            f"({reason}) — fix native/hvdtpu.cc or the Makefile; "
            f"tier-1 refuses to silently drop the zero-copy plane")

    config.addinivalue_line(
        "markers", "mp: spawns worker subprocesses (slow integration "
        "tier; deselect with -m 'not mp' for the ~2-minute fast "
        "suite)")
    config.addinivalue_line(
        "markers", "fast: in-process unit tier (alias: -m fast == "
        "-m 'not mp')")
    config.addinivalue_line(
        "markers", "lint: pure-static hvdlint analyzer checks + "
        "lockdep units (no world spawn; subset of the fast tier — "
        "run alone with -m lint)")
    config.addinivalue_line(
        "markers", "slow: wall-clock outliers (many-world convergence "
        "runs, big example smokes) excluded from the budgeted tier-1 "
        "sweep (-m 'not slow'); the full matrix (plain `pytest "
        "tests/`) still runs them")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__.rsplit(".", 1)[-1] in _MP_MODULES:
            item.add_marker(pytest.mark.mp)
        else:
            item.add_marker(pytest.mark.fast)


@pytest.fixture()
def hvd_world():
    """A fresh size-1 horovod_tpu world per test."""
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()
