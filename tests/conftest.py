"""Test configuration.

Force JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so SPMD/mesh tests exercise real multi-device sharding without
TPU hardware (the driver separately dry-runs the multi-chip path; see
__graft_entry__.py)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture()
def hvd_world():
    """A fresh size-1 horovod_tpu world per test."""
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()
