"""Unit tests for self-operation (common/selfop.py): wire codec, the
host-grouped sync tree, the supervision policy's decision guards, the
cut-through relay helper, preemption notices, and the async sharded
checkpoints — everything that doesn't need a real multi-process world
(tests/test_multiprocess.py covers those)."""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import controller as hcontroller
from horovod_tpu.common import elastic, faults, network, selfop, wire
from horovod_tpu.common.config import Config


@pytest.fixture(autouse=True)
def _clean_selfop_state():
    yield
    selfop.reset()
    elastic.reset()
    faults.clear()


def _cfg(**kw) -> Config:
    c = Config()
    c.elastic_enabled = True
    for k, v in kw.items():
        setattr(c, k, v)
    return c


# -- wire codec --------------------------------------------------------------

def test_selfop_sync_manifest_roundtrip():
    arrays = [("w", "<f4", (3, 4)), ("b", "<f8", ())]
    scalars = [("step", 1, "7"), ("done", 0, "False")]
    payload = wire.serialize_selfop_sync(
        "host-a", 0, 5, 1 << 20, "bf16", arrays, scalars, ["opaque"])
    info = wire.parse_selfop_sync(payload)
    assert info["gen"] == 5 and info["chunk"] == 1 << 20
    assert info["compression"] == "bf16"
    assert info["arrays"] == arrays
    assert info["scalars"] == scalars
    assert info["legacy"] == ["opaque"]


@pytest.mark.parametrize("cut", [1, 6, 11, 20])
def test_truncated_sync_manifest_fails_as_transport_error(cut):
    payload = wire.serialize_selfop_sync(
        "h", 0, 1, 4096, "none", [("w", "<f4", (2,))], [], [])
    with pytest.raises(ConnectionError):
        wire.parse_selfop_sync(payload[:cut])


def test_verdict_codec_carries_demotion():
    payload = wire.serialize_elastic_verdict(
        elastic.VERDICT_OK, 3, 0, 4, "h", 1, "straggler",
        demote_rank=3, pace_us=1500)
    v = wire.parse_elastic_verdict(payload)
    assert v["demote_rank"] == 3 and v["pace_us"] == 1500
    # absence is encoded, not implied
    payload = wire.serialize_elastic_verdict(
        elastic.VERDICT_OK, 3, 0, 4, "h", 1, "c")
    v = wire.parse_elastic_verdict(payload)
    assert v["demote_rank"] == -1 and v["pace_us"] == 0


# -- state partitioning and the sync tree ------------------------------------

def test_partition_state_groups_by_wire_describability():
    values = {
        "w": np.ones((2, 3), np.float32),
        "step": 7,
        "lr": 0.1,
        "flag": True,
        "opaque": {"nested": 1},
        "strided": np.ones((4, 4), np.float32)[:, ::2],
    }
    arrays, scalars, legacy = selfop._partition_state(values)
    assert [k for k, _, _ in arrays] == ["w"]
    assert sorted(k for k, _, _ in scalars) == ["flag", "lr", "step"]
    assert sorted(legacy) == ["opaque", "strided"]
    # scalar codes round-trip through the ctor table
    for key, stype, rep in scalars:
        rebuilt = wire._SYNC_SCALAR_CTORS[stype](rep)
        assert rebuilt == values[key] and type(rebuilt) is type(values[key])


def test_host_tree_groups_by_host():
    table = {0: ("a", 1), 1: ("a", 2), 2: ("b", 3), 3: ("b", 4),
             4: ("c", 5)}
    assert selfop._host_tree(0, 5, table) == (-1, [1, 2, 4])
    assert selfop._host_tree(1, 5, table) == (0, [])
    assert selfop._host_tree(2, 5, table) == (0, [3])   # host-root of b
    assert selfop._host_tree(3, 5, table) == (2, [])
    assert selfop._host_tree(4, 5, table) == (0, [])    # lone host c


def test_host_tree_falls_back_to_star_without_host_info():
    assert selfop._host_tree(0, 4, {}) == (-1, [1, 2, 3])
    assert selfop._host_tree(2, 4, {}) == (0, [])


def test_compress_roundtrip_bf16_and_fp16():
    src = np.arange(16, dtype=np.float32) * 0.5
    raw = src.view(np.uint8)
    for comp in ("bf16", "fp16"):
        payload = selfop._compress_chunk(raw, comp)
        assert payload.nbytes == raw.nbytes // 2
        back = selfop._decompress_chunk(
            payload.view(np.uint8), comp).view(np.float32)
        np.testing.assert_allclose(back, src, rtol=1e-2)
    # exact values representable in both halves round-trip bit-exactly
    np.testing.assert_array_equal(
        selfop._decompress_chunk(
            selfop._compress_chunk(raw, "bf16").view(np.uint8),
            "bf16").view(np.float32), src)


# -- cut-through relay helper ------------------------------------------------

def _channel_pair(secret=b"s3cr3t"):
    a, b = socket.socketpair()
    return (network.Channel(a, secret, peer="a"),
            network.Channel(b, secret, peer="b"))


def test_relay_frame_into_forwards_while_receiving():
    root_tx, mid_rx = _channel_pair()
    mid_tx, leaf_rx = _channel_pair()
    payload = np.arange(4096, dtype=np.uint8)
    out = np.zeros(4096, dtype=np.uint8)

    t = threading.Thread(target=root_tx.sendv,
                         args=((payload,), selfop.SYNC_TAG))
    t.start()
    n = hcontroller.relay_frame_into(mid_rx, [mid_tx],
                                     selfop.SYNC_TAG, out)
    t.join()
    assert n == 4096
    np.testing.assert_array_equal(out, payload)
    got = np.zeros(4096, dtype=np.uint8)
    tag, m = leaf_rx.recv_into(memoryview(got))
    assert tag == selfop.SYNC_TAG and m == 4096
    np.testing.assert_array_equal(got, payload)
    for ch in (root_tx, mid_rx, mid_tx, leaf_rx):
        ch.close()


def test_relay_frame_into_rejects_wrong_tag(monkeypatch):
    # Force the Python fallback so the tag check is exercised even on
    # builds without the native relay.
    from horovod_tpu import native as _native
    monkeypatch.setattr(_native, "get", lambda: None)
    tx, rx = _channel_pair()
    out = np.zeros(16, dtype=np.uint8)
    t = threading.Thread(target=tx.send, args=(b"x" * 16, 9))
    t.start()
    with pytest.raises(ConnectionError, match="tag"):
        hcontroller.relay_frame_into(rx, [], selfop.SYNC_TAG, out)
    t.join()
    tx.close()
    rx.close()


# -- preemption notice -------------------------------------------------------

def test_notice_preemption_sets_flag_and_reset_clears(monkeypatch):
    monkeypatch.setenv("HOROVOD_PREEMPT_GRACE", "600")  # never fires here
    assert not selfop.preempted()
    selfop.notice_preemption()
    assert selfop.preempted()
    assert selfop._grace_timer is not None
    selfop.reset()
    assert not selfop.preempted()
    assert selfop._grace_timer is None


def test_notice_file_scopes_to_launch_rank(tmp_path, monkeypatch):
    notice = tmp_path / "preempt"
    monkeypatch.setenv("HOROVOD_PREEMPT_NOTICE", str(notice))
    assert not selfop._notice_file_hit(1)   # no file yet
    notice.write_text("0, 2")
    assert selfop._notice_file_hit(0)
    assert selfop._notice_file_hit(2)
    assert not selfop._notice_file_hit(1)
    notice.write_text("")                    # empty = whole host
    assert selfop._notice_file_hit(1)


def test_policy_preempt_decision_on_any_rank(monkeypatch):
    monkeypatch.setenv("HOROVOD_PREEMPT_GRACE", "600")
    pol = selfop.SupervisionPolicy(rank=3)
    assert pol.tick() is None
    selfop.notice_preemption()
    assert pol.tick() == ("preempt", 3)
    assert pol.decisions["preempt_drain"] >= 1


def test_preempt_fault_spec_parses():
    (f,) = faults.parse_spec("rank=2:preempt:cycle=40:seconds=5")
    assert f.action == "preempt" and f.rank == 2
    assert f.at_cycle == 40 and f.seconds == 5.0
    with pytest.raises(ValueError):
        faults.parse_spec("rank=1:preempt:cycle=1:count=3")  # not delay


# -- supervision policy: demotion guards -------------------------------------

class _FakeTracker:
    def __init__(self, window, counts, lags):
        self._stats = {"window": window, "gathers": window,
                       "last_counts": counts, "max_lag": lags}

    def window_stats(self):
        return dict(self._stats)


class _FakeController:
    def __init__(self, ages):
        self._ages = ages

    def peer_heartbeat_ages(self):
        return dict(self._ages)


class _FakeRuntime:
    def __init__(self, tracker, ages=None):
        self._straggler = tracker
        self.controller = _FakeController(ages or {})
        self.config = Config()


def _armed_policy():
    """A rank-0 policy with the generation-churn cooldown already
    served (a fresh context starts a 5 s quiet period)."""
    elastic.ensure_context(_cfg(), b"")
    pol = selfop.SupervisionPolicy(rank=0)
    pol._last_gen = 0
    pol._last_gen_change = time.monotonic() - 60.0
    return pol


def test_demote_fires_on_habitual_straggler():
    pol = _armed_policy()
    rt = _FakeRuntime(_FakeTracker(300, {2: 250, 1: 10}, {2: 0.02}),
                      ages={2: 0.5})
    assert pol.tick(rt) == ("demote", -1)
    worst, pace_us = pol.take_pending_demote()
    assert worst == 2
    assert pace_us == 20000  # min(20ms lag, 50ms cap) in microseconds
    # one demotion per rank per process: never re-fires
    assert pol.tick(rt) is None
    assert pol.take_pending_demote() is None


def test_demote_guards_hold():
    pol = _armed_policy()
    # below the attribution window
    rt = _FakeRuntime(_FakeTracker(50, {2: 49}, {2: 0.02}))
    assert pol.tick(rt) is None
    # below the share threshold
    rt = _FakeRuntime(_FakeTracker(300, {2: 100, 1: 90}, {2: 0.02}))
    assert pol.tick(rt) is None
    # never demote the coordinator
    rt = _FakeRuntime(_FakeTracker(300, {0: 290}, {0: 0.02}))
    assert pol.tick(rt) is None
    # a silent peer is a liveness problem, not a straggler
    rt = _FakeRuntime(_FakeTracker(300, {2: 290}, {2: 0.02}),
                      ages={2: 29.0})
    assert pol.tick(rt) is None
    assert pol.take_pending_demote() is None


def test_demote_respects_generation_churn_cooldown():
    elastic.ensure_context(_cfg(), b"")
    pol = selfop.SupervisionPolicy(rank=0)  # fresh: cooldown running
    rt = _FakeRuntime(_FakeTracker(300, {2: 290}, {2: 0.02}))
    assert pol.tick(rt) is None


def test_cycle_pace_spares_the_demoted_rank():
    selfop.verdict().install("demote", 2, 4, "straggler", 20000)
    assert selfop.cycle_pace_s(0) == pytest.approx(0.02)
    assert selfop.cycle_pace_s(1) == pytest.approx(0.02)
    assert selfop.cycle_pace_s(2) == 0.0
    # an empty verdict (non-demote resize) clears pacing everywhere
    selfop.verdict().install("", -1, 5, "", 0)
    assert selfop.cycle_pace_s(0) == 0.0


def test_verdict_is_marked_world_coherent():
    assert getattr(selfop.SupervisionVerdict.install,
                   "__world_coherent__", False)
    v = selfop.SupervisionVerdict()
    assert v.line() == ""
    v.install("demote", 1, 2, "why", 100)
    assert "demote" in v.line() and "target=1" in v.line()


# -- async sharded checkpoints -----------------------------------------------

def _committed_state(**values):
    s = elastic.State(**values)
    s.commit()
    return s


def test_shard_write_restore_roundtrip(tmp_path):
    d = str(tmp_path / "ck")
    s = _committed_state(w=np.arange(8, dtype=np.float32),
                         b=np.ones(3), step=11, lr=0.5)
    committed = s._committed
    for rank in range(2):
        selfop._write_shard(committed, seq=1, rank=rank, world=2,
                            directory=d)
    fresh = elastic.State(w=np.zeros(8, np.float32), b=np.zeros(3),
                          step=0, lr=0.0)
    assert selfop.restore_state(fresh, d) == 1
    np.testing.assert_array_equal(fresh.w, np.arange(8.0))
    np.testing.assert_array_equal(fresh.b, np.ones(3))
    assert fresh.step == 11 and fresh.lr == 0.5
    assert object.__getattribute__(fresh, "_commit_seq") == 1


def test_restore_skips_incomplete_and_torn_sets(tmp_path):
    d = str(tmp_path / "ck")
    s = _committed_state(w=np.arange(4, dtype=np.float32), step=1)
    for rank in range(2):
        selfop._write_shard(s._committed, 1, rank, 2, d)
    s2 = _committed_state(w=np.full(4, 9.0, np.float32), step=2)
    for rank in range(2):
        selfop._write_shard(s2._committed, 2, rank, 2, d)

    # seq 3: only rank 0's shard exists (kill mid-sequence)
    selfop._write_shard(s2._committed, 3, 0, 2, d)
    # seq 2 rank 1: npz corrupted after the digest was recorded
    npz, _ = selfop._shard_paths(d, 2, 1, 2)
    with open(npz, "r+b") as fh:
        fh.seek(0)
        fh.write(b"\xde\xad\xbe\xef")

    fresh = elastic.State(w=np.zeros(4, np.float32), step=0)
    assert selfop.restore_state(fresh, d) == 1  # falls back to seq 1
    np.testing.assert_array_equal(fresh.w, np.arange(4.0))
    assert fresh.step == 1


def test_restore_returns_none_on_empty_or_garbage_dir(tmp_path):
    fresh = elastic.State(w=np.zeros(2, np.float32))
    assert selfop.restore_state(fresh, str(tmp_path / "nope")) is None
    d = tmp_path / "junk"
    d.mkdir()
    (d / "shard_s1_r0_of_1.json").write_text("{not json")
    assert selfop.restore_state(fresh, str(d)) is None


def test_shard_prune_keeps_newest_per_rank(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_SELFOP_CKPT_KEEP", "2")
    d = str(tmp_path / "ck")
    s = _committed_state(w=np.ones(2, np.float32))
    for seq in (1, 2, 3, 4):
        selfop._write_shard(s._committed, seq, 0, 1, d)
    seqs = sorted(int(selfop._SHARD_RE.match(n).group(1))
                  for n in os.listdir(d) if n.endswith(".json"))
    assert seqs == [3, 4]


def test_maybe_checkpoint_writes_on_idle_and_skips_unchanged(
        tmp_path, monkeypatch):
    d = str(tmp_path / "ck")
    monkeypatch.setenv("HOROVOD_SELFOP_CKPT_DIR", d)
    monkeypatch.setenv("HOROVOD_SELFOP_CKPT_INTERVAL", "1")
    from horovod_tpu.utils import checkpoint as uckpt

    s = _committed_state(w=np.arange(4, dtype=np.float32))
    selfop.register_state(s)
    selfop.maybe_checkpoint(rank=0, size=1, idle=True)
    uckpt.wait_pending_saves()
    assert any(n.endswith(".json") for n in os.listdir(d))
    assert selfop.checkpoint_age_s() >= 0.0
    # same commit seq: a later due bucket writes nothing new
    selfop._ckpt_last_bucket -= 1
    before = sorted(os.listdir(d))
    selfop.maybe_checkpoint(rank=0, size=1, idle=True)
    uckpt.wait_pending_saves()
    assert sorted(os.listdir(d)) == before


def test_checkpoint_age_unknown_before_first_write():
    assert selfop.checkpoint_age_s() == -1.0


# -- launcher world restarts -------------------------------------------------

class _FakeProc:
    def __init__(self, rc_after=None):
        self.rc_after = rc_after
        self.terminated = False

    def poll(self):
        if self.terminated:
            return 0
        if self.rc_after and time.monotonic() >= self.rc_after[0]:
            return self.rc_after[1]
        return None

    def terminate(self):
        self.terminated = True
        self.rc_after = (0.0, 0)

    def wait(self, timeout=None):
        return self.poll() or 0

    def kill(self):
        self.terminate()


def test_run_local_elastic_restarts_fresh_world(monkeypatch):
    from horovod_tpu.run.launch import HostBlacklist, run_local_elastic
    monkeypatch.setenv("HOROVOD_FAULT_SPEC", "rank=0:kill:cycle=1")
    worlds = []

    def spawn_fn(slot, env, joiner):
        if not joiner and slot == 0:
            worlds.append(dict(env))  # one entry per world attempt
        if len(worlds) <= 1:
            # first world: everyone dies hard, below the floor
            return _FakeProc(rc_after=(time.monotonic() + 0.05, -9))
        return _FakeProc(rc_after=(time.monotonic() + 0.3, 0))

    rc = run_local_elastic(
        2, ["train.py"], spawn_fn=spawn_fn, min_np=2, restarts=1,
        blacklist=HostBlacklist(base_s=30.0, retries=0), poll_s=0.02)
    assert rc == 0
    assert len(worlds) == 2
    # the first world inherited the fault spec; the restarted one must not
    assert worlds[0].get("HOROVOD_FAULT_SPEC")
    assert "HOROVOD_FAULT_SPEC" not in worlds[1]


def test_run_local_elastic_restart_budget_exhausts():
    from horovod_tpu.run.launch import HostBlacklist, run_local_elastic

    def spawn_fn(slot, env, joiner):
        return _FakeProc(rc_after=(time.monotonic() + 0.05, 3))

    rc = run_local_elastic(
        2, ["train.py"], spawn_fn=spawn_fn, min_np=2, restarts=1,
        blacklist=HostBlacklist(base_s=30.0, retries=0), poll_s=0.02)
    assert rc == 3  # two worlds tried, both lost, budget spent
