"""head_dim-aware flash-attention tile ladder (ADVICE r05): the
512x1024 default block pair was only ever measured for D <= 128;
past that the kernels' per-program VMEM working set grows linearly
with D, so the ladder must shrink as D doubles. These tests pin the
selection logic across a (seq, head_dim) sweep and prove the scaled
tiles still compute the exact attention (interpret mode on CPU)."""

import numpy as np
import pytest

from horovod_tpu.parallel.flash_attention import (
    _BLOCK_K_LADDER, _BLOCK_Q_LADDER, _auto_block, _ladders_for,
)


def _blocks_for(seq_q, seq_k, head_dim):
    ql, kl = _ladders_for(head_dim)
    return _auto_block(seq_q, ql, None), _auto_block(seq_k, kl, None)


def test_default_ladder_unchanged_up_to_128():
    """D <= 128 keeps the measured 512x1024 defaults exactly — the
    ladder change must not perturb validated configurations."""
    for d in (32, 64, 96, 128):
        assert _ladders_for(d) == (_BLOCK_Q_LADDER, _BLOCK_K_LADDER)
    assert _blocks_for(2048, 2048, 128) == (512, 1024)
    assert _blocks_for(512, 1024, 64) == (512, 1024)


def test_ladder_halves_per_doubling_past_128():
    assert _ladders_for(256) == ((256, 128), (512, 256, 128))
    assert _ladders_for(512) == ((128,), (256, 128))
    # floor: tiles never shrink below the 128-lane MXU width
    assert _ladders_for(1024) == ((128,), (128,))
    assert _ladders_for(4096) == ((128,), (128,))


def test_working_set_stays_roughly_d_invariant():
    """The point of the ladder: (block_q + 2*block_k) * D — the
    resident q/k/v tile footprint — must not grow with D beyond the
    validated D=128 envelope (floor-limited tails excepted)."""
    base_q, base_k = _blocks_for(4096, 4096, 128)
    base = (base_q + 2 * base_k) * 128
    for d in (256, 512):
        bq, bk = _blocks_for(4096, 4096, d)
        assert (bq + 2 * bk) * d <= base, (d, bq, bk)


def test_auto_block_divisibility_sweep():
    """Across the sweep, the chosen blocks always divide the sequence
    when any ladder entry does (graceful degradation contract)."""
    for d in (64, 128, 256, 512):
        ql, kl = _ladders_for(d)
        for seq in (128, 256, 384, 512, 1024, 1536, 2048, 4096):
            bq = _auto_block(seq, ql, None)
            bk = _auto_block(seq, kl, None)
            if any(seq % b == 0 for b in ql):
                assert seq % bq == 0, (d, seq, bq)
            if any(seq % b == 0 for b in kl):
                assert seq % bk == 0, (d, seq, bk)
            # explicit blocks always win
            assert _auto_block(seq, ql, 32) == 32


def test_explicit_blocks_still_override():
    assert _auto_block(2048, _ladders_for(512)[0], 256) == 256


@pytest.mark.parametrize("head_dim", [160, 256])
def test_flash_matches_dense_at_large_head_dim(head_dim):
    """Numerical proof at D > 128: the auto-picked (scaled) tiles
    compute the same causal attention as the dense reference. Small
    sequence so interpret mode stays fast; D is the variable under
    test."""
    jnp = pytest.importorskip("jax.numpy")
    from horovod_tpu.parallel.flash_attention import flash_attention
    rng = np.random.RandomState(11)
    b, s, h = 1, 256, 1
    q = jnp.asarray(rng.randn(b, s, h, head_dim) * 0.1, jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, head_dim) * 0.1, jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, head_dim) * 0.1, jnp.float32)
    out = flash_attention(q, k, v, causal=True, interpret=True)

    qf = np.asarray(q, np.float64)[:, :, 0]
    kf = np.asarray(k, np.float64)[:, :, 0]
    vf = np.asarray(v, np.float64)[:, :, 0]
    scores = np.einsum("bqd,bkd->bqk", qf, kf) / np.sqrt(head_dim)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bqk,bkd->bqd", p, vf)
    np.testing.assert_allclose(np.asarray(out)[:, :, 0], ref,
                               atol=3e-5)
