"""Runtime thread-affinity sanitizer (common/threadcheck.py).

Unit tier for the dynamic half of hvdlint's thread-ownership
analyzer: raise/warn/disabled modes, the first-write-free rule,
lock-held cross-role writes, owner migration for unpinned fields, the
unarmed no-op contract (checked fields stay plain attributes, sites
enumerable), and the metrics-plane mirror of the violation counter.
The mp tier arms HOROVOD_TPU_THREADCHECK=1 in every spawned world
(tests/test_multiprocess.py::_base_env), so each multiprocess
scenario doubles as a zero-violation affinity regression test; this
module proves the sanitizer's own semantics in-process.
"""

import threading

import pytest

from horovod_tpu.common import lockdep, threadcheck
from horovod_tpu.common.threadcheck import ThreadAffinityError

pytestmark = pytest.mark.lint


@pytest.fixture()
def armed():
    """raise-mode threadcheck + warn-mode lockdep (the held-lock
    witness), restored to env-driven defaults afterwards."""
    threadcheck.reset("raise")
    lockdep.reset("warn")
    try:
        yield
    finally:
        threadcheck.reset()
        lockdep.reset()


def _toy(owner=None):
    class Toy:
        pass
    threadcheck.install(Toy, "x", "test.Toy.x", owner=owner)
    return Toy


def _in_role(role, fn):
    """Run fn on a thread registered under ``role``; re-raise its
    exception (if any) in the caller."""
    box = {}

    def run():
        threadcheck.register_role(role)
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            box["exc"] = e

    t = threading.Thread(target=run)
    t.start()
    t.join()
    if "exc" in box:
        raise box["exc"]


def test_first_write_free_then_fixed_owner_enforced(armed):
    Toy = _toy(owner="hvd-background")
    obj = Toy()
    obj.x = 1  # constructor-style init from main: always free
    assert obj.x == 1
    with pytest.raises(ThreadAffinityError) as ei:
        obj.x = 2  # second write from main, no lock, owner is bg
    msg = str(ei.value)
    assert "test.Toy.x" in msg and "hvd-background" in msg \
        and "troubleshooting" in msg
    # the violating write was refused, not stored
    assert obj.x == 1
    # the owning role writes freely
    _in_role("hvd-background", lambda: setattr(obj, "x", 3))
    assert obj.x == 3


def test_cross_role_write_legal_under_lockdep_lock(armed):
    Toy = _toy(owner="hvd-background")
    obj = Toy()
    obj.x = 1
    lk = lockdep.lock("threadcheck_test.L")
    with lk:
        obj.x = 2  # main trespasses WITH a tracked lock held: legal
    assert obj.x == 2 and threadcheck.violation_count() == 0


def test_owner_migrates_for_unpinned_fields(armed):
    Toy = _toy(owner=None)
    obj = Toy()
    obj.x = 1  # first write: owner seeds to main
    lk = lockdep.lock("threadcheck_test.M")

    def locked_write():
        with lk:
            obj.x = 2  # legal (lock held) -> ownership migrates

    _in_role("hvd-overlap", locked_write)
    assert obj.x == 2
    _in_role("hvd-overlap", lambda: setattr(obj, "x", 3))  # now owner
    with pytest.raises(ThreadAffinityError):
        obj.x = 4  # main lost ownership at the handoff
    assert threadcheck.violation_count() == 1


def test_warn_mode_counts_without_raising(armed, capsys):
    threadcheck.reset("warn")
    Toy = _toy(owner="hvd-background")
    obj = Toy()
    obj.x = 1
    obj.x = 2  # violation: logged + counted, value still stored
    obj.x = 3
    assert obj.x == 3
    assert threadcheck.violation_count() == 2
    assert "test.Toy.x" in capsys.readouterr().err


def test_unarmed_is_a_plain_attribute():
    threadcheck.reset("")  # force-disable regardless of ambient env
    try:
        Toy = _toy(owner="hvd-background")
        # install() recorded the site but touched nothing
        assert "x" not in Toy.__dict__
        assert (Toy, "x", "test.Toy.x", "hvd-background") \
            in threadcheck.sites()
        obj = Toy()
        obj.x = 1
        obj.x = 2  # any thread, any order: no descriptor, no checks
        assert obj.x == 2 and threadcheck.violation_count() == 0
        # register_role is a no-op too: no thread-local state accrues
        threadcheck.register_role("hvd-background")
        assert threadcheck.current_role() == threadcheck.MAIN_ROLE
    finally:
        threadcheck.reset()


def test_runtime_sites_enumerated_and_unarmed_by_default():
    """The shipped install() sites are visible unarmed (the no-op
    contract the ISSUE pins): importing the wired modules registers
    the checked fields, yet none of the classes carry a descriptor
    until armed."""
    from horovod_tpu.common import coordinator, overlap  # noqa: F401
    from horovod_tpu.common import runtime, trace  # noqa: F401

    threadcheck.reset("")  # force-disable, stripping any leftovers
    try:
        ids = {fid for _cls, _attr, fid, _own in threadcheck.sites()}
        assert {
            "runtime.Runtime._tenant_lane",
            "coordinator.ResponseCache.epoch",
            "coordinator.StallInspector._last_check",
            "overlap.OverlapRunner._cycles_total",
            "trace.WorldTraceWriter.spans_written",
        } <= ids, ids
        for cls, attr, _fid, _own in threadcheck.sites():
            assert not isinstance(cls.__dict__.get(attr),
                                  threadcheck._Checked), (cls, attr)
    finally:
        threadcheck.reset()


def test_reset_arms_and_strips_descriptors():
    Toy = _toy()
    threadcheck.reset("raise")
    try:
        assert isinstance(Toy.__dict__["x"], threadcheck._Checked)
    finally:
        threadcheck.reset("")
    assert "x" not in Toy.__dict__
    threadcheck.reset()


def test_objects_built_before_arming_keep_working():
    """The descriptor backs values in the instance __dict__ under the
    attribute's own name, so pre-arm objects transparently fall under
    checking when a test re-arms mid-flight."""
    threadcheck.reset("")
    Toy = _toy(owner="hvd-background")
    obj = Toy()
    obj.x = 1  # plain attribute write, pre-arm
    threadcheck.reset("raise")
    lockdep.reset("warn")
    try:
        assert obj.x == 1  # readable through the descriptor
        # no owner was recorded pre-arm, so the object defaults to
        # main ownership (forgiving: pre-arm objects were built by
        # the test's own thread) — main keeps writing...
        obj.x = 2
        assert obj.x == 2
        # ...but a foreign role is checked immediately
        with pytest.raises(ThreadAffinityError):
            _in_role("hvd-overlap", lambda: setattr(obj, "x", 3))
    finally:
        threadcheck.reset()
        lockdep.reset()


def test_env_arming_and_lockdep_coupling(monkeypatch):
    """HOROVOD_TPU_THREADCHECK=1 arms raise mode from the env, and
    implicitly arms lockdep in warn mode when LOCKCHECK is unset —
    threadcheck's 'synchronized' witness is lockdep's held stack,
    which plain unwrapped locks never feed."""
    monkeypatch.setenv("HOROVOD_TPU_THREADCHECK", "1")
    monkeypatch.delenv("HOROVOD_TPU_LOCKCHECK", raising=False)
    threadcheck.reset(None)  # None = re-read the env
    lockdep.reset(None)
    try:
        assert threadcheck.enabled()
        assert threadcheck._get_mode() == "raise"
        assert lockdep._get_mode() == "warn"
    finally:
        monkeypatch.delenv("HOROVOD_TPU_THREADCHECK", raising=False)
        threadcheck.reset()
        lockdep.reset()


def test_threadcheck_counter_reaches_metrics_plane(monkeypatch):
    """hvd_threadcheck_violations_total mirrors violation_count()
    through the runtime collector, next to the lockcheck counter."""
    import horovod_tpu as hvd

    hvd.shutdown()
    threadcheck.reset("warn")
    lockdep.reset("warn")
    monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
    try:
        Toy = _toy(owner="hvd-background")
        obj = Toy()
        obj.x = 1
        obj.x = 2  # main vs hvd-background, no lock: counted
        assert threadcheck.violation_count() >= 1
        hvd.init()
        try:
            view = hvd.metrics()
            rec = view["local"]["hvd_threadcheck_violations_total"]
            assert rec["v"] >= 1.0, rec
            assert rec["v"] == float(threadcheck.violation_count())
        finally:
            hvd.shutdown()
    finally:
        threadcheck.reset()
        lockdep.reset()
