"""Launcher tests: local spawn, multi-host driver/task protocol (task
servers run in threads standing in for ssh-reached hosts), failure
propagation. The reference leaves its host-discovery machinery
untested (SURVEY §4); we do better."""

import os
import subprocess
import sys
import tempfile
import threading

import pytest

from horovod_tpu.run.launch import (
    parse_hosts, run_local, run_multihost,
)
from horovod_tpu.run.services import TaskServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT_OK = """
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import horovod_tpu as hvd
hvd.init()
out = hvd.allreduce(np.full(3, float(hvd.rank() + 1), np.float32),
                    op=hvd.Sum)
expected = sum(range(1, hvd.size() + 1))
assert np.allclose(out, expected), (out, expected)
with open(os.path.join({tmp!r}, f"rank{{hvd.rank()}}.ok"), "w") as f:
    f.write(str(hvd.size()))
hvd.shutdown()
"""

SCRIPT_FAIL = """
import os, sys
sys.path.insert(0, {repo!r})
import horovod_tpu as hvd
hvd.init()
rank = hvd.rank()
hvd.shutdown()
sys.exit(3 if rank == 1 else 0)
"""


def _env():
    return {"JAX_PLATFORMS": "cpu", "HOROVOD_CYCLE_TIME": "1",
            "PYTHONPATH": REPO}


def test_parse_hosts():
    assert parse_hosts("a:4,b:2") == [("a", 4), ("b", 2)]
    assert parse_hosts("single") == [("single", 1)]
    assert parse_hosts("h:1, g:3") == [("h", 1), ("g", 3)]


def test_run_local_world():
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, "train.py")
        with open(script, "w") as f:
            f.write(SCRIPT_OK.format(repo=REPO, tmp=tmp))
        code = run_local(3, [sys.executable, script], env=_env())
        assert code == 0
        for r in range(3):
            assert os.path.exists(os.path.join(tmp, f"rank{r}.ok"))


def test_run_local_propagates_failure():
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, "train.py")
        with open(script, "w") as f:
            f.write(SCRIPT_FAIL.format(repo=REPO))
        code = run_local(2, [sys.executable, script], env=_env())
        assert code == 3


def test_multihost_driver_protocol():
    """Two simulated hosts x two slots: the full driver flow
    (registration, ring probe, rank assignment, launch, exit
    collection) over real TCP, with task servers in threads."""
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, "train.py")
        with open(script, "w") as f:
            f.write(SCRIPT_OK.format(repo=REPO, tmp=tmp))

        threads = []

        def spawn(host_index, driver_addr, driver_port, env):
            os.environ["HOROVOD_SECRET_KEY"] = env["HOROVOD_SECRET_KEY"]
            server = TaskServer(host_index, driver_addr, driver_port,
                                env["HOROVOD_SECRET_KEY"].encode())
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            threads.append(t)
            return t

        code = run_multihost(
            [("hostA", 2), ("hostB", 2)],
            [sys.executable, script],
            env=_env(), spawn_fn=spawn, start_timeout=30.0,
            host_check_fn=lambda h: True)
        assert code == 0
        for r in range(4):
            assert os.path.exists(os.path.join(tmp, f"rank{r}.ok")), \
                f"rank {r} never ran"


def test_cli_local():
    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, "train.py")
        with open(script, "w") as f:
            f.write(SCRIPT_OK.format(repo=REPO, tmp=tmp))
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
             sys.executable, script],
            env={**os.environ, **_env()}, cwd=REPO,
            capture_output=True, timeout=120)
        assert proc.returncode == 0, proc.stderr.decode()
        assert os.path.exists(os.path.join(tmp, "rank0.ok"))
        assert os.path.exists(os.path.join(tmp, "rank1.ok"))


def test_unreachable_host_fails_fast_with_named_host():
    """A dead host must abort BEFORE anything is spawned, naming the
    host (reference: run/run.py:44-100 threaded ssh pre-check) — not
    surface later as a generic registration timeout."""
    spawned = []

    def spawn(host_index, driver_addr, driver_port, env):
        spawned.append(host_index)

    with pytest.raises(RuntimeError, match="deadhost.*unreachable|"
                                           "unreachable.*deadhost"):
        run_multihost(
            [("hostA", 1), ("deadhost", 1)],
            [sys.executable, "-c", "pass"],
            env=_env(), spawn_fn=spawn, start_timeout=5.0,
            host_check_fn=lambda h: h != "deadhost")
    assert spawned == [], "task servers were spawned despite the " \
                          "failed pre-check"


def test_host_check_cache_skips_repeat_probes(tmp_path):
    """Successful checks are cached (reference: run/util/cache.py 60-min
    result cache); failures are always re-probed."""
    from horovod_tpu.run.launch import HostCheckCache, \
        check_hosts_reachable
    calls = []

    def check(h):
        calls.append(h)
        return h != "badhost"

    path = str(tmp_path / "hostcheck.json")
    hosts = [("alpha", 1), ("beta", 1)]
    check_hosts_reachable(hosts, check_fn=check,
                          cache=HostCheckCache(path=path))
    assert sorted(calls) == ["alpha", "beta"]

    # second run with a fresh cache object backed by the same file:
    # both hosts hit the cache, no probes
    calls.clear()
    check_hosts_reachable(hosts, check_fn=check,
                          cache=HostCheckCache(path=path))
    assert calls == []

    # an expired cache re-probes
    calls.clear()
    check_hosts_reachable(hosts, check_fn=check,
                          cache=HostCheckCache(path=path, ttl_s=0.0))
    assert sorted(calls) == ["alpha", "beta"]

    # failures are never served from cache
    calls.clear()
    cache = HostCheckCache(path=path)
    with pytest.raises(RuntimeError, match="badhost"):
        check_hosts_reachable([("badhost", 1)], check_fn=check,
                              cache=cache)
    calls.clear()
    with pytest.raises(RuntimeError, match="badhost"):
        check_hosts_reachable([("badhost", 1)], check_fn=check,
                              cache=cache)
    assert calls == ["badhost"]


def _fn_for_api_run(scale):
    import horovod_tpu as hvd
    return (hvd.rank() * scale, hvd.size())


def test_api_run_collects_ordered_results():
    """(reference contract: horovod.spark.run returns per-rank results
    ordered by rank, spark/__init__.py:195-199)"""
    from horovod_tpu.run.api import run
    results = run(_fn_for_api_run, args=(10,), num_proc=3, env=_env())
    assert results == [(0, 3), (10, 3), (20, 3)]
