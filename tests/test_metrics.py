"""Metrics-plane tests: registry semantics, world-fold merge rules,
wire codec, Prometheus rendering, the rank-0 read surfaces, the
disabled path's no-op guarantee, and multi-process world aggregation
(including the hierarchical local-root fold and a SIGKILL mid-scrape
preserving the PR 2 fail-fast abort)."""

import json
import os
import signal
import urllib.request

import numpy as np
import pytest

from horovod_tpu.common import metrics as hm
from horovod_tpu.common import wire
from tests.test_multiprocess import run_scenario

_METRICS_ENV = {
    "HOROVOD_TPU_METRICS": "1",
    "HOROVOD_TPU_METRICS_INTERVAL": "0.2",
    "HOROVOD_TPU_METRICS_PORT": "0",
}


# -- registry / metric semantics -------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = hm.MetricsRegistry()
        c = reg.counter("c_total")
        c.inc()
        c.inc(4)
        g = reg.gauge("g", agg=hm.AGG_MAX)
        g.set(2.5)
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        snap = reg.snapshot()
        assert snap["c_total"] == {"k": "c", "v": 5.0}
        assert snap["g"] == {"k": "g", "agg": "max", "v": 2.5}
        assert snap["h_seconds"]["counts"] == [1, 1, 1]
        assert snap["h_seconds"]["count"] == 3
        assert snap["h_seconds"]["sum"] == pytest.approx(5.55)

    def test_factories_memoize_by_name(self):
        reg = hm.MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")  # kind mismatch on a reused name

    def test_reuse_with_different_identity_raises(self):
        """agg and bucket bounds are metric identity (merge_into fails
        loudly on them cross-rank) — a second call site disagreeing
        within a rank must raise, not silently adopt the first."""
        reg = hm.MetricsRegistry()
        reg.gauge("g", agg=hm.AGG_MAX)
        with pytest.raises(ValueError):
            reg.gauge("g")  # default agg=sum
        reg.histogram("h", buckets=(0.1, 1.0))
        with pytest.raises(ValueError):
            reg.histogram("h")  # default latency buckets

    def test_histogram_rejects_unsorted_buckets(self):
        with pytest.raises(ValueError):
            hm.Histogram("h", buckets=(1.0, 0.5))

    def test_collectors_run_at_snapshot(self):
        reg = hm.MetricsRegistry()
        g = reg.gauge("depth")
        reg.add_collector(lambda: g.set(7))
        assert reg.snapshot()["depth"]["v"] == 7.0

    def test_disabled_registry_is_noop(self):
        reg = hm.create_registry(False)
        assert reg is hm.NOOP_REGISTRY
        assert reg.counter("a") is hm.NOOP_METRIC
        assert reg.gauge("b") is hm.NOOP_METRIC
        assert reg.histogram("c") is hm.NOOP_METRIC
        hm.NOOP_METRIC.inc()
        hm.NOOP_METRIC.observe(1.0)
        hm.NOOP_METRIC.set(2.0)
        assert reg.snapshot() == {}


class TestMergeSemantics:
    def test_counters_sum(self):
        a = {"c": {"k": "c", "v": 3.0}}
        hm.merge_into(a, {"c": {"k": "c", "v": 4.0}})
        assert a["c"]["v"] == 7.0

    def test_gauges_sum_or_max(self):
        a = {"d": {"k": "g", "agg": "sum", "v": 2.0},
             "age": {"k": "g", "agg": "max", "v": 1.0}}
        hm.merge_into(a, {"d": {"k": "g", "agg": "sum", "v": 5.0},
                          "age": {"k": "g", "agg": "max", "v": 9.0}})
        assert a["d"]["v"] == 7.0
        assert a["age"]["v"] == 9.0  # max-age: oldest silence wins

    def test_histograms_add_bucketwise(self):
        a = {"h": {"k": "h", "bounds": [0.1, 1.0],
                   "counts": [1, 0, 2], "sum": 5.0, "count": 3}}
        hm.merge_into(a, {"h": {"k": "h", "bounds": [0.1, 1.0],
                                "counts": [0, 4, 1], "sum": 2.0,
                                "count": 5}})
        assert a["h"]["counts"] == [1, 4, 3]
        assert a["h"]["sum"] == 7.0 and a["h"]["count"] == 8

    def test_identity_mismatches_raise(self):
        with pytest.raises(ValueError):
            hm.merge_into({"x": {"k": "c", "v": 1.0}},
                          {"x": {"k": "g", "agg": "sum", "v": 1.0}})
        with pytest.raises(ValueError):
            hm.merge_into(
                {"h": {"k": "h", "bounds": [1.0], "counts": [0, 0],
                       "sum": 0.0, "count": 0}},
                {"h": {"k": "h", "bounds": [2.0], "counts": [0, 0],
                       "sum": 0.0, "count": 0}})

    def test_merge_into_copies_new_records(self):
        src = {"h": {"k": "h", "bounds": [1.0], "counts": [1, 0],
                     "sum": 0.5, "count": 1}}
        dst = hm.merge_into({}, src)
        hm.merge_into(dst, src)
        assert src["h"]["counts"] == [1, 0]  # source untouched
        assert dst["h"]["counts"] == [2, 0]


class TestWireCodec:
    def _snap(self):
        reg = hm.MetricsRegistry()
        reg.counter("bytes_total").inc(4096)
        reg.gauge('age{peer="3"}', agg=hm.AGG_MAX).set(1.5)
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.1))
        h.observe(0.005)
        h.observe(0.05)
        return reg.snapshot()

    def test_roundtrip(self):
        snap = self._snap()
        nranks, back = wire.parse_metrics_frame(
            wire.serialize_metrics_frame(3, snap))
        assert nranks == 3
        assert back == snap

    def test_combine_sums_frames_and_ranks(self):
        snap = self._snap()
        f = wire.serialize_metrics_frame(1, snap)
        nranks, merged = wire.parse_metrics_frame(
            wire.combine_metrics_frames([f, f, f]))
        assert nranks == 3
        assert merged["bytes_total"]["v"] == 3 * 4096
        assert merged['age{peer="3"}']["v"] == 1.5  # max, not sum
        assert merged["lat_seconds"]["counts"] == [3, 3, 0]

    def test_unknown_version_rejected(self):
        blob = bytearray(wire.serialize_metrics_frame(1, {}))
        blob[0] = 99
        with pytest.raises(ValueError):
            wire.parse_metrics_frame(bytes(blob))

    def test_combine_drop_incompatible_keeps_healthy_frames(self):
        """A local root folding its host must skip ONE skewed leaf's
        frame, not silence the whole host (TcpWorker.send_metrics)."""
        good = wire.serialize_metrics_frame(
            1, {"b_total": {"k": "c", "v": 5.0}})
        skewed = wire.serialize_metrics_frame(
            1, {"b_total": {"k": "g", "agg": "sum", "v": 1.0}})
        nranks, merged = wire.parse_metrics_frame(
            wire.combine_metrics_frames(
                [good, skewed, b"\x99garbage", good],
                drop_incompatible=True))
        assert nranks == 2
        assert merged["b_total"] == {"k": "c", "v": 10.0}
        with pytest.raises(Exception):
            wire.combine_metrics_frames([good, skewed])


class TestPrometheusRendering:
    def test_counters_gauges_and_labels(self):
        txt = hm.render_prometheus({
            "a_total": {"k": "c", "v": 5.0},
            'ops_total{op="allreduce"}': {"k": "c", "v": 2.0},
            "depth": {"k": "g", "agg": "sum", "v": 3.0},
        })
        assert "# TYPE a_total counter" in txt
        assert "a_total 5" in txt.splitlines()
        assert 'ops_total{op="allreduce"} 2' in txt.splitlines()
        assert "# TYPE depth gauge" in txt

    def test_help_renders_once_per_base(self):
        reg = hm.MetricsRegistry()
        reg.counter('ops_total{op="a"}', "batches executed").inc()
        reg.counter('ops_total{op="b"}').inc()
        txt = hm.render_prometheus(reg.snapshot())
        assert txt.count("# HELP ops_total batches executed") == 1
        assert txt.count("# TYPE ops_total counter") == 1

    def test_histogram_renders_cumulative_with_inf(self):
        txt = hm.render_prometheus({
            'h_seconds{op="x"}': {"k": "h", "bounds": [0.1, 1.0],
                                  "counts": [2, 1, 3], "sum": 9.5,
                                  "count": 6}})
        lines = txt.splitlines()
        assert "# TYPE h_seconds histogram" in lines
        assert 'h_seconds_bucket{op="x",le="0.1"} 2' in lines
        assert 'h_seconds_bucket{op="x",le="1"} 3' in lines
        assert 'h_seconds_bucket{op="x",le="+Inf"} 6' in lines
        assert 'h_seconds_sum{op="x"} 9.5' in lines
        assert 'h_seconds_count{op="x"} 6' in lines


class TestWorldAggregator:
    def test_world_folds_local_and_owner_frames(self):
        agg = hm.WorldAggregator(size=4)
        agg.update_local({"b_total": {"k": "c", "v": 10.0}})
        frame = wire.serialize_metrics_frame(
            2, {"b_total": {"k": "c", "v": 32.0}})
        agg.ingest(2, frame)
        w = agg.world()
        assert w["b_total"]["v"] == 42.0
        assert w["hvd_ranks_reporting"]["v"] == 3.0  # 1 local + 2 folded
        assert w["hvd_world_size"]["v"] == 4.0

    def test_latest_frame_wins_no_double_count(self):
        agg = hm.WorldAggregator(size=2)
        for v in (5.0, 8.0):
            agg.ingest(1, wire.serialize_metrics_frame(
                1, {"b_total": {"k": "c", "v": v}}))
        assert agg.world()["b_total"]["v"] == 8.0

    def test_garbled_frame_dropped(self):
        agg = hm.WorldAggregator(size=2)
        agg.ingest(1, b"\x99garbage")
        assert agg.world()["hvd_ranks_reporting"]["v"] == 0.0

    def test_identity_mismatched_frame_dropped_not_poisonous(self):
        """A parseable frame whose metric identity disagrees (skewed
        code across ranks) must be dropped at ingest — never stored to
        make every later world() raise and 500 the endpoint."""
        agg = hm.WorldAggregator(size=2)
        agg.update_local({"x": {"k": "c", "v": 1.0}})
        agg.ingest(1, wire.serialize_metrics_frame(
            1, {"x": {"k": "g", "agg": "sum", "v": 9.0}}))
        w = agg.world()  # must not raise
        assert w["x"]["v"] == 1.0
        assert w["hvd_ranks_reporting"]["v"] == 1.0


def test_http_server_serves_prometheus_and_json():
    snap = {"up_total": {"k": "c", "v": 1.0}}
    srv = hm.MetricsHTTPServer(lambda: snap, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        txt = urllib.request.urlopen(base + "/metrics",
                                     timeout=5).read().decode()
        assert "up_total 1" in txt
        data = json.loads(urllib.request.urlopen(
            base + "/metrics.json", timeout=5).read().decode())
        assert data == snap
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        srv.close()


# -- stall-report extension (satellite) ------------------------------------

def test_stall_report_carries_world_stats(capsys):
    from horovod_tpu.common import logging as hlog
    from horovod_tpu.common.coordinator import MessageTable, StallInspector
    from horovod_tpu.common.message import Request

    hlog.set_level("warning")
    insp = StallInspector(size=2, warning_time=0.0)
    table = MessageTable()
    table.increment_tensor_count(
        Request(request_rank=0, tensor_name="grad"), 2)
    insp.check(table, world_stats="tensor queue depth 3; oldest peer "
                                  "heartbeat ages: rank 1 4.2s")
    err = capsys.readouterr().err
    assert "Stalled op: grad" in err
    assert "[world: tensor queue depth 3" in err
    assert "rank 1 4.2s" in err


# -- the disabled path: no-op hooks on every instrumented site -------------

def test_disabled_metrics_installs_noop_hooks_everywhere():
    """Tier-1 guard for the zero-overhead contract: with
    HOROVOD_TPU_METRICS unset (the default), every instrumented call
    site across the runtime, controller and op backends must hold the
    shared no-op metric — not a real counter, not None-guarded
    ad-hockery — and the gated clock reads must be off."""
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b

    hvd.shutdown()
    assert os.environ.get("HOROVOD_TPU_METRICS", "0") != "1"
    hvd.init()
    try:
        rt = _b.runtime()
        assert rt.metrics is hm.NOOP_REGISTRY
        assert not rt._metrics_on
        sites = [n for n in dir(rt) if n.startswith("_m_")]
        assert len(sites) >= 15, sites
        for n in sites:
            assert getattr(rt, n) is hm.NOOP_METRIC, n
        om = rt.op_manager
        assert not om._metrics_on
        for m in (list(om._m_ops.values()) + list(om._m_bytes.values())
                  + list(om._m_wall.values()) + [om._m_fill]):
            assert m is hm.NOOP_METRIC
        for b in om._backends:
            assert b.m_ops is hm.NOOP_METRIC, b.name
            assert b.m_bytes is hm.NOOP_METRIC, b.name
        ctl = rt.controller
        assert not ctl._metrics_on
        assert ctl._m_ctrl_rx is hm.NOOP_METRIC
        assert ctl._m_ctrl_tx is hm.NOOP_METRIC
        assert rt._aggregator is None
        assert rt._metrics_http is None
        view = hvd.metrics()
        assert not view["enabled"] and view["local"] == {}
    finally:
        hvd.shutdown()


# -- single-process end-to-end (size-1 world, all three surfaces) ----------

def test_metrics_single_process_surfaces(tmp_path):
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common.config import Config

    hvd.shutdown()
    log_path = str(tmp_path / "metrics.jsonl")
    cfg = Config.from_env()
    cfg.metrics_enabled = True
    cfg.metrics_interval_s = 0.05
    cfg.metrics_port = 0
    cfg.metrics_log = log_path
    hvd.init(config=cfg)
    try:
        x = np.ones(512, np.float32)
        for i in range(4):
            hvd.allreduce(x, average=False, name=f"sp.{i}")
        import time
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if os.path.exists(log_path) and \
                    os.path.getsize(log_path) > 0:
                break
            time.sleep(0.05)
        view = hvd.metrics()
        assert view["enabled"]
        assert view["local"]["hvd_bytes_allreduced_total"]["v"] \
            == 4 * x.nbytes
        assert view["world"]["hvd_bytes_allreduced_total"]["v"] \
            == 4 * x.nbytes
        assert view["local"]['hvd_ops_total{op="allreduce"}']["v"] == 4
        assert view["local"]["hvd_cycle_seconds"]["count"] > 0
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{view['http_port']}/metrics",
            timeout=5).read().decode()
        assert f"hvd_bytes_allreduced_total {4 * x.nbytes}" in txt
        with open(log_path) as f:
            line = json.loads(f.readline())
        assert "world" in line and "ts" in line
    finally:
        hvd.shutdown()


# -- multi-process world aggregation ---------------------------------------

@pytest.mark.parametrize("mode,extra", [
    ("shm", {}),
    ("socket", {"HOROVOD_TPU_SHM": "0"}),
])
def test_metrics_world_aggregation(mode, extra):
    """ws=4: rank 0's world-aggregated bytes_allreduced must equal the
    sum of every rank's local counter, and the live /metrics scrape
    must agree (the acceptance-criteria assertion)."""
    run_scenario("metrics_world", 4, timeout=120.0,
                 extra_env={**_METRICS_ENV, **extra})


def test_metrics_world_aggregation_hier_controller():
    """Same world-sum exactness when remote leaves fold behind a local
    root: the root must combine its host's METRICS frames into one
    upward frame without losing or double-counting ranks."""
    run_scenario("metrics_world", 4, timeout=120.0,
                 extra_env=_METRICS_ENV,
                 per_rank_env=lambda rank: {
                     "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_metrics_sigkill_mid_scrape_preserves_abort():
    """SIGKILL rank 1 mid-collective while rank 0 is being scraped:
    survivors still raise WorldAbortedError naming the dead rank
    within the heartbeat deadline — the metrics plane must never mask
    the PR 2 fail-fast invariant."""
    run_scenario(
        "metrics_sigkill", 3, timeout=60.0,
        extra_env={**_METRICS_ENV,
                   "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
                   "HOROVOD_HEARTBEAT_TIMEOUT": "3",
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=25"},
        expect_rc={1: -signal.SIGKILL})
