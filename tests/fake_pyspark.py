"""Process-based double of the pyspark surface horovod_tpu.spark uses.

The test image has no pyspark and installs are off, so this module
models what Spark local mode actually does with a partition function:
each partition executes in its own forked worker process and the
"driver" collects the yielded rows. That preserves exactly what the
Spark integration needs proven — real multi-process rendezvous,
coordinator socket handoff, per-rank env, result ordering — without
the Spark runtime itself (the reference asserts the same things
against local-mode Spark, test/test_spark.py:51-69).

Install with ``fake_pyspark.install()`` BEFORE importing
horovod_tpu.spark's run() path; it registers ``pyspark`` and
``pyspark.sql`` in sys.modules.
"""

from __future__ import annotations

import multiprocessing
import sys
import types


class _MappedRDD:
    def __init__(self, parts, fn):
        self._parts = parts
        self._fn = fn

    def collect(self):
        ctx = multiprocessing.get_context("fork")
        q = ctx.Queue()
        fn = self._fn

        def _worker(i, part):
            try:
                out = list(fn(i, iter(part)))
                q.put((i, True, out))
            except BaseException as e:  # surfaced in the driver
                q.put((i, False, repr(e)))

        procs = [ctx.Process(target=_worker, args=(i, part), daemon=True)
                 for i, part in enumerate(self._parts)]
        for p in procs:
            p.start()
        rows = {}
        errors = []
        for _ in procs:
            i, ok, out = q.get(timeout=120)
            if ok:
                rows[i] = out
            else:
                errors.append((i, out))
        for p in procs:
            p.join(timeout=30)
        if errors:
            raise RuntimeError(f"partition failures: {errors}")
        return [row for i in sorted(rows) for row in rows[i]]


class _RDD:
    def __init__(self, data, num_partitions):
        data = list(data)
        # one element per partition when counts match (the spark.run
        # usage shape: parallelize(range(n), n))
        self._parts = [[] for _ in range(num_partitions)]
        for i, x in enumerate(data):
            self._parts[i % num_partitions].append(x)

    def mapPartitionsWithIndex(self, fn):
        return _MappedRDD(self._parts, fn)


class _SparkContext:
    defaultParallelism = 2

    def parallelize(self, data, num_partitions):
        return _RDD(data, num_partitions)


class _Session:
    def __init__(self):
        self.sparkContext = _SparkContext()


class _Builder:
    def getOrCreate(self):
        return _Session()


class SparkSession:
    builder = _Builder()


def install() -> None:
    pyspark = types.ModuleType("pyspark")
    pyspark.__version__ = "0.0-fake"
    sql = types.ModuleType("pyspark.sql")
    sql.SparkSession = SparkSession
    pyspark.sql = sql
    sys.modules["pyspark"] = pyspark
    sys.modules["pyspark.sql"] = sql
