"""Async collective completion: the negotiation loop must keep cycling
while an earlier collective is still executing (reference analog:
Status::InProgress + detached finalizer threads,
cuda_operations.cc:148-179)."""

import threading
import time

import numpy as np
import pytest

from horovod_tpu.common.config import Config
from horovod_tpu.common.controller import LocalController
from horovod_tpu.common.finalizer import Finalizer
from horovod_tpu.common.message import (
    RequestType, numpy_dtype_to_datatype,
)
from horovod_tpu.common.runtime import Runtime
from horovod_tpu.common.status import Status
from horovod_tpu.common.tensor_table import TensorTableEntry
from horovod_tpu.ops.backend import CollectiveBackend
from horovod_tpu.ops.operation_manager import OperationManager


class GatedAsyncBackend(CollectiveBackend):
    """Issues instantly; the FIRST batch's completion blocks on a gate
    the test controls — a stand-in for a huge in-flight allreduce."""

    name = "gated-async"

    def __init__(self):
        self.gate = threading.Event()
        self.issued = []          # tensor names in issue order
        self.issued_cv = threading.Condition()

    def enabled(self, entries, response):
        return True

    def execute_allreduce(self, entries, response):
        for e in entries:
            e.output = e.tensor
        with self.issued_cv:
            first = not self.issued
            self.issued.extend(response.tensor_names)
            self.issued_cv.notify_all()
        gate = self.gate if first else None

        def finalize():
            if gate is not None:
                assert gate.wait(10.0), "test gate never opened"
            for e in entries:
                if e.callback:
                    e.callback(Status.OK())

        assert self.finalizer is not None
        assert self.finalizer.submit(finalize)
        return Status.InProgress()


def _enqueue(rt, name, done_events):
    arr = np.arange(4, dtype=np.float32)
    entry = TensorTableEntry(tensor_name=name, tensor=arr)
    ev = threading.Event()
    done_events[name] = ev

    def callback(status):
        assert status.ok(), status.reason
        ev.set()

    entry.callback = callback
    st = rt.enqueue(RequestType.ALLREDUCE, entry,
                    numpy_dtype_to_datatype(arr.dtype), arr.shape)
    assert st.ok(), st.reason


def test_negotiation_continues_while_collective_in_flight():
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.stall_check_disable = True
    backend = GatedAsyncBackend()
    rt = Runtime(cfg, LocalController(), OperationManager([backend]))
    rt.start()
    done = {}
    try:
        _enqueue(rt, "big.0", done)
        # wait until cycle N has ISSUED the big collective
        with backend.issued_cv:
            assert backend.issued_cv.wait_for(
                lambda: "big.0" in backend.issued, timeout=10.0)

        # cycle N+1: a second tensor must negotiate, issue, AND complete
        # while big.0 is still executing (its gate is closed).
        _enqueue(rt, "small.1", done)
        assert done["small.1"].wait(10.0), \
            "negotiation loop blocked behind the in-flight collective"
        assert not done["big.0"].is_set(), \
            "big.0 completed before its gate opened?"

        backend.gate.set()
        assert done["big.0"].wait(10.0)
    finally:
        backend.gate.set()
        rt.request_shutdown()
        rt.join(10.0)


def test_drain_completes_in_flight_on_shutdown():
    """Shutdown must wait for issued collectives: their callbacks fire
    with the real status, not SHUT_DOWN_ERROR."""
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.stall_check_disable = True
    backend = GatedAsyncBackend()
    rt = Runtime(cfg, LocalController(), OperationManager([backend]))
    rt.start()
    done = {}
    try:
        _enqueue(rt, "big.0", done)
        with backend.issued_cv:
            assert backend.issued_cv.wait_for(
                lambda: "big.0" in backend.issued, timeout=10.0)
        rt.request_shutdown()
        time.sleep(0.05)            # loop exits; drain blocks on gate
        assert not done["big.0"].is_set()
        backend.gate.set()
        rt.join(10.0)
        assert done["big.0"].wait(10.0)
    finally:
        backend.gate.set()
        rt.request_shutdown()
        rt.join(10.0)


def test_finalizer_drain_refuses_new_work():
    fin = Finalizer()
    ran = threading.Event()
    assert fin.submit(ran.set)
    fin.drain(5.0)
    assert ran.is_set()
    assert not fin.submit(lambda: None)


def test_sync_mode_keeps_blocking_semantics():
    """HOROVOD_ASYNC_COMPLETION=0: no finalizer attached; a backend
    without one returns OK synchronously and callbacks fire in-loop."""
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.stall_check_disable = True
    cfg.async_completion = False

    class SyncBackend(CollectiveBackend):
        name = "sync"

        def enabled(self, entries, response):
            return True

        def execute_allreduce(self, entries, response):
            assert self.finalizer is None
            for e in entries:
                e.output = e.tensor
            return Status.OK()

    rt = Runtime(cfg, LocalController(), OperationManager([SyncBackend()]))
    rt.start()
    done = {}
    try:
        _enqueue(rt, "x.0", done)
        assert done["x.0"].wait(10.0)
    finally:
        rt.request_shutdown()
        rt.join(10.0)


def test_timeline_negotiation_interleaves_with_slow_collective(tmp_path):
    """End-to-end overlap EVIDENCE: with async completion on, the
    timeline must show tensor 2's NEGOTIATE_ALLREDUCE beginning INSIDE
    tensor 1's COLLECTIVE span — i.e. cycle k+1's negotiation ran while
    cycle k's collective was still in flight, and the COLLECTIVE span
    closes at true completion (the CUDA-finalizer-driven Timeline end
    of the reference, cuda_operations.cc:148-179)."""

    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.stall_check_disable = True
    cfg.timeline_path = str(tmp_path / "overlap.json")
    backend = GatedAsyncBackend()
    rt = Runtime(cfg, LocalController(), OperationManager([backend]))
    rt.start()
    done = {}
    try:
        _enqueue(rt, "big.0", done)
        with backend.issued_cv:
            assert backend.issued_cv.wait_for(
                lambda: "big.0" in backend.issued, timeout=10.0)
        _enqueue(rt, "small.1", done)
        assert done["small.1"].wait(10.0)
        assert not done["big.0"].is_set()
        backend.gate.set()
        assert done["big.0"].wait(10.0)
    finally:
        backend.gate.set()
        rt.request_shutdown()
        rt.join(10.0)

    from tests.trace_utils import (
        collective_span, load_trace, negotiate_start_ts,
    )

    _, by_name = load_trace(cfg.timeline_path)
    coll_start, coll_end = collective_span(by_name["big.0"])
    neg_ts = negotiate_start_ts(by_name["small.1"])
    _, small_done = collective_span(by_name["small.1"])
    # small.1 negotiated AND completed strictly inside big.0's
    # COLLECTIVE span
    assert coll_start < neg_ts < coll_end, (coll_start, neg_ts, coll_end)
    assert coll_start < small_done < coll_end, (small_done, coll_end)
