"""World trace plane units (ISSUE 11, common/trace.py + the TAG_TRACE
codec in common/wire.py): frame roundtrip/truncation, the hierarchical
concat fold, NTP clock math + min-RTT smoothing, the flight-recorder
ring + postmortem dump, straggler attribution, and the merged catapult
writer's offset correction."""

import json
import os
import signal

import pytest

from horovod_tpu.common import trace as htrace
from horovod_tpu.common import wire
from tests.test_multiprocess import run_scenario


def _section(rank, spans, echo=None, dropped=0):
    return {"rank": rank, "dropped": dropped, "echo": echo,
            "spans": spans}


SPANS = [(wire.SPAN_SLICE, 17, 1.5, 0.25, "ROUND"),
         (wire.SPAN_SLICE, 17, 1.75, 0.1, "ALLREDUCE x3"),
         (wire.SPAN_MARK, 18, 2.0, 0.0, "ABORT")]


class TestTraceCodec:
    def test_roundtrip(self):
        blob = wire.serialize_trace_frame(
            [_section(2, SPANS, echo=(41, 10.5, 10.75), dropped=3),
             _section(3, [])])
        secs = wire.parse_trace_frame(blob)
        assert len(secs) == 2
        assert secs[0]["rank"] == 2 and secs[0]["dropped"] == 3
        assert secs[0]["echo"] == (41, 10.5, 10.75)
        assert secs[0]["spans"] == SPANS
        assert secs[1] == _section(3, [])

    def test_every_truncation_raises(self):
        """Every strict prefix must fail parse loudly (the _Reader
        length-guard contract every wire codec carries), never decode
        a silently-wrong frame."""
        blob = wire.serialize_trace_frame(
            [_section(1, SPANS, echo=(7, 1.0, 2.0))])
        for cut in range(len(blob)):
            with pytest.raises((ConnectionError, ValueError)):
                wire.parse_trace_frame(blob[:cut])

    def test_unknown_version_rejected(self):
        blob = wire.serialize_trace_frame([_section(0, [])])
        with pytest.raises(ValueError):
            wire.parse_trace_frame(b"\xff" + blob[1:])

    def test_combine_concatenates_sections(self):
        """The hierarchical fold CONCATENATES — spans are one-shot
        deltas; a latest-wins fold (the metrics semantics) would lose
        every earlier batch."""
        a = wire.serialize_trace_frame([_section(1, SPANS[:1])])
        b = wire.serialize_trace_frame([_section(2, SPANS[1:]),
                                        _section(3, [])])
        secs = wire.parse_trace_frame(wire.combine_trace_frames([a, b]))
        assert [s["rank"] for s in secs] == [1, 2, 3]
        assert secs[0]["spans"] == SPANS[:1]
        assert secs[1]["spans"] == SPANS[1:]

    def test_combine_drops_garbled_frame(self):
        good = wire.serialize_trace_frame([_section(1, SPANS)])
        secs = wire.parse_trace_frame(
            wire.combine_trace_frames([b"\x00garbage", good]))
        assert [s["rank"] for s in secs] == [1]

    def test_code_families_distinct(self):
        assert len(set(wire.SPAN_NAMES)) == len(wire.SPAN_NAMES)
        assert len(set(wire.EV_NAMES)) == len(wire.EV_NAMES)
        for v in list(wire.SPAN_NAMES) + list(wire.EV_NAMES):
            assert 0 <= v <= 255


class TestClockSync:
    def test_ntp_offset_recovered_exactly(self):
        """Symmetric delay, known offset: the four-stamp math must
        recover it exactly. Peer clock = coord clock + 2.5s; one-way
        delay 10ms each direction."""
        cs = htrace.ClockSync()
        off, delay = 2.5, 0.010
        t1 = 100.0
        cs.ping_sent(7, t1)
        t2 = t1 + delay + off          # peer clock at ping receipt
        t3 = t2 + 0.050                # peer processes for 50ms
        t4 = (t3 - off) + delay        # coord clock at echo arrival
        cs.echo(1, 7, t2, t3, t4)
        got_off, got_rtt = cs.offsets()[1]
        assert got_off == pytest.approx(off, abs=1e-9)
        assert got_rtt == pytest.approx(2 * delay, abs=1e-9)
        assert cs.offset_of(1) == pytest.approx(off, abs=1e-9)
        assert cs.offset_of(0) == 0.0  # the coordinator IS the frame

    def test_min_rtt_sample_wins(self):
        """A congested (asymmetric-queueing) sample inflates RTT and
        skews the offset — the estimator must prefer the cleanest
        round trip in the window."""
        cs = htrace.ClockSync()
        cs.ping_sent(1, 100.0)
        cs.echo(1, 1, 101.0, 101.0, 100.002)      # rtt 2ms, off ~1.0
        cs.ping_sent(2, 200.0)
        cs.echo(1, 2, 201.4, 201.4, 200.5)        # rtt 500ms, skewed
        off, rtt = cs.offsets()[1]
        assert rtt == pytest.approx(0.002, abs=1e-9)
        assert off == pytest.approx(0.999, abs=1e-3)

    def test_unknown_ping_and_negative_rtt_dropped(self):
        cs = htrace.ClockSync()
        cs.echo(1, 99, 1.0, 2.0, 3.0)  # never sent: forgotten
        assert cs.offsets() == {}
        cs.ping_sent(5, 100.0)
        cs.echo(1, 5, 200.0, 210.0, 100.1)  # rtt < 0: clocks moved
        assert cs.offsets() == {}

    def test_worker_echo_consumed_once_and_coord_only(self):
        cs = htrace.ClockSync()
        cs.ping_received(3, 10, 1.0)   # a local root's beacon: ignored
        assert cs.take_echo() is None
        cs.ping_received(0, 11, 2.0)
        seq, t2, t3 = cs.take_echo()
        assert (seq, t2) == (11, 2.0) and t3 > 0
        assert cs.take_echo() is None  # one ping answered once

    def test_offsets_line_formatting(self):
        htrace._reset_for_tests()
        try:
            cs = htrace.clock()
            cs.ping_sent(1, 0.0)
            cs.echo(2, 1, 0.101, 0.101, 0.002)
            line = htrace.clock_offsets_line()
            assert "rank 2" in line and "ms" in line
        finally:
            htrace._reset_for_tests()


class TestFlightRecorder:
    def test_ring_wraps_keeping_latest(self):
        rec = htrace.FlightRecorder(capacity=8)
        for i in range(20):
            rec.record(wire.EV_CYCLE, cycle=i)
        evs = rec.events()
        assert len(evs) == 8
        assert [e[2] for e in evs] == list(range(12, 20))  # chrono
        assert all(e[1] == wire.EV_CYCLE for e in evs)

    def test_dump_format(self, tmp_path):
        rec = htrace.FlightRecorder(capacity=16)
        rec.set_identity(3)
        rec.record(wire.EV_CYCLE, cycle=41)
        rec.record(wire.EV_ABORT, cycle=42, arg=1,
                   note="connection to rank 1 lost")
        path = str(tmp_path / "flight.jsonl")
        got = rec.dump(cause="test abort", origin=1, path=path)
        assert got == path
        lines = [json.loads(l) for l in open(path)]
        header, events = lines[0], lines[1:]
        assert header["flight"] == 1 and header["rank"] == 3
        assert header["origin"] == 1 and header["cause"] == "test abort"
        assert set(header["build"]) == {"version", "native", "knobs",
                                        "flags"}
        assert [e["ev"] for e in events] == ["cycle", "abort"]
        assert events[1]["arg"] == 1
        assert "rank 1" in events[1]["note"]
        # a second dump appends a fresh block
        rec.dump(cause="again", origin=-1, path=path)
        assert sum(1 for l in open(path)
                   if json.loads(l).get("flight")) == 2

    def test_dump_never_raises(self):
        rec = htrace.FlightRecorder(capacity=8)
        assert rec.dump(path="/nonexistent-dir/zz/flight.jsonl") is None

    def test_events_survive_lock_held_on_same_thread(self, tmp_path):
        # SIGUSR2 delivers on the main thread; if that thread is mid-
        # record() and holds the ring lock, the handler's dump() must
        # still complete (best-effort snapshot) instead of deadlocking.
        rec = htrace.FlightRecorder(capacity=8)
        rec.record(wire.EV_CYCLE, cycle=7)
        assert rec._lock.acquire(timeout=1.0)
        try:
            evs = rec.events()  # must return, not block forever
            assert [e[2] for e in evs] == [7]
            path = rec.dump(cause="SIGUSR2",
                            path=str(tmp_path / "f.jsonl"))
            assert path is not None
        finally:
            rec._lock.release()

    def test_disabled_env_hands_out_noop(self, monkeypatch):
        htrace._reset_for_tests()
        try:
            monkeypatch.setenv("HOROVOD_TPU_FLIGHT", "0")
            rec = htrace.flight()
            assert rec is htrace.NOOP_RECORDER
            assert not rec.enabled
            rec.record(wire.EV_CYCLE, 1)  # all no-ops
            assert rec.events() == []
            assert rec.dump(cause="x") is None
        finally:
            htrace._reset_for_tests()

    def test_default_on_singleton(self):
        htrace._reset_for_tests()
        try:
            assert os.environ.get("HOROVOD_TPU_FLIGHT", "1") != "0"
            rec = htrace.flight()
            assert isinstance(rec, htrace.FlightRecorder)
            assert htrace.flight() is rec
        finally:
            htrace._reset_for_tests()


class TestDisabledRuntimeSites:
    def test_noop_write_sites_enumerable(self, monkeypatch):
        """HOROVOD_TPU_FLIGHT=0 + no trace path: every instrumented
        site must hold the shared no-op objects (the NOOP_METRIC
        contract — the disabled paths stay provably free)."""
        import horovod_tpu as hvd
        from horovod_tpu.common import basics as _b
        htrace._reset_for_tests()
        monkeypatch.setenv("HOROVOD_TPU_FLIGHT", "0")
        monkeypatch.delenv("HOROVOD_TPU_TRACE", raising=False)
        hvd.shutdown()
        hvd.init()
        try:
            rt = _b.runtime()
            assert rt._flight is htrace.NOOP_RECORDER
            assert rt._trace is htrace.NOOP_TRACE
            assert not rt._trace_on
            assert rt._trace_writer is None
            assert rt._straggler is None  # metrics off too
            ctl = rt.controller
            assert not ctl._trace_on and ctl._on_arrivals is None
            assert ctl.trace_sink is None
        finally:
            hvd.shutdown()
            htrace._reset_for_tests()


class TestHierTracePublish:
    """A hierarchical local root must not park child TRACE frames for
    its own publish interval: every parked second inflates the echo's
    t4 and biases the leaf's clock offset (systematically — same-period
    publish timers hold a constant phase, so min-RTT can't filter it)."""

    def _runtime_stub(self, child_trace, interval=60.0):
        import time
        from types import SimpleNamespace
        sent = []
        controller = SimpleNamespace(
            rank=1, _child_trace=child_trace,
            send_trace=lambda p: sent.append(p))
        collector = SimpleNamespace(drain=lambda: ([], 0))
        rt = SimpleNamespace(
            config=SimpleNamespace(trace_interval_s=interval),
            controller=controller, _trace=collector,
            _trace_writer=None, _trace_spans_sent=0,
            _trace_last_pub=time.monotonic())  # interval NOT elapsed
        return rt, sent

    def test_pending_child_frames_bypass_interval(self):
        from horovod_tpu.common.runtime import Runtime
        rt, sent = self._runtime_stub(child_trace=[b"leaf-frame"])
        Runtime._maybe_publish_trace(rt)
        assert len(sent) == 1  # forwarded now, not a minute from now
        secs = wire.parse_trace_frame(sent[0])
        assert len(secs) == 1 and secs[0]["rank"] == 1
        assert secs[0]["spans"] == [] and secs[0]["dropped"] == 0

    def test_idle_rank_still_waits_out_interval(self):
        from horovod_tpu.common.runtime import Runtime
        rt, sent = self._runtime_stub(child_trace=[])
        Runtime._maybe_publish_trace(rt)
        assert sent == []  # nothing to say, nothing parked: no frame


class TestStragglerTracker:
    def test_last_arriver_and_skew(self):
        from horovod_tpu.common import metrics as hm
        reg = hm.MetricsRegistry()
        tr = htrace.StragglerTracker(reg)
        for _ in range(9):
            tr.note_gather({0: 10.0, 1: 10.001, 2: 10.050, 3: 10.002})
        tr.note_gather({0: 20.0, 1: 20.2, 2: 20.01, 3: 20.0})
        line = tr.report_line()
        assert "rank 2 last-arriver in 90% of the last 10" in line
        snap = reg.snapshot()
        assert snap['hvd_last_arriver_total{peer="2"}']["v"] == 9.0
        assert snap['hvd_last_arriver_total{peer="1"}']["v"] == 1.0
        assert snap['hvd_arrival_lag_seconds{peer="2"}']["v"] == \
            pytest.approx(0.050)
        assert snap['hvd_arrival_lag_seconds{peer="2"}']["agg"] == "max"
        h = snap["hvd_cycle_skew_seconds"]
        assert h["count"] == 10
        assert h["sum"] == pytest.approx(9 * 0.050 + 0.2)

    def test_window_slides(self):
        tr = htrace.StragglerTracker()
        tr.WINDOW  # class constant stays 1000
        for i in range(htrace.StragglerTracker.WINDOW + 50):
            tr.note_gather({0: 1.0, 1: 2.0})  # rank 1 always last
        line = tr.report_line()
        assert "rank 1 last-arriver in 100% of the last 1000" in line

    def test_empty_before_any_gather(self):
        assert htrace.StragglerTracker().report_line() == ""


class TestWorldTraceWriter:
    def _write(self, tmp_path, sections, clock=None):
        path = str(tmp_path / "world.json")
        w = htrace.WorldTraceWriter(path, clock_sync=clock
                                    or htrace.ClockSync())
        for rank, spans, dropped in sections:
            w.add_section(rank, spans, dropped)
        w.close()
        with open(path) as f:
            return json.load(f)  # must be VALID JSON after close

    def test_tracks_and_cycle_args(self, tmp_path):
        events = self._write(tmp_path, [
            (0, [(wire.SPAN_SLICE, 5, 1.0, 0.5, "ROUND")], 0),
            (2, [(wire.SPAN_SLICE, 5, 1.1, 0.4, "ROUND"),
                 (wire.SPAN_MARK, 6, 1.9, 0.0, "ABORT")], 1),
        ])
        pids = {e["pid"] for e in events}
        assert pids == {0, 2}
        names = {e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names == {"rank 0", "rank 2"}
        rounds = [e for e in events if e.get("name") == "ROUND"]
        assert all(e["ph"] == "X" and e["args"]["wc"] == 5
                   for e in rounds)
        marks = [e for e in events if e.get("name") == "ABORT"]
        assert marks and marks[0]["ph"] == "i"
        drops = [e for e in events
                 if str(e.get("name", "")).startswith("TRACE_DROPPED")]
        assert drops and drops[0]["pid"] == 2

    def test_offset_correction_aligns_tracks(self, tmp_path):
        """Rank 1's clock sits +2.0s from the coordinator; after
        correction its span must land at the same coordinator time as
        rank 0's concurrent span."""
        cs = htrace.ClockSync()
        cs.ping_sent(1, 50.0)
        cs.echo(1, 1, 52.0, 52.0, 50.0)  # offset exactly +2.0, rtt 0
        path = str(tmp_path / "world.json")
        w = htrace.WorldTraceWriter(path, clock_sync=cs)
        w.add_section(0, [(wire.SPAN_SLICE, 9, 100.0, 0.5, "ROUND")])
        w.add_section(1, [(wire.SPAN_SLICE, 9, 102.0, 0.5, "ROUND")])
        w.close()
        events = json.load(open(path))
        ts = {e["pid"]: e["ts"] for e in events
              if e.get("name") == "ROUND"}
        assert ts[0] == ts[1]

    def test_tracks_clamped_monotonic(self, tmp_path):
        """A drifting offset estimate between batches must never make
        a rank's own track run backwards."""
        cs = htrace.ClockSync()
        path = str(tmp_path / "world.json")
        w = htrace.WorldTraceWriter(path, clock_sync=cs)
        w.add_section(1, [(wire.SPAN_SLICE, 1, 10.0, 0.5, "ROUND")])
        # offset estimate jumps to +5s: raw correction would throw
        # the next span far BEFORE the previous one
        cs.ping_sent(1, 0.0)
        cs.echo(1, 1, 5.0, 5.0, 0.0)
        w.add_section(1, [(wire.SPAN_SLICE, 2, 10.6, 0.5, "ROUND")])
        w.close()
        events = [e for e in json.load(open(path))
                  if e.get("name") == "ROUND"]
        assert events[1]["ts"] >= events[0]["ts"]

    def test_ingest_closes_clock_loop(self, tmp_path):
        cs = htrace.ClockSync()
        cs.ping_sent(3, 0.0)
        path = str(tmp_path / "world.json")
        w = htrace.WorldTraceWriter(path, clock_sync=cs)
        payload = wire.serialize_trace_frame([
            _section(2, [(wire.SPAN_SLICE, 1, 1.0, 0.1, "ROUND")],
                     echo=(3, 0.5, 0.6))])
        w.ingest(2, payload)
        w.ingest(2, b"garbled")  # dropped, never raises
        w.close()
        assert 2 in cs.offsets()
        events = json.load(open(path))
        assert any(e.get("name") == "ROUND" and e["pid"] == 2
                   for e in events)


class TestBuildInfo:
    def test_triplet_shape(self):
        bi = htrace.build_info()
        from horovod_tpu import __version__
        assert bi["version"] == __version__
        assert bi["native"] and bi["knobs"]
        assert len(bi["knobs"]) == 12

    def test_knobs_digest_tracks_env(self, monkeypatch):
        a = htrace.knobs_digest()
        monkeypatch.setenv("HOROVOD_SOME_TEST_KNOB", "1")
        b = htrace.knobs_digest()
        assert a != b
        monkeypatch.delenv("HOROVOD_SOME_TEST_KNOB")
        assert htrace.knobs_digest() == a


# -- multi-process e2e (scenario bodies in tests/mp_scenarios.py) -----

# Short publish/beacon intervals so 60 gathers see many TRACE frames
# and clock-sync loops; speculation off keeps every recv on the Python
# paths where PING echoes close the NTP exchange.
_TRACE_MP_ENV = {
    "HOROVOD_TPU_METRICS": "1",
    "HOROVOD_TPU_METRICS_INTERVAL": "0.2",
    "HOROVOD_TPU_TRACE_INTERVAL": "0.2",
    "HOROVOD_HEARTBEAT_INTERVAL": "0.2",
    "HOROVOD_HEARTBEAT_TIMEOUT": "60",
    "HOROVOD_CACHE_SPECULATIVE": "0",
}


def test_trace_world_merged_catapult_and_straggler(tmp_path):
    """The ISSUE 11 e2e: ws=4 with a repeating 250ms ``delay`` fault
    on rank 2. The scenario asserts the straggler attribution NAMES
    rank 2 (arrival-lag dominance + last-arriver counter + skew
    histogram) and that the piggybacked clock sync closed; this
    wrapper validates the merged catapult artifact rank 0 wrote."""
    path = str(tmp_path / "world_trace.json")
    run_scenario(
        "trace_world", 4, timeout=180.0,
        extra_env={**_TRACE_MP_ENV,
                   "HOROVOD_TPU_TRACE": path,
                   "HOROVOD_FAULT_SPEC":
                       "rank=2:delay:cycle=8:ms=250:count=40"})
    events = json.load(open(path))  # ONE valid-JSON merged file
    spans = [e for e in events if e.get("ph") in ("X", "i")]
    assert {e["pid"] for e in spans} == {0, 1, 2, 3}  # track per rank
    names = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {f"rank {r}" for r in range(4)}
    for rank in range(4):
        track = [e["ts"] for e in spans if e["pid"] == rank]
        assert track, f"rank {rank} track empty"
        # offset-corrected timestamps stay monotonic per track
        assert track == sorted(track), f"rank {rank} runs backwards"
        # ...and carry the world cycle number, itself monotone
        wcs = [e["args"]["wc"] for e in spans if e["pid"] == rank
               if "wc" in e.get("args", {})]
        assert wcs and wcs == sorted(wcs)


def test_trace_arrival_stamps_cover_native_steady():
    """Skew/last-arriver attribution must not go dark when the steady
    loop collapses into one-call native cycles (hvd_steady_coord):
    the scenario asserts the skew histogram advances at least once
    per native cycle and exactly one last-arriver is charged per
    stamped gather."""
    run_scenario(
        "trace_native_arrivals", 4, timeout=120.0,
        extra_env={"HOROVOD_TPU_METRICS": "1",
                   "HOROVOD_TPU_SHM": "0"})


def test_flight_dump_on_sigkill_world(tmp_path):
    """SIGKILL rank 2 mid-steady-cycle with NO profiling armed: every
    survivor raises WorldAbortedError naming rank 2 (the PR 2
    invariant) and leaves a flight-recorder postmortem in
    HOROVOD_TPU_FLIGHT_DIR naming the dead rank and holding the final
    cycles (asserted rank-side in the scenario)."""
    run_scenario(
        "flight_sigkill", 4, timeout=90.0,
        extra_env={"HOROVOD_HEARTBEAT_INTERVAL": "0.3",
                   "HOROVOD_HEARTBEAT_TIMEOUT": "3",
                   "HOROVOD_FAULT_SPEC": "rank=2:kill:op=25",
                   "HOROVOD_TPU_FLIGHT_DIR": str(tmp_path)},
        expect_rc={2: -signal.SIGKILL})
    dumps = sorted(tmp_path.glob("hvd-flight-rank*.jsonl"))
    headers = [json.loads(p.open().readline()) for p in dumps]
    assert {h["rank"] for h in headers} == {0, 1, 3}, dumps
    assert all(h["origin"] == 2 for h in headers)
