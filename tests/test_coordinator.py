"""Coordinator negotiation logic tests (reference analog: the 2-rank
mismatch tests in test/test_tensorflow.py:265-332 run end-to-end; here
we additionally unit-test the decision core the way the TPU build can,
since it is pure logic — reference: operations.cc:163-399,1118-1234)."""

import pytest

from horovod_tpu.common.coordinator import (
    MessageTable, construct_response, fuse_responses,
)
from horovod_tpu.common.message import (
    DataType, Request, RequestType, Response, ResponseType,
)


def _req(rank, name="t", op=RequestType.ALLREDUCE,
         dtype=DataType.FLOAT32, shape=(4, 2), root=-1, device=-1):
    return Request(request_rank=rank, request_type=op, tensor_type=dtype,
                   tensor_name=name, root_rank=root, device=device,
                   tensor_shape=shape)


class TestMessageTable:
    def test_ready_when_all_ranks_report(self):
        t = MessageTable()
        assert not t.increment_tensor_count(_req(0), size=3)
        assert not t.increment_tensor_count(_req(1), size=3)
        assert t.increment_tensor_count(_req(2), size=3)
        assert t.pop_ready() == ["t"]
        assert t.pop_ready() == []

    def test_readiness_order_is_fifo(self):
        t = MessageTable()
        for r in range(2):
            t.increment_tensor_count(_req(r, "b"), 2)
            t.increment_tensor_count(_req(r, "a"), 2)
        assert t.pop_ready() == ["b", "a"]

    def test_remove_fires_completion_hook(self):
        removed = []
        t = MessageTable(on_remove=removed.append)
        for r in range(2):
            t.increment_tensor_count(_req(r, "g"), 2)
        t.pop_ready()
        t.remove("g")
        assert removed == ["g"]


class TestStallWarningPruning:
    def test_recurring_tensor_warns_again_after_completion(self):
        """A tensor that stalls, completes, then stalls AGAIN must warn
        again — _warned is pruned on MessageTable.remove(), not kept
        for the process lifetime."""
        from horovod_tpu.common.coordinator import StallInspector

        insp = StallInspector(size=2, warning_time=0.0)
        table = MessageTable(on_remove=insp.tensor_completed)

        def stall_and_check():
            table.increment_tensor_count(_req(0, "grad"), 2)
            insp.check(table)  # warns: rank 1 never reported
            return "grad" in insp._warned

        assert stall_and_check()
        # second check while still stalled: no duplicate warning state
        insp.check(table)
        assert "grad" in insp._warned
        # rank 1 finally reports; negotiation completes
        table.increment_tensor_count(_req(1, "grad"), 2)
        table.pop_ready()
        table.remove("grad")
        assert "grad" not in insp._warned
        # the SAME name stalls later in the process lifetime
        assert stall_and_check()


class TestConstructResponse:
    def _negotiate(self, requests, size):
        t = MessageTable()
        for r in requests:
            t.increment_tensor_count(r, size)
        return construct_response(t, requests[0].tensor_name, size)

    def test_allreduce_ok(self):
        resp = self._negotiate([_req(0), _req(1)], 2)
        assert resp.response_type == ResponseType.ALLREDUCE
        assert resp.tensor_names == ["t"]
        assert resp.tensor_sizes == [8]

    def test_mismatched_dtype_is_error(self):
        resp = self._negotiate(
            [_req(0, dtype=DataType.FLOAT32),
             _req(1, dtype=DataType.FLOAT64)], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "data type" in resp.error_message.lower()

    def test_mismatched_op_is_error(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.ALLREDUCE),
             _req(1, op=RequestType.ALLGATHER, shape=(3, 2))], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "operation" in resp.error_message.lower()

    def test_mismatched_allreduce_shape_is_error(self):
        resp = self._negotiate([_req(0, shape=(4, 2)),
                                _req(1, shape=(4, 3))], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "shape" in resp.error_message.lower()

    def test_mixed_placement_is_error(self):
        resp = self._negotiate([_req(0, device=-1), _req(1, device=0)], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "placement" in resp.error_message.lower()

    def test_allgather_variable_dim0_ok(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.ALLGATHER, shape=(5, 3)),
             _req(1, op=RequestType.ALLGATHER, shape=(2, 3))], 2)
        assert resp.response_type == ResponseType.ALLGATHER
        assert resp.tensor_sizes == [5, 2]

    def test_allgather_mismatched_higher_dim_is_error(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.ALLGATHER, shape=(5, 3)),
             _req(1, op=RequestType.ALLGATHER, shape=(2, 4))], 2)
        assert resp.response_type == ResponseType.ERROR

    def test_allgather_mismatched_rank_is_error(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.ALLGATHER, shape=(5, 3)),
             _req(1, op=RequestType.ALLGATHER, shape=(5, 3, 1))], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "rank" in resp.error_message.lower()

    def test_broadcast_mismatched_root_is_error(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.BROADCAST, root=0),
             _req(1, op=RequestType.BROADCAST, root=1)], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "root rank" in resp.error_message.lower()

    def test_broadcast_ok(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.BROADCAST, root=1),
             _req(1, op=RequestType.BROADCAST, root=1)], 2)
        assert resp.response_type == ResponseType.BROADCAST

    def test_alltoall_indivisible_dim0_is_error(self):
        resp = self._negotiate(
            [_req(0, op=RequestType.ALLTOALL, shape=(5, 3)),
             _req(1, op=RequestType.ALLTOALL, shape=(5, 3))], 2)
        assert resp.response_type == ResponseType.ERROR
        assert "divisible" in resp.error_message


class TestFusion:
    def _ar(self, name, numel):
        return Response(response_type=ResponseType.ALLREDUCE,
                        tensor_names=[name], devices=[-1, -1],
                        tensor_sizes=[numel])

    def test_fuses_under_threshold(self):
        dtypes = {"a": DataType.FLOAT32, "b": DataType.FLOAT32}
        fused = fuse_responses([self._ar("a", 10), self._ar("b", 10)],
                               dtypes, fusion_threshold_bytes=1024)
        assert len(fused) == 1
        assert fused[0].tensor_names == ["a", "b"]
        assert fused[0].tensor_sizes == [10, 10]

    def test_does_not_fuse_over_threshold(self):
        dtypes = {"a": DataType.FLOAT32, "b": DataType.FLOAT32}
        fused = fuse_responses([self._ar("a", 10), self._ar("b", 10)],
                               dtypes, fusion_threshold_bytes=60)
        assert len(fused) == 2

    def test_does_not_fuse_mixed_dtypes(self):
        dtypes = {"a": DataType.FLOAT32, "b": DataType.FLOAT64}
        fused = fuse_responses([self._ar("a", 10), self._ar("b", 10)],
                               dtypes, fusion_threshold_bytes=1 << 20)
        assert len(fused) == 2

    def test_lookahead_skip(self):
        # a(40B) + c(40B) fuse past the incompatible b (f64), which is
        # retried afterwards (reference: operations.cc:1118-1234).
        dtypes = {"a": DataType.FLOAT32, "b": DataType.FLOAT64,
                  "c": DataType.FLOAT32}
        fused = fuse_responses(
            [self._ar("a", 10), self._ar("b", 10), self._ar("c", 10)],
            dtypes, fusion_threshold_bytes=100)
        assert [f.tensor_names for f in fused] == [["a", "c"], ["b"]]

    def _ag(self, name, rows):
        return Response(response_type=ResponseType.ALLGATHER,
                        tensor_names=[name], devices=[-1, -1],
                        tensor_sizes=list(rows))

    def test_allgather_does_not_fuse_into_allreduce(self):
        dtypes = {"a": DataType.FLOAT32, "g": DataType.FLOAT32,
                  "b": DataType.FLOAT32}
        fused = fuse_responses(
            [self._ar("a", 10), self._ag("g", [3, 4]), self._ar("b", 10)],
            dtypes, fusion_threshold_bytes=1 << 20,
            slice_numels={"g": 1})
        assert [f.tensor_names for f in fused] == [["a", "b"], ["g"]]

    def test_allgather_fusion(self):
        """ALLGATHER responses fuse like allreduce, with entry-major
        tensor_sizes and dim0-sum × slice-numel byte accounting
        (reference: operations.cc:1172-1234)."""
        dtypes = {"g1": DataType.FLOAT32, "g2": DataType.FLOAT32}
        fused = fuse_responses(
            [self._ag("g1", [3, 4]), self._ag("g2", [2, 5])],
            dtypes, fusion_threshold_bytes=1 << 20,
            slice_numels={"g1": 8, "g2": 8})
        assert len(fused) == 1
        assert fused[0].tensor_names == ["g1", "g2"]
        # entry-major: g1's per-rank rows then g2's
        assert fused[0].tensor_sizes == [3, 4, 2, 5]

    def test_allgather_fusion_respects_output_bytes(self):
        # g1 output: (3+4) rows × 8 el × 4 B = 224 B; g2: 224 B.
        # Threshold 300 B admits one but not both.
        dtypes = {"g1": DataType.FLOAT32, "g2": DataType.FLOAT32}
        fused = fuse_responses(
            [self._ag("g1", [3, 4]), self._ag("g2", [3, 4])],
            dtypes, fusion_threshold_bytes=300,
            slice_numels={"g1": 8, "g2": 8})
        assert len(fused) == 2

    def test_allgather_fusion_mixed_dtype_splits(self):
        dtypes = {"g1": DataType.FLOAT32, "g2": DataType.FLOAT64}
        fused = fuse_responses(
            [self._ag("g1", [1, 1]), self._ag("g2", [1, 1])],
            dtypes, fusion_threshold_bytes=1 << 20,
            slice_numels={"g1": 4, "g2": 4})
        assert len(fused) == 2

    def test_error_responses_pass_through(self):
        err = Response(response_type=ResponseType.ERROR,
                       tensor_names=["x"], error_message="boom")
        fused = fuse_responses([err], {}, 1 << 20)
        assert fused == [err]


class TestCycleCost:
    """Coordinator cycle-cost regression guards: the 64-rank
    many-tensor storm the scaling projection depends on must stay
    cheap (docs/benchmarks.md budgets ~1 ms/cycle at 64 ranks; bounds
    here are several-x that so scheduler noise on a shared vCPU can't
    flake them, while a complexity regression — e.g. the list.pop(0)
    scan fuse_responses used to do — still trips them by an order of
    magnitude)."""

    def test_fuse_responses_scales_linearly(self):
        """20k pass-through responses (each over threshold) must fuse
        in far less than the seconds the quadratic pop(0) version
        took — the deque walk does ~20k O(1) steps."""
        import time as _t
        n = 20_000
        dtypes = {f"t{i}": DataType.FLOAT32 for i in range(n)}
        responses = [
            Response(response_type=ResponseType.ALLREDUCE,
                     tensor_names=[f"t{i}"], devices=[-1, -1],
                     tensor_sizes=[1024])
            for i in range(n)]
        t0 = _t.perf_counter()
        fused = fuse_responses(responses, dtypes,
                               fusion_threshold_bytes=64)
        elapsed = _t.perf_counter() - t0
        assert len(fused) == n
        assert elapsed < 0.5, f"fuse_responses took {elapsed:.2f}s " \
            f"for {n} pass-through responses - complexity regression"

    def test_coordinator_cycle_cost_64_ranks(self):
        """Full coordinator half-cycle (parse 64 RequestLists, count
        readiness, construct + fuse + serialize) at 64 simulated ranks
        x 8 allreduces PLUS 4 variable-dim0 allgathers — the fused-
        allgather fusion branch (dim0-sum x slice-numel byte
        accounting) rides the same budget. Min-of-7 bounds the
        intrinsic cost free of scheduler noise."""
        import time as _t

        from horovod_tpu.common import wire
        from horovod_tpu.common.message import RequestList, ResponseList

        n_ranks, tensors, gathers = 64, 8, 4
        payloads = [
            wire.serialize_request_list(RequestList(
                [_req(r, name=f"grad.{t}", shape=(1024,))
                 for t in range(tensors)]
                + [_req(r, name=f"gath.{t}",
                        op=RequestType.ALLGATHER,
                        shape=(r % 3 + 1, 16))
                   for t in range(gathers)]))
            for r in range(n_ranks)]
        best = float("inf")
        for _ in range(7):
            t0 = _t.perf_counter()
            table = MessageTable()
            dtypes, slices = {}, {}
            for data in payloads:
                rl = wire.parse_request_list(data)
                for req in rl.requests:
                    dtypes[req.tensor_name] = req.tensor_type
                    numel = 1
                    for d in req.tensor_shape[1:]:
                        numel *= d
                    slices[req.tensor_name] = numel
                    table.increment_tensor_count(req, n_ranks)
            responses = [construct_response(table, name, n_ranks)
                         for name in table.pop_ready()]
            fused = fuse_responses(responses, dtypes, 64 << 20, slices)
            wire.serialize_response_list(ResponseList(fused))
            best = min(best, _t.perf_counter() - t0)
        # all 8 grads fuse into one batch, all 4 gathers into another
        assert len(fused) == 2
        by_type = {f.response_type: f for f in fused}
        ag = by_type[ResponseType.ALLGATHER]
        assert ag.tensor_names == [f"gath.{t}" for t in range(gathers)]
        # entry-major sizes: each entry carries all 64 ranks' dim-0 rows
        assert len(ag.tensor_sizes) == gathers * n_ranks
        # The seed budgeted 5 ms for 8 requests/rank; the allgather
        # branch adds 4 more — scale the budget with the workload so
        # the guard keeps the same per-request bar.
        budget_s = 5e-3 * (tensors + gathers) / tensors
        assert best < budget_s, (
            f"coordinator cycle took {best * 1e3:.2f} ms at "
            f"{n_ranks} ranks (budget {budget_s * 1e3:.0f} ms) - "
            f"per-cycle cost regression")
