"""Every example must actually run (reference model: the examples tree
is part of the tested surface — .travis.yml runs the example scripts'
frameworks' test files; here we execute each example end-to-end with
tiny shapes so a user's first contact with the repo can't be broken).

Each example runs in its own subprocess: examples own their world
(hvd.init/shutdown) and some need a virtual multi-device CPU platform,
which must be configured before jax imports."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(REPO, "examples")


def _run(script, *args, n_devices=1, timeout=420, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["HOROVOD_CYCLE_TIME"] = "1"
    if extra_env:
        env.update(extra_env)
    # Keep the TPU plugin's sitecustomize from overriding jax_platforms
    # back to the tunneled TPU (same hygiene as test_multiprocess).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    # Scrub any inherited device-count flag, then pin ours.
    flags = " ".join(f for f in flags.split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(EX, script), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO)
    assert proc.returncode == 0, (
        f"{script} failed\n--- stdout ---\n{proc.stdout[-3000:]}\n"
        f"--- stderr ---\n{proc.stderr[-3000:]}")
    return proc.stdout


def test_jax_mnist():
    out = _run("jax_mnist.py", "--epochs", "1", "--batch-size", "256")
    assert "loss" in out.lower()


def test_torch_mnist():
    out = _run("torch_mnist.py", "--epochs", "1", "--batch-size", "256")
    assert "loss" in out.lower()


def test_tensorflow_mnist():
    out = _run("tensorflow_mnist.py", "--epochs", "1",
               "--batch-size", "256")
    assert "loss" in out.lower()


def test_keras_mnist():
    out = _run("keras_mnist.py")
    assert "val" in out.lower() or "loss" in out.lower()


@pytest.mark.slow
def test_jax_synthetic_benchmark():
    out = _run("jax_synthetic_benchmark.py", "--batch-size", "2",
               "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
               "--num-iters", "1")
    assert "img/sec" in out.lower()


@pytest.mark.slow
def test_transformer_long_context():
    """Newly green with the jaxshim port; 25s of 8-device CPU-mesh
    compile makes it a wall-clock outlier — the ring-attention paths
    it drives stay tier-1 via test_parallel."""
    out = _run("transformer_long_context.py", "--seq-len", "256",
               "--batch-size", "2", "--layers", "2", "--heads", "2",
               "--head-dim", "16", "--steps", "2", n_devices=8)
    assert "mesh" in out.lower()


@pytest.mark.slow
def test_moe_pipeline_parallel():
    """Newly green with the jaxshim port; ~29s of 8-device CPU-mesh
    compile — the dp x pp x ep Trainer paths stay tier-1 via
    test_parallel's pipelined-LM and expert-sharding tests."""
    out = _run("moe_pipeline_parallel.py", n_devices=8)
    assert "loss" in out.lower() or "moe" in out.lower()


def test_zero_fsdp():
    out = _run("zero_fsdp.py", n_devices=8)
    assert "ZeRO-1" in out and "FSDP" in out


def test_torch_imagenet_resnet50(tmp_path):
    """ImageNet-scale torch example (fp16 allreduce + gradient
    accumulation + warmup + checkpoint/resume), smoke-sized."""
    ckpt = str(tmp_path / "checkpoint-{epoch}.pth.tar")
    out = _run("torch_imagenet_resnet50.py", "--epochs", "1",
               "--steps-per-epoch", "2", "--batch-size", "2",
               "--batches-per-allreduce", "2", "--image-size", "32",
               "--num-classes", "10", "--width", "8",
               "--fp16-allreduce", "--checkpoint-format", ckpt)
    assert "loss" in out.lower()
    assert os.path.exists(ckpt.format(epoch=1))
    # resume path: epoch 1 checkpoint found -> trains epoch 2 only
    out = _run("torch_imagenet_resnet50.py", "--epochs", "2",
               "--steps-per-epoch", "2", "--batch-size", "2",
               "--image-size", "32", "--num-classes", "10",
               "--width", "8", "--checkpoint-format", ckpt)
    assert "epoch 2/2" in out and "epoch 1/2" not in out


@pytest.mark.slow
def test_keras_imagenet_resnet50(tmp_path):
    """ImageNet-scale keras example: warmup + staged-decay callbacks,
    metric averaging, fusion-threshold sweep knob."""
    out = _run("keras_imagenet_resnet50.py", "--epochs", "1",
               "--steps-per-epoch", "2", "--batch-size", "2",
               "--image-size", "32", "--num-classes", "10",
               "--fusion-threshold", str(1 << 20), "--fp16-allreduce",
               "--checkpoint-dir", str(tmp_path), timeout=600)
    assert "loss" in out.lower()


def test_keras_mnist_advanced():
    """Warmup + LR schedule + MetricAverage composed in one fit."""
    out = _run("keras_mnist_advanced.py", "--epochs", "3",
               "--warmup-epochs", "1", "--batch-size", "128")
    assert "lr trajectory" in out and "val_loss" in out


@pytest.mark.slow
def test_keras_spark_training():
    """End-to-end Spark workflow in fake-pyspark demo mode: driver
    dataset -> spark.run training -> driver-side scoring."""
    out = _run("keras_spark_training.py", "--num-proc", "2",
               timeout=600, extra_env={"HVD_FAKE_PYSPARK": "1"})
    assert "holdout RMSE" in out


def test_torch_synthetic_benchmark():
    out = _run("torch_synthetic_benchmark.py", "--model",
               "resnet50tiny", "--batch-size", "4",
               "--num-warmup-batches", "1", "--num-batches-per-iter",
               "1", "--num-iters", "2")
    assert "Img/sec per process" in out and "Total img/sec" in out


def test_tensorflow_mnist_eager():
    out = _run("tensorflow_mnist_eager.py", "--steps", "40")
    first, last = out.split("loss ")[-1].split(" over ")[0].split(" -> ")
    assert float(last) < float(first)  # it actually learns


def test_mxnet_mnist():
    out = _run("mxnet_mnist.py", "--steps", "40",
               extra_env={"HVD_FAKE_MXNET": "1"})
    assert "loss" in out and "->" in out


def test_tensorflow_word2vec():
    out = _run("tensorflow_word2vec.py", "--steps", "60")
    assert "IndexedSlices" in out
    first, last = out.split("loss ")[1].split(" over ")[0].split(" -> ")
    assert float(last) < float(first)  # it actually learns


@pytest.mark.parametrize("script", sorted(
    f for f in os.listdir(EX) if f.endswith(".py")))
def test_every_example_is_covered(script):
    """A new example without a smoke test above fails this guard."""
    covered = {
        "jax_mnist.py", "torch_mnist.py", "tensorflow_mnist.py",
        "keras_mnist.py", "jax_synthetic_benchmark.py",
        "transformer_long_context.py", "moe_pipeline_parallel.py",
        "zero_fsdp.py", "tensorflow_word2vec.py",
        "torch_imagenet_resnet50.py", "keras_imagenet_resnet50.py",
        "keras_mnist_advanced.py", "keras_spark_training.py",
        "torch_synthetic_benchmark.py", "tensorflow_mnist_eager.py",
        "mxnet_mnist.py",
    }
    assert script in covered, f"add a smoke test for examples/{script}"
