"""Unit tests for the multi-tenant collective service
(horovod_tpu/common/tenancy.py, docs/multitenancy.md): identity
derivation, the world-id wire envelope, the TENANT_* service codecs,
the QoS scheduler, per-tenant metric labels, and the in-process
single-member tenant path. Multi-process tenant scenarios live in
test_multiprocess.py / mp_scenarios.py."""

import numpy as np
import pytest

from horovod_tpu.common import tenancy, wire
from horovod_tpu.common.message import (
    CacheCycleRequest, DataType,
)


# -- identity derivation (the sub-world port-collision bugfix) --------------

def test_world_id_nonzero_and_deterministic():
    a = tenancy.derive_world_id("jobA", [0, 1, 2, 3])
    assert a == tenancy.derive_world_id("jobA", [0, 1, 2, 3])
    assert 1 <= a <= 0xFFFFFFFF
    assert a != tenancy.derive_world_id("jobB", [0, 1, 2, 3])
    assert a != tenancy.derive_world_id("jobA", [0, 1])


def test_subworld_ports_distinct_per_name_and_membership():
    """The pre-tenancy derivation keyed on ranks[0] alone: two
    subsets sharing a first rank collided, and a rank-0-anchored
    subset landed on the base port itself (the fleet coordinator's).
    The membership+name derivation must separate all of these."""
    base = 20000
    ports = {
        ("", (0, 1)): tenancy.derive_subworld_port(base, "", [0, 1]),
        ("", (0, 1, 2)): tenancy.derive_subworld_port(base, "",
                                                      [0, 1, 2]),
        ("", (1, 2)): tenancy.derive_subworld_port(base, "", [1, 2]),
        ("a", (0, 1)): tenancy.derive_subworld_port(base, "a", [0, 1]),
        ("b", (0, 1)): tenancy.derive_subworld_port(base, "b", [0, 1]),
    }
    assert len(set(ports.values())) == len(ports), ports
    # never the fleet's own endpoint, even anchored at rank 0
    assert all(p != base for p in ports.values())
    # deterministic: every member derives the same port
    assert ports[("a", (0, 1))] == tenancy.derive_subworld_port(
        base, "a", [0, 1])


def test_init_subworld_never_squats_the_env_port():
    """basics.init(comm=[0, ...]) on a larger launched world must
    derive away from the env port (the full world's coordinator may
    be alive on it in service mode); the FULL membership keeps it."""
    from horovod_tpu.common.basics import _is_full_world
    assert _is_full_world([0, 1, 2], 3)
    assert not _is_full_world([0, 1], 3)
    assert not _is_full_world([1, 2], 3)
    assert not _is_full_world([0, 2, 1], 3)  # list order is identity


# -- world-id envelope ------------------------------------------------------

def test_stamp_unstamp_roundtrip():
    frame = b"\x01some-cycle-frame"
    assert wire.stamp_world(frame, 0) is frame
    stamped = wire.stamp_world(frame, 0xDEADBEEF)
    assert stamped[:1] == wire.TENANT_PREFIX
    assert wire.unstamp_world(stamped, 0xDEADBEEF) == frame
    # unstamped frames pass through a 0-world check
    assert wire.unstamp_world(frame, 0) == frame


def test_unstamp_mismatch_names_both_worlds():
    stamped = wire.stamp_world(b"\x01x", 17)
    with pytest.raises(ConnectionError) as ei:
        wire.unstamp_world(stamped, 23)
    msg = str(ei.value)
    assert "0x00000011" in msg and "0x00000017" in msg
    # a stamped frame reaching a default world also fails fast
    with pytest.raises(ConnectionError):
        wire.unstamp_world(stamped, 0)
    # an unstamped frame reaching a tenant world fails fast too
    with pytest.raises(ConnectionError):
        wire.unstamp_world(b"\x01x", 17)


def test_truncated_envelope_is_a_transport_error():
    with pytest.raises(ConnectionError):
        wire.read_world(wire.TENANT_PREFIX + b"\x01")


def test_spec_frame_parts_match_stamped_serializer():
    """The native steady cycle byte-compares spec_frame_parts regions;
    they must equal the stamped classic serialization exactly, or a
    native tenant rank and a pure-Python one would drift on the wire."""
    payload = np.arange(8, dtype=np.float32)
    req = CacheCycleRequest(
        epoch=7, nslots=64, hit_mask=0b1010,
        spec_payload=[(DataType.FLOAT32, payload)])
    for world_id in (0, 0x1234ABCD):
        classic = wire.stamp_world(
            wire.serialize_cycle_request(req), world_id)
        prefix, hdrs = wire.spec_frame_parts(
            7, 64, 0b1010, [(DataType.FLOAT32, payload.nbytes)],
            world_id=world_id)
        native = prefix + b"".join(
            h + bytes(b.tobytes()) for h, b in zip(hdrs, [payload]))
        assert native == classic, world_id


def test_combine_cycle_requests_folds_same_world_stamps():
    f1 = wire.stamp_world(wire.serialize_cycle_request(
        CacheCycleRequest(epoch=1, nslots=8, hit_mask=0b11,
                          invalid_mask=0)), 99)
    f2 = wire.stamp_world(wire.serialize_cycle_request(
        CacheCycleRequest(epoch=1, nslots=8, hit_mask=0b01,
                          invalid_mask=0b10)), 99)
    folded = wire.combine_cycle_requests([f1, f2])
    assert folded is not None
    inner = wire.unstamp_world(folded, 99)
    agg = wire.parse_cycle_request(inner)
    assert agg.hit_mask == 0b01 and agg.invalid_mask == 0b10
    # mixed world ids must refuse to fold (forwarded unfolded so the
    # coordinator's unstamp check names the stray)
    f3 = wire.stamp_world(wire.serialize_cycle_request(
        CacheCycleRequest(epoch=1, nslots=8, hit_mask=0b01)), 98)
    assert wire.combine_cycle_requests([f1, f3]) is None


# -- TENANT_* service codecs ------------------------------------------------

def test_tenant_attach_lease_roundtrip():
    att = wire.serialize_tenant_attach(
        wire.TENANT_ATTACH, 0xAB, 3, "evaljob", 2, 4, "10.0.0.9", 7777)
    m = wire.parse_tenant_attach(att)
    assert m == {"kind": wire.TENANT_ATTACH, "world_id": 0xAB,
                 "gen": 3, "tenant": "evaljob", "replica": 2,
                 "group": 4, "host": "10.0.0.9", "port": 7777}
    lease = wire.serialize_tenant_lease(
        wire.TENANT_LEASE, 0xAB, 3, 11, 4,
        [("a", 1), ("b", 2)], cause="ok")
    lm = wire.parse_tenant_lease(lease)
    assert lm["lease"] == 11 and lm["members"] == [("a", 1), ("b", 2)]
    assert lm["cause"] == "ok"


def test_tenant_snapshot_roundtrip_and_dtypes():
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
              "b": np.ones((), np.float64),
              "step": np.asarray([7], np.int64)}
    blob = wire.serialize_tenant_snapshot(5, params)
    version, out = wire.parse_tenant_snapshot(blob)
    assert version == 5 and set(out) == set(params)
    for k in params:
        assert out[k].dtype == params[k].dtype
        assert out[k].shape == params[k].shape
        np.testing.assert_array_equal(out[k], params[k])
    # parsed arrays are fresh copies (writable, detached from frame)
    out["w"][0, 0] = -1.0


def test_tenant_codec_truncation_raises_connection_error():
    """Every prefix cut of every tenant frame must surface as a
    transport error, never struct.error/IndexError (the _Reader
    length-guard contract the wire analyzer enforces)."""
    frames = [
        wire.serialize_tenant_attach(wire.TENANT_ATTACH, 1, 2, "t",
                                     0, 2, "h", 9),
        wire.serialize_tenant_lease(wire.TENANT_LEASE, 1, 2, 3, 2,
                                    [("a", 1)], "c"),
        wire.serialize_tenant_snapshot(
            1, {"w": np.ones(3, np.float32)}),
    ]
    parsers = [wire.parse_tenant_attach, wire.parse_tenant_lease,
               wire.parse_tenant_snapshot]
    for frame, parse in zip(frames, parsers):
        for cut in range(len(frame)):
            with pytest.raises((ConnectionError, ValueError)):
                parse(frame[:cut])


# -- QoS scheduler ----------------------------------------------------------

def _drive(sched, lane, hold_s=0.0, nbytes=0):
    lane.acquire(hold_s)
    lane.note_cycle(nbytes)


def test_scheduler_weighted_share_skews_grants():
    """Two saturated lanes at weights 3:1: stride scheduling must
    grant ~3x the cycles to the heavy lane. Driven synthetically —
    both lanes kept 'wanting' by interleaved acquire/note calls."""
    sched = tenancy.TenantScheduler()
    heavy = sched.register(1, "heavy", 3.0, 0, 0)
    light = sched.register(2, "light", 1.0, 0, 0)
    # Interleave: each round both lanes try to run as fast as the
    # scheduler lets them (hold long enough that ordering is obeyed).
    import threading
    stop = threading.Event()
    counts = {}

    def worker(lane):
        n = 0
        while not stop.is_set():
            _drive(sched, lane, hold_s=1.0)
            n += 1
        counts[lane.name] = n

    ts = [threading.Thread(target=worker, args=(l,))
          for l in (heavy, light)]
    for t in ts:
        t.start()
    import time
    time.sleep(1.0)
    stop.set()
    for t in ts:
        t.join(5.0)
    ratio = counts["heavy"] / max(1, counts["light"])
    assert ratio > 1.8, counts  # 3.0 ideal; generous floor for CI


def test_scheduler_quota_defers_but_never_blocks_forever():
    sched = tenancy.TenantScheduler()
    lane = sched.register(1, "capped", 1.0, 0, quota_cycles_s=5.0)
    import time
    t0 = time.monotonic()
    for _ in range(8):
        _drive(sched, lane, hold_s=0.4)
    elapsed = time.monotonic() - t0
    # 8 cycles at 5/s with a 1-cycle burst: real deferral happened...
    assert lane.deferrals > 0 and lane.deferred_s > 0.1, \
        (lane.deferrals, lane.deferred_s)
    # ...but each wait was clamped by the hold cap, so the lane is
    # deferred, not starved: 8 cycles always complete.
    assert lane.cycles == 8
    assert elapsed < 8 * 0.4 + 1.0


def test_scheduler_idle_lane_gets_no_credit():
    """A lane that idles while another runs is clamped to the global
    virtual clock on re-entry — it must NOT monopolize to catch up.
    The reset is TIME-based (idle > _IDLE_RESET_S), so saturated
    lanes' stride differentials are never clobbered."""
    import time
    sched = tenancy.TenantScheduler()
    a = sched.register(1, "a", 1.0, 0, 0)
    b = sched.register(2, "b", 1.0, 0, 0)
    for _ in range(50):
        _drive(sched, a)
    # b was genuinely idle past the reset window: clamped to a's clock
    time.sleep(tenancy.TenantScheduler._IDLE_RESET_S + 0.1)
    _drive(sched, b)
    assert b.vtime >= a.vtime - 1.5, (a.vtime, b.vtime)
    # whereas a sub-window gap keeps earned stride credit intact
    c = sched.register(3, "c", 1.0, 0, 0)
    base = c.vtime
    _drive(sched, c)
    assert c.vtime == pytest.approx(base + 1.0)


def test_scheduler_unregister_releases_contenders():
    sched = tenancy.TenantScheduler()
    a = sched.register(1, "a", 1.0, 0, 0)
    ghost = sched.register(2, "ghost", 1.0, 0, 0)
    # ghost grabs a turn and never completes (simulates a dead world
    # that stopped mid-cycle with want set)
    ghost.acquire(0.0)
    sched.unregister(ghost)
    import time
    t0 = time.monotonic()
    _drive(sched, a, hold_s=5.0)
    # with the ghost unregistered, a proceeds immediately instead of
    # waiting out the 5s hold cap
    assert time.monotonic() - t0 < 1.0


def test_quota_prefers_live_metrics_bytes():
    total = {"v": 0.0}
    sched = tenancy.TenantScheduler()
    lane = sched.register(1, "m", 1.0, quota_bytes_s=1000.0,
                          quota_cycles_s=0.0,
                          live_bytes_fn=lambda: total["v"])
    lane.note_cycle(0)          # baseline snapshot
    total["v"] += 800.0
    lane.note_cycle(12345)      # reported value must be IGNORED
    assert lane.bytes == 800, lane.bytes
    assert lane.tokens_b == pytest.approx(1000.0 - 800.0, abs=1.0)


# -- per-tenant observability ----------------------------------------------

def test_metrics_registry_tenant_labels():
    from horovod_tpu.common.metrics import MetricsRegistry
    reg = MetricsRegistry(const_labels={"tenant": "jobA"})
    c = reg.counter("hvd_cycles_total", "x")
    assert c.name == 'hvd_cycles_total{tenant="jobA"}'
    g = reg.counter('hvd_ops_total{op="allreduce"}')
    assert g.name == 'hvd_ops_total{op="allreduce",tenant="jobA"}'
    # memoized by labeled name: same object back
    assert reg.counter("hvd_cycles_total") is c
    snap = reg.snapshot()
    assert 'hvd_cycles_total{tenant="jobA"}' in snap


def test_trace_collector_tenant_prefix():
    from horovod_tpu.common.trace import TraceCollector
    col = TraceCollector(tenant="jobA")
    col.slice("ROUND", 1.0, 0.5, 3)
    spans, dropped = col.drain()
    assert spans[0][-1] == "jobA:ROUND"


def test_flight_recorder_worlds_in_header(tmp_path):
    from horovod_tpu.common.trace import FlightRecorder
    import json
    rec = FlightRecorder(capacity=16)
    rec.set_identity(0)
    rec.note_world(0xAB, "jobA", 1)
    rec.record(0, cycle=1)
    path = rec.dump(cause="test", path=str(tmp_path / "f.jsonl"))
    header = json.loads(open(path).read().splitlines()[0])
    assert header["worlds"]["0x000000ab"]["tenant"] == "jobA"
    assert header["worlds"]["0x000000ab"]["rank"] == 1


# -- in-process tenant lifecycle -------------------------------------------

def test_single_member_tenant_and_non_member():
    import horovod_tpu as hvd
    hvd.init()
    try:
        t = hvd.create_tenant("solo.unit", [0])
        assert t is not None and t.size == 1 and t.rank == 0
        assert t.world_id == tenancy.derive_world_id("solo.unit", [0])
        out = t.allreduce(np.full(4, 3.0, np.float32), average=False,
                          name="u")
        np.testing.assert_allclose(out, 3.0)
        # per-tenant auto-name counters are scoped: the default
        # world's sequence is untouched by tenant submissions
        with t.use():
            assert hvd.rank() == 0
        stats = t.lane_stats()
        assert stats["cycles"] >= 1
        line = t._runtime._world_status_line()
        assert "tenant solo.unit" in line and "weight" in line
        t.shutdown()
        assert "solo.unit" not in tenancy.tenants()
        # a rank outside the membership gets None back
        assert hvd.create_tenant("elsewhere", [5, 6]) is None
        # duplicate names in one process are refused
        t2 = hvd.create_tenant("solo.unit", [0])
        assert t2 is not None
        # auto-name counters are scoped AND reset per tenant
        # incarnation: a re-created tenant's sequence restarts at 0
        # on every rank (stale counters would diverge names across
        # a respawned member's fresh process)
        t2.allreduce(np.ones(2, np.float32), average=False)
        from horovod_tpu import ops as _ops
        assert _ops._counters.get(("solo.unit", "allreduce")) == 1
        with pytest.raises(ValueError):
            hvd.create_tenant("solo.unit", [0])
        t2.shutdown()
        assert not any(k[0] == "solo.unit" for k in _ops._counters)
        t3 = hvd.create_tenant("solo.unit", [0])
        out = t3.allreduce(np.full(2, 5.0, np.float32), average=False)
        np.testing.assert_allclose(out, 5.0)
        assert _ops._counters.get(("solo.unit", "allreduce")) == 1
        t3.shutdown()
    finally:
        hvd.shutdown()


def test_service_gate_attach_fanout_detach():
    """In-process service-mode round trip: gate up, two replicas
    attach as one group, the snapshot travels gate → root → child
    over the fanout, both detach; the gate serves ONE send."""
    import threading
    gate = tenancy.ServiceGate(port=0)
    try:
        v = gate.publish({"w": np.arange(6, dtype=np.float32)})
        got = {}

        def client(replica):
            rep = tenancy.attach("127.0.0.1", gate.port, "grp",
                                 replica=replica, group=2, timeout=15)
            got[replica] = rep.fetch_snapshot()
            rep.detach()

        ts = [threading.Thread(target=client, args=(r,))
              for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert set(got) == {0, 1}
        for r in (0, 1):
            ver, params = got[r]
            assert ver == v
            np.testing.assert_array_equal(
                params["w"], np.arange(6, dtype=np.float32))
        stats = gate.stats()
        assert stats["attaches"] == 2 and stats["detaches"] == 2
        assert stats["snapshots_served"] == 1  # fanout did the rest
        assert stats["groups"] == {}
    finally:
        gate.close()


def test_service_gate_close_unblocks_attached_replicas():
    """gate.close() must drain CONNECTED replicas too (their service
    threads park in a timeout-less recv): a still-attached replica's
    next operation fails promptly instead of hanging to process
    exit."""
    import time
    gate = tenancy.ServiceGate(port=0)
    rep = tenancy.attach("127.0.0.1", gate.port, "grp", replica=0,
                         group=1, timeout=15)
    gate.close()
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        rep.fetch_snapshot(min_version=1, timeout=10)
    assert time.monotonic() - t0 < 5.0
