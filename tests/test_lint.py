"""hvdlint (tools/hvdlint) + runtime lockdep (common/lockdep.py).

Two tiers in one module, both fast/in-process (pytest.mark.lint):

* the PROJECT gate — all nine analyzers over ``horovod_tpu/`` must
  report zero findings (this is the tier-1 rendering of the
  acceptance bar `python -m tools.hvdlint horovod_tpu` exits 0);
* per-analyzer FIXTURES — for every analyzer, a known-bad snippet that
  must fire and a known-good twin that must stay silent, proving each
  detection is real rather than vacuously green;
* real-tree MUTATION tests — each seeded historical bug class (and
  each true positive this suite ever fixed) is textually reintroduced
  into a scratch copy of the package and the analyzer must re-find it,
  proving the gate is live on the shipped code, not just on fixtures;
* the ``--changed`` cache — whole-tree replay semantics and every
  invalidation trigger (edit, rename, pragma tweak, analyzer change);
* runtime lockdep unit tests — inversion raise/warn/count semantics,
  condition-variable transparency, metrics mirror.
"""

import glob
import json
import os
import shutil
import subprocess
import sys
import textwrap
import threading

import pytest

from tools.hvdlint import lint_paths
from tools.hvdlint.core import Project

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_snippet(tmp_path, code: str, analyzer: str, name="mod.py",
                  docs: dict = None):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / name).write_text(textwrap.dedent(code))
    if docs:
        d = tmp_path / "docs"
        d.mkdir(exist_ok=True)
        for fn, content in docs.items():
            (d / fn).write_text(content)
    return lint_paths([str(pkg)], [analyzer])


# -- the project gate -------------------------------------------------------

def test_tree_is_clean():
    """Every analyzer over the real package: zero findings. A finding
    here means either a real new bug (fix it) or an intentional
    pattern (suppress WITH a justification, or extend the analyzer's
    allowlist — both reviewed changes)."""
    findings = lint_paths([os.path.join(REPO, "horovod_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_json_exit_codes(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", "horovod_tpu", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["count"] == 0 and payload["findings"] == []

    bad = tmp_path / "bad.py"
    bad.write_text("import os\nX = os.environ.get('HOROVOD_FOO')\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", str(bad), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert payload["count"] == 1
    assert payload["findings"][0]["analyzer"] == "knobs"

    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", "-a", "no-such",
         "horovod_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2


# -- lock-order -------------------------------------------------------------

BAD_LOCK_CYCLE = """
    import threading

    class A:
        def __init__(self):
            self._la = threading.Lock()
            self._lb = threading.Lock()

        def ab(self):
            with self._la:
                with self._lb:
                    pass

        def ba(self):
            with self._lb:
                with self._la:
                    pass
"""

GOOD_LOCK_ORDER = """
    import threading

    class A:
        def __init__(self):
            self._la = threading.Lock()
            self._lb = threading.Lock()

        def ab(self):
            with self._la:
                with self._lb:
                    pass

        def ab2(self):
            with self._la:
                with self._lb:
                    pass
"""


def test_lock_order_cycle_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_LOCK_CYCLE, "lock-order")
    assert any("cycle" in f.message for f in fs), fs


def test_lock_order_consistent_is_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_LOCK_ORDER, "lock-order") == []


def test_lock_order_blocking_under_lock(tmp_path):
    code = """
        import threading
        import time

        class A:
            def __init__(self):
                self._l = threading.Lock()

            def bad(self):
                with self._l:
                    time.sleep(1)

            def good(self):
                with self._l:
                    x = 1
                time.sleep(1)
    """
    fs = _lint_snippet(tmp_path, code, "lock-order")
    assert len(fs) == 1 and "time.sleep" in fs[0].message, fs


def test_lock_order_interprocedural_blocking(tmp_path):
    """Blocking reached through a resolved call chain, not directly."""
    code = """
        import queue
        import threading

        class A:
            def __init__(self):
                self._l = threading.Lock()
                self._queue = queue.Queue()

            def outer(self):
                with self._l:
                    self.inner()

            def inner(self):
                self._queue_wait()

            def _queue_wait(self):
                self._queue.get()
    """
    fs = _lint_snippet(tmp_path, code, "lock-order")
    assert any("may block" in f.message and "outer" in f.message
               for f in fs), fs


def test_lock_order_cv_wait_on_own_lock_is_fine(tmp_path):
    code = """
        import threading

        class H:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def wait(self):
                with self._cv:
                    self._cv.wait_for(lambda: True)
    """
    assert _lint_snippet(tmp_path, code, "lock-order") == []


def test_lock_order_self_deadlock_through_call(tmp_path):
    code = """
        import threading

        class A:
            def __init__(self):
                self._l = threading.Lock()

            def outer(self):
                with self._l:
                    self.inner()

            def inner(self):
                with self._l:
                    pass
    """
    fs = _lint_snippet(tmp_path, code, "lock-order")
    assert any("self-deadlock" in f.message for f in fs), fs


def test_lock_order_suppression_needs_justification(tmp_path):
    code = """
        import threading
        import time

        _l = threading.Lock()

        def bad():
            with _l:
                time.sleep(1)  # hvdlint: disable=lock-order -- boot-only path, single-threaded by contract

        def bad2():
            with _l:
                time.sleep(2)  # hvdlint: disable=lock-order
    """
    fs = _lint_snippet(tmp_path, code, "lock-order")
    # first suppression holds; the bare one is rejected AND the finding
    # on its line is still silenced only by a VALID pragma
    assert any(f.analyzer == "pragma" for f in fs), fs
    assert sum(1 for f in fs if f.analyzer == "lock-order") == 0, fs


# -- wire-protocol ----------------------------------------------------------

BAD_WIRE = """
    import struct

    FRAME_FULL = 0
    FRAME_AGG = 2
    PACKED_PREFIX = b"\\x02"

    def serialize_thing(x):
        return bytes((FRAME_FULL,)) + x

    def parse_thing(data):
        kind = struct.unpack_from("<B", data, 0)[0]
        if kind != FRAME_FULL:
            raise ConnectionError(kind)
        return data[1:]

    def serialize_orphan(x):
        return x
"""

GOOD_WIRE = """
    import struct

    FRAME_FULL = 0
    FRAME_AGG = 2
    PACKED_PREFIX = b"\\xfe"

    def serialize_thing(x, agg=False):
        return bytes((FRAME_AGG if agg else FRAME_FULL,)) + x

    def parse_thing(data):
        if len(data) < 1:
            raise ConnectionError("truncated")
        kind = struct.unpack_from("<B", data, 0)[0]
        if kind not in (FRAME_FULL, FRAME_AGG):
            raise ConnectionError(kind)
        return data[1:]
"""


def test_wire_protocol_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_WIRE, "wire-protocol",
                       name="wire.py")
    msgs = "\n".join(f.message for f in fs)
    assert "collides with frame discriminator FRAME_AGG" in msgs
    assert "no matching parse_orphan" in msgs
    assert "not dominated by a buffer-length guard" in msgs
    # FRAME_AGG never parsed/serialized both ways? it IS unused in
    # parse — the coverage check fires too
    assert "never appears in any parse" in msgs


def test_wire_protocol_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_WIRE, "wire-protocol",
                         name="wire.py") == []


def test_wire_protocol_scopes_to_wire_modules(tmp_path):
    # the same unguarded unpack in a non-wire module is out of scope
    assert _lint_snippet(tmp_path, BAD_WIRE, "wire-protocol",
                         name="codec.py") == []


BAD_WIRE_CODES = """
    WIRE_NONE = 0
    WIRE_BF16 = 1
    WIRE_FP16 = 1
    ALG_DEFAULT = 0
    ALG_RING = 300
"""

GOOD_WIRE_CODES = """
    WIRE_NONE = 0
    WIRE_BF16 = 1
    WIRE_NAMES = 1  # name tables are exempt, not codes
    ALG_DEFAULT = 0
    ALG_STAR = 1
"""


def test_wire_protocol_code_family_collision_fires(tmp_path):
    """The negotiated-attribute families (WIRE_*/ALG_* — the wire
    dtype and algorithm bytes Requests/Responses carry) must stay
    pairwise distinct per family and u8-ranged."""
    fs = _lint_snippet(tmp_path, BAD_WIRE_CODES, "wire-protocol",
                       name="wire_dtype.py")
    msgs = "\n".join(f.message for f in fs)
    assert "WIRE_BF16 and WIRE_FP16 share byte value" in msgs
    assert "ALG_RING = 300 does not fit the u8" in msgs


def test_wire_protocol_code_family_clean(tmp_path):
    # same family value reused across DIFFERENT families is fine
    # (WIRE_BF16 == ALG_STAR == 1): the families ride distinct bytes
    assert _lint_snippet(tmp_path, GOOD_WIRE_CODES, "wire-protocol",
                         name="wire_dtype.py") == []


BAD_TRACE_CODES = """
    SPAN_SLICE = 0
    SPAN_MARK = 0
    EV_CYCLE = 0
    EV_ABORT = 1
    EV_ELASTIC = 1
    EV_NAMES = 1  # name tables exempt
"""


def test_wire_protocol_trace_code_families_fire(tmp_path):
    """The PR 11 families — SPAN_* trace span kinds and EV_* flight
    recorder event codes — join the same distinctness contract: a
    collision silently aliases two meanings in every TRACE frame and
    every postmortem ring."""
    fs = _lint_snippet(tmp_path, BAD_TRACE_CODES, "wire-protocol",
                       name="wire.py")
    msgs = "\n".join(f.message for f in fs)
    assert "SPAN_SLICE and SPAN_MARK share byte value" in msgs
    assert "EV_ABORT and EV_ELASTIC share byte value" in msgs


BAD_TENANT_CODES = """
    TENANT_ATTACH = 0
    TENANT_LEASE = 0
    TENANT_NAMES = 0  # name tables exempt
    TENANT_BIG = 300
"""


def test_wire_protocol_tenant_code_family_fires(tmp_path):
    """The TENANT_* service-plane frame kinds (common/tenancy.py
    attach/lease/snapshot protocol) join the distinctness contract —
    an aliased kind byte would let one gate frame decode as another."""
    fs = _lint_snippet(tmp_path, BAD_TENANT_CODES, "wire-protocol",
                       name="wire.py")
    msgs = "\n".join(f.message for f in fs)
    assert "TENANT_ATTACH and TENANT_LEASE share byte value" in msgs
    assert "TENANT_BIG = 300 does not fit the u8" in msgs


def test_wire_protocol_real_tenant_codes_distinct():
    """Anchor the real tree: every TENANT_* kind in wire.py is
    distinct and u8-ranged (the analyzer gate proves itself on the
    fixture above; this proves the SHIPPED codes)."""
    from horovod_tpu.common import wire
    codes = {n: getattr(wire, n) for n in dir(wire)
             if n.startswith("TENANT_") and not n.endswith("NAMES")
             and not n.endswith("PREFIX")
             and isinstance(getattr(wire, n), int)}
    assert len(codes) >= 6, codes
    assert len(set(codes.values())) == len(codes), codes
    assert all(0 <= v <= 255 for v in codes.values()), codes


BAD_ALG_CODES = """
    ALG_DEFAULT = 0
    ALG_STAR = 1
    ALG_TWOLEVEL = 3
    ALG_ICI = 3
    ALG_HUGE = 300
"""


def test_wire_protocol_alg_ici_joins_family_distinctness(tmp_path):
    """ALG_ICI (the ISSUE 18 mesh-plane verdict) rides the same
    negotiated u8 algorithm byte as star/ring/two-level — a collision
    would make the coordinator's ICI stamp decode as another
    topology on every peer."""
    fs = _lint_snippet(tmp_path, BAD_ALG_CODES, "wire-protocol",
                       name="wire_dtype.py")
    msgs = "\n".join(f.message for f in fs)
    assert "ALG_TWOLEVEL and ALG_ICI share byte value" in msgs
    assert "ALG_HUGE = 300 does not fit the u8" in msgs


def test_wire_protocol_real_alg_codes_distinct():
    """Anchor the real tree: every shipped ALG_* verdict code in
    wire_dtype.py — ALG_ICI included — is pairwise distinct and
    u8-ranged."""
    from horovod_tpu.common import wire_dtype as wd
    codes = {n: getattr(wd, n) for n in dir(wd)
             if n.startswith("ALG_") and not n.endswith("NAMES")
             and isinstance(getattr(wd, n), int)}
    assert len(codes) >= 5, codes          # default/star/ring/2lvl/ici
    assert "ALG_ICI" in codes, codes
    assert len(set(codes.values())) == len(codes), codes
    assert all(0 <= v <= 255 for v in codes.values()), codes


BAD_CONTROLLER_TAGS = """
    TAG_HANDSHAKE = 1
    TAG_REQUESTS = 2
    TAG_TRACE = 2
    TAG_BIG = 999
"""

GOOD_CONTROLLER_TAGS = """
    TAG_HANDSHAKE = 1
    TAG_REQUESTS = 2
    TAG_METRICS = 7
    TAG_TRACE = 8
"""


def test_wire_protocol_controller_tag_collision_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_CONTROLLER_TAGS, "wire-protocol",
                       name="controller.py")
    msgs = "\n".join(f.message for f in fs)
    assert "TAG_REQUESTS and TAG_TRACE share byte value" in msgs
    assert "TAG_BIG = 999 does not fit the u8" in msgs


def test_wire_protocol_controller_tags_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_CONTROLLER_TAGS,
                         "wire-protocol", name="controller.py") == []


def test_trace_frame_codec_real_tree_guarded(tmp_path):
    """The REAL wire.py trace codec passes the analyzer — pairing
    (serialize_/parse_trace_frame), guard domination, and family
    distinctness all hold on the shipped tree (the clean-tree gate
    covers this too; this pins the specific module)."""
    import shutil
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    shutil.copy(os.path.join(REPO, "horovod_tpu", "common", "wire.py"),
                pkg / "wire.py")
    assert lint_paths([str(pkg)], ["wire-protocol"]) == []


# -- native-codec -----------------------------------------------------------

_NATIVE_HEADER = """
    #pragma once
    #include <cstdint>
    extern "C" {
    int hvd_sum_into(void* acc, const void* src, int64_t count,
                     int dtype);
    int hvd_gather_frames(const int* fds, int n, const uint8_t* secret,
                          int secret_len, uint8_t** bufs, int64_t* lens,
                          uint8_t* tags, int timeout_ms);
    void hvd_free(uint8_t* buf);
    int hvd_orphan(int fd, void (*cb)(void), int n);
    }
"""

BAD_NATIVE_LOADER = """
    import ctypes

    def _configure(lib):
        # arity drift: C declares 4 params, mirror lists 3
        lib.hvd_sum_into.restype = ctypes.c_int
        lib.hvd_sum_into.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        # argtypes without restype
        lib.hvd_gather_frames.argtypes = [
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvd_free.restype = None
        lib.hvd_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        # configured but not declared anywhere
        lib.hvd_ghost.restype = ctypes.c_int
        lib.hvd_ghost.argtypes = [ctypes.c_int]

    def gather(lib, fds):
        # allocating entry point with no hvd_free anywhere in sight
        return lib.hvd_gather_frames(fds, 1, None, 0, None, None,
                                     None, -1)
"""

GOOD_NATIVE_LOADER = """
    import ctypes

    def _configure(lib):
        lib.hvd_sum_into.restype = ctypes.c_int
        lib.hvd_sum_into.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int]
        lib.hvd_gather_frames.restype = ctypes.c_int
        lib.hvd_gather_frames.argtypes = [
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvd_free.restype = None
        lib.hvd_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.hvd_orphan.restype = ctypes.c_int
        lib.hvd_orphan.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                   ctypes.c_int]

    def gather(lib, fds, bufs):
        rc = lib.hvd_gather_frames(fds, 1, None, 0, bufs, None,
                                   None, -1)
        for b in bufs:
            lib.hvd_free(b)
        return rc
"""


def _lint_native(tmp_path, loader_code: str, header: str = None):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "native.py").write_text(textwrap.dedent(loader_code))
    native_dir = tmp_path / "native"
    native_dir.mkdir(exist_ok=True)
    (native_dir / "hvdtpu.h").write_text(
        textwrap.dedent(header or _NATIVE_HEADER))
    return lint_paths([str(pkg)], ["native-codec"])


def test_native_codec_fires(tmp_path):
    fs = _lint_native(tmp_path, BAD_NATIVE_LOADER)
    msgs = "\n".join(f.message for f in fs)
    assert "argtypes lists 3 parameters but the C declaration has 4" \
        in msgs
    assert "hvd_gather_frames has argtypes but no restype" in msgs
    assert "hvd_orphan is declared" in msgs  # unmirrored entry point
    assert "hvd_ghost is configured for ctypes but not declared" in msgs
    assert "never references hvd_free" in msgs


def test_native_codec_clean(tmp_path):
    assert _lint_native(tmp_path, GOOD_NATIVE_LOADER) == []


def test_native_codec_function_pointer_arity(tmp_path):
    """A function-pointer parameter's own parentheses must not split
    the C parameter count (the hvd_steady_coord on_idle shape)."""
    from tools.hvdlint.native_codec import parse_header
    decls = parse_header(textwrap.dedent(_NATIVE_HEADER))
    assert decls["hvd_orphan"] == 3


def test_native_codec_tag_distinctness(tmp_path):
    code = """
        TAG_A = 1
        TAG_B = 1
        TAG_BIG = 300
    """
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "controller.py").write_text(textwrap.dedent(code))
    fs = lint_paths([str(pkg)], ["native-codec"])
    msgs = "\n".join(f.message for f in fs)
    assert "TAG_A and TAG_B share byte value" in msgs
    assert "does not fit the u8 tag byte" in msgs


def test_native_codec_real_tree_mirror():
    """The REAL loader must mirror the REAL header exactly — this is
    the check that catches a future C signature change whose author
    forgot the ctypes side."""
    from tools.hvdlint.native_codec import parse_header
    header = os.path.join(REPO, "native", "hvdtpu.h")
    with open(header) as fh:
        decls = parse_header(fh.read())
    # every entry point this PR leans on is visible to the analyzer
    for fn in ("hvd_sendv", "hvd_recv_into", "hvd_steady_worker",
               "hvd_steady_worker_chunked", "hvd_steady_coord",
               "hvd_sum_into", "hvd_cast",
               # the kernel-side wire-speed additions
               "hvd_gather_frames_batched", "hvd_sendv_zc",
               "hvd_relay_frame", "hvd_quant8", "hvd_dequant8",
               "hvd_build_flags"):
        assert fn in decls, fn
    fs = lint_paths([os.path.join(REPO, "horovod_tpu")],
                    ["native-codec"])
    assert fs == [], "\n".join(f.render() for f in fs)


BAD_REACTOR_DRIVER = """
    import ctypes

    def gather_batched(lib, fds, n):
        dev = ctypes.POINTER(ctypes.c_uint8)()
        return lib.hvd_gather_frames_batched(fds, n, ctypes.byref(dev))

    def relay(lib, up_fd, kids):
        spill = ctypes.POINTER(ctypes.c_uint8)()
        return lib.hvd_relay_frame(up_fd, kids, ctypes.byref(spill))
"""


def test_native_codec_reactor_entry_points_allocating(tmp_path):
    """The reactor entry points spill malloc'd frames back to Python
    (batched-gather deviations, relay oversize/deviation payloads) —
    a driver that consumes them without hvd_free is the same
    per-cycle leak as a gather_frames driver."""
    fs = _lint_native(tmp_path, BAD_REACTOR_DRIVER)
    msgs = "\n".join(f.message for f in fs)
    assert "gather_batched calls hvd_gather_frames_batched" in msgs
    assert "relay calls hvd_relay_frame" in msgs


def test_wire_truncated_frames_raise_connectionerror():
    """The fix the analyzer demanded: every decoder surfaces a
    truncated buffer as ConnectionError, never struct.error/IndexError
    or a silently-wrong mask."""
    import numpy as np

    from horovod_tpu.common import wire
    from horovod_tpu.common.message import (
        CacheCycleRequest, Request, RequestList, RequestType, DataType,
    )

    req = Request(request_rank=0, request_type=RequestType.ALLREDUCE,
                  tensor_type=DataType.FLOAT32, tensor_name="t",
                  tensor_shape=(4, 4))
    full = wire.serialize_cycle_request(RequestList([req], False))
    cached = wire.serialize_cycle_request(CacheCycleRequest(
        epoch=3, nslots=64, hit_mask=(1 << 63) | 5,
        spec_payload=[(DataType.FLOAT32,
                       np.ones(8, np.float32).tobytes())]))
    metrics = wire.serialize_metrics_frame(
        1, {"c": {"k": "c", "v": 1.0},
            "h": {"k": "h", "bounds": [0.1], "counts": [1, 2],
                  "sum": 0.5, "count": 3}})
    for blob, parse in ((full, wire.parse_cycle_request),
                        (cached, wire.parse_cycle_request),
                        (metrics, wire.parse_metrics_frame)):
        parse(blob)  # intact roundtrip sanity
        for cut in range(1, len(blob)):
            try:
                parse(blob[:cut])
            except (ConnectionError, ValueError):
                pass  # ValueError: metrics version byte path
            # no struct.error, no IndexError, no silent success with
            # a wrong mask REQUIRED — silent success is only legal if
            # the truncation removed nothing the parser reads
    # the mask specifically must never silently truncate
    with pytest.raises(ConnectionError):
        wire.parse_cycle_request(cached[:15])


# -- world-coherence --------------------------------------------------------

BAD_COHERENCE = """
    class Cache:
        def __init__(self):
            self.epoch = 0  # hvdlint: world-replicated

        def put(self, k):
            self.epoch += 1

    class Runtime:
        def __init__(self):
            self._cache = Cache()

        def local_poke(self):
            self._cache.put("x")
"""

GOOD_COHERENCE = """
    from horovod_tpu.common.invariants import world_coherent

    class Cache:
        def __init__(self):
            self.epoch = 0  # hvdlint: world-replicated

        def put(self, k):
            self.epoch += 1

    class Runtime:
        def __init__(self):
            self._cache = Cache()

        @world_coherent
        def apply_verdict(self):
            self._cache.put("x")
"""


def test_world_coherence_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_COHERENCE, "world-coherence")
    msgs = "\n".join(f.message for f in fs)
    assert "world-replicated" in msgs and "Cache.put" in msgs, fs


def test_world_coherence_annotated_is_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_COHERENCE,
                         "world-coherence") == []


def test_world_coherence_decorator_is_load_bearing():
    """Stripping @world_coherent from the runtime's verdict applier
    must fail the real tree — the annotation is what the analyzer
    anchors trust to, not a comment."""
    from tools.hvdlint import world_coherence
    p = Project([os.path.join(REPO, "horovod_tpu")])
    info = p.index.functions[
        "horovod_tpu.common.runtime.Runtime._apply_cached_cycle"]
    info.decorators = set()
    fs = world_coherence.run(p)
    assert any("world-replicated" in f.message for f in fs), fs


# A rank-local mutation of the elastic membership (the PR 8 rank
# table / generation / blacklist) — the exact divergence class the
# elastic re-rendezvous must never allow: one rank editing its own
# view of who is in the world outside a broadcast verdict.
BAD_ELASTIC_COHERENCE = """
    class Membership:
        def __init__(self):
            self.rank_table = {}  # hvdlint: world-replicated
            self.generation = 0  # hvdlint: world-replicated

        def install(self, gen, table):
            self.rank_table = dict(table)
            self.generation = gen

    class Recovery:
        def __init__(self):
            self._membership = Membership()

        def handle_timeout(self, dead_rank):
            # rank-LOCAL guess: drops a member without a verdict
            self._membership.install(
                self._membership.generation + 1, {})
"""


def test_world_coherence_fires_on_local_elastic_mutation(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_ELASTIC_COHERENCE,
                       "world-coherence")
    msgs = "\n".join(f.message for f in fs)
    assert "world-replicated" in msgs and "Membership.install" in msgs, fs


def test_world_coherence_real_elastic_membership_is_anchored():
    """The REAL elastic Membership.install must carry the
    @world_coherent anchor — stripping it fails the tree, proving the
    rank table / generation / blacklist can only move behind
    broadcast-identical inputs."""
    from tools.hvdlint import world_coherence
    p = Project([os.path.join(REPO, "horovod_tpu")])
    qn = "horovod_tpu.common.elastic.Membership.install"
    assert qn in p.index.functions, sorted(
        k for k in p.index.functions if "elastic" in k)[:20]
    info = p.index.functions[qn]
    info.decorators = set()
    # apply_membership is covered only through its own decorator;
    # strip that too so coverage cannot flow around the mutator.
    p.index.functions[
        "horovod_tpu.common.elastic.ElasticContext.apply_membership"
    ].decorators = set()
    fs = world_coherence.run(p)
    assert any("Membership" in f.message
               and "world-replicated" in f.message for f in fs), fs


# A rank-local mutation of an overlap in-flight cycle table — the
# divergence class the overlap tier must never allow: one rank
# reordering (or locally appending to) its submitted-cycle sequence
# outside the world-identically-built submission path, which would
# desynchronize the strictly-FIFO wire order peers rely on.
BAD_OVERLAP_COHERENCE = """
    class Runtime:
        def __init__(self):
            self._inflight_masks = []  # hvdlint: world-replicated

        def requeue_priority(self, mask):
            # rank-LOCAL reorder: jumps a cycle ahead of the FIFO
            self._inflight_masks.insert(0, mask)
"""


def test_world_coherence_fires_on_local_overlap_mutation(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_OVERLAP_COHERENCE,
                       "world-coherence")
    msgs = "\n".join(f.message for f in fs)
    assert "world-replicated" in msgs \
        and "requeue_priority" in msgs, fs


def test_world_coherence_real_ici_plan_state_is_anchored():
    """The REAL IciPlane.note_cache_epoch must carry the
    @world_coherent anchor — its epoch-coupled compiled-plan state is
    world-replicated (one fused-psum executable set per broadcast
    cache epoch); stripping the anchor fails the tree, proving a
    rank-local epoch move (which would desynchronize eviction and
    replay stale executables on one rank) cannot land unnoticed."""
    from tools.hvdlint import world_coherence
    p = Project([os.path.join(REPO, "horovod_tpu")])
    qn = "horovod_tpu.ops.xla_ops.IciPlane.note_cache_epoch"
    assert qn in p.index.functions, sorted(
        k for k in p.index.functions if "IciPlane" in k)[:20]
    p.index.functions[qn].decorators = set()
    fs = world_coherence.run(p)
    assert any("_epoch" in f.message
               and "world-replicated" in f.message for f in fs), fs


def test_world_coherence_real_overlap_inflight_is_anchored():
    """The REAL overlap submit path must carry the @world_coherent
    anchor — stripping it (and the drain-side mutators coverage could
    flow through) fails the tree, proving the in-flight cycle
    sequence only ever moves in the world-identical program order."""
    from tools.hvdlint import world_coherence
    p = Project([os.path.join(REPO, "horovod_tpu")])
    qn = "horovod_tpu.common.runtime.Runtime._submit_overlap_cycle"
    assert qn in p.index.functions, sorted(
        k for k in p.index.functions if "overlap" in k)[:20]
    for fn in ("_submit_overlap_cycle", "_apply_overlap_verdict",
               "_unwind_cancelled_cycle", "_drop_inflight_mask"):
        p.index.functions[
            f"horovod_tpu.common.runtime.Runtime.{fn}"
        ].decorators = set()
    fs = world_coherence.run(p)
    assert any("_inflight_masks" in f.message
               and "world-replicated" in f.message for f in fs), fs


# A rank-local mutation of a tenant's scheduling descriptor — the
# divergence class multi-tenancy must never allow: one rank adopting
# its LOCAL env weight/quota instead of the coordinator-broadcast
# descriptor, so its pacing (and therefore its cycle participation)
# marches to a different drummer than its peers'.
BAD_TENANT_COHERENCE = """
    class Tenant:
        def __init__(self):
            self._desc = None  # hvdlint: world-replicated

        def apply(self, desc):
            self._desc = dict(desc)

    class Bootstrap:
        def __init__(self):
            self._tenant = Tenant()

        def from_local_env(self, env_weight):
            # rank-LOCAL source: this rank's env, not the broadcast
            self._tenant.apply({"weight": env_weight})
"""


def test_world_coherence_fires_on_local_tenant_descriptor(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_TENANT_COHERENCE,
                       "world-coherence")
    msgs = "\n".join(f.message for f in fs)
    assert "world-replicated" in msgs and "Tenant.apply" in msgs, fs


def test_world_coherence_real_tenant_descriptor_is_anchored():
    """The REAL tenant descriptor install must carry the
    @world_coherent anchor — stripping it (and the module-level
    installer coverage could flow through) fails the tree, proving
    tenant scheduling state only ever moves on the coordinator's
    handshake broadcast."""
    from tools.hvdlint import world_coherence
    p = Project([os.path.join(REPO, "horovod_tpu")])
    qn = "horovod_tpu.common.tenancy.Tenant._apply_descriptor"
    assert qn in p.index.functions, sorted(
        k for k in p.index.functions if "tenancy" in k)[:20]
    p.index.functions[qn].decorators = set()
    p.index.functions[
        "horovod_tpu.common.tenancy._install_descriptor"
    ].decorators = set()
    fs = world_coherence.run(p)
    assert any("_desc" in f.message
               and "world-replicated" in f.message for f in fs), fs


# A rank-local mutation of the supervision verdict — the divergence
# class self-operation must never allow: one rank adopting a demotion
# (and therefore pacing its cycles) that its peers never saw, instead
# of installing the descriptor carried by the resize verdict broadcast.
BAD_SELFOP_COHERENCE = """
    class SupervisionVerdict:
        def __init__(self):
            self.kind = ""  # hvdlint: world-replicated
            self.pace_us = 0  # hvdlint: world-replicated

        def install(self, kind, pace_us):
            self.kind = kind
            self.pace_us = pace_us

    class Policy:
        def __init__(self):
            self._verdict = SupervisionVerdict()

        def local_hunch(self, lag_s):
            # rank-LOCAL source: this rank's own lag estimate, not the
            # coordinator's broadcast decision
            self._verdict.install("demote", int(lag_s * 1e6))
"""


def test_world_coherence_fires_on_local_selfop_verdict(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_SELFOP_COHERENCE,
                       "world-coherence")
    msgs = "\n".join(f.message for f in fs)
    assert "world-replicated" in msgs \
        and "SupervisionVerdict.install" in msgs, fs


def test_world_coherence_real_selfop_verdict_is_anchored():
    """The REAL SupervisionVerdict.install must carry the
    @world_coherent anchor — stripping it fails the tree, proving the
    demotion/pacing descriptor only ever moves on inputs every member
    received in the same resize verdict."""
    from tools.hvdlint import world_coherence
    p = Project([os.path.join(REPO, "horovod_tpu")])
    qn = "horovod_tpu.common.selfop.SupervisionVerdict.install"
    assert qn in p.index.functions, sorted(
        k for k in p.index.functions if "selfop" in k)[:20]
    p.index.functions[qn].decorators = set()
    fs = world_coherence.run(p)
    assert any("SupervisionVerdict" in f.message
               and "world-replicated" in f.message for f in fs), fs


def test_world_coherent_decorator_is_identity():
    from horovod_tpu.common.invariants import world_coherent

    @world_coherent
    def f(x):
        return x + 1

    assert f(1) == 2 and f.__world_coherent__


# -- teardown ---------------------------------------------------------------

BAD_TEARDOWN = """
    class R:
        def run(self):
            try:
                pass
            finally:
                self.finalizer.drain()
                self.timeline.shutdown()
"""

GOOD_TEARDOWN = """
    class R:
        def run(self):
            try:
                pass
            finally:
                try:
                    self.finalizer.drain()
                except Exception:
                    pass
                try:
                    self.timeline.shutdown()
                except Exception:
                    pass
"""


def test_teardown_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_TEARDOWN, "teardown")
    assert len(fs) == 2, fs
    assert all("unguarded cleanup stage" in f.message for f in fs)


def test_teardown_guarded_is_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_TEARDOWN, "teardown") == []


def test_teardown_close_function_last_stage_may_raise(tmp_path):
    code = """
        class C:
            def close(self):
                try:
                    self._ch.close()
                except OSError:
                    pass
                self._server.close()
    """
    assert _lint_snippet(tmp_path, code, "teardown") == []


def test_teardown_single_stage_is_fine(tmp_path):
    code = """
        def f(path):
            fh = open(path)
            try:
                return fh.read()
            finally:
                fh.close()
    """
    assert _lint_snippet(tmp_path, code, "teardown") == []


# -- knobs ------------------------------------------------------------------

def test_knobs_direct_read_fires(tmp_path):
    code = """
        import os

        def f():
            return os.environ.get("HOROVOD_WHATEVER", "1")
    """
    fs = _lint_snippet(tmp_path, code, "knobs")
    assert any("HOROVOD_WHATEVER" in f.message
               and "outside common/config.py" in f.message for f in fs)


def test_knobs_config_module_and_writes_are_fine(tmp_path):
    code = """
        import os

        def from_env():
            return os.environ.get("HOROVOD_THING", "1")

        def launcher(v):
            os.environ["HOROVOD_CHILD"] = v
            os.environ.setdefault("HOROVOD_OTHER", "x")
    """
    fs = _lint_snippet(tmp_path, code, "knobs", name="config.py",
                       docs={"knobs.md": "HOROVOD_THING does things"})
    assert fs == [], fs


def test_knobs_undocumented_fires(tmp_path):
    code = """
        import os

        def from_env():
            return os.environ.get("HOROVOD_SECRET_HANDSHAKE", "")
    """
    fs = _lint_snippet(tmp_path, code, "knobs", name="config.py",
                       docs={"other.md": "nothing relevant"})
    assert any("appears nowhere" in f.message for f in fs), fs


# -- runtime lockdep --------------------------------------------------------

@pytest.fixture
def lockcheck():
    from horovod_tpu.common import lockdep
    lockdep.reset("raise")
    yield lockdep
    lockdep.reset()


def test_lockdep_inversion_raises(lockcheck):
    a = lockcheck.lock("t.A")
    b = lockcheck.lock("t.B")
    with a:
        with b:
            pass
    with pytest.raises(lockcheck.LockInversionError) as ei:
        with b:
            with a:
                pass
    assert "t.A" in str(ei.value) and "t.B" in str(ei.value)
    assert lockcheck.inversion_count() == 1
    # the inverting acquire was REFUSED before taking the lock: a is
    # free, so the consistent order still works afterwards
    with a:
        with b:
            pass


def test_lockdep_consistent_order_never_fires(lockcheck):
    a = lockcheck.lock("t.A")
    b = lockcheck.lock("t.B")
    errors = []

    def worker():
        try:
            for _ in range(200):
                with a:
                    with b:
                        pass
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors and lockcheck.inversion_count() == 0


def test_lockdep_cross_thread_inversion(lockcheck):
    """The edge recorded by one thread convicts another — that is the
    whole point (a single thread never deadlocks with itself)."""
    a = lockcheck.lock("t.A")
    b = lockcheck.lock("t.B")

    def establish():
        with a:
            with b:
                pass

    t = threading.Thread(target=establish)
    t.start()
    t.join()
    with pytest.raises(lockcheck.LockInversionError):
        with b:
            with a:
                pass


def test_lockdep_same_class_instances_do_not_false_positive(lockcheck):
    l1 = lockcheck.lock("metrics.Counter._lock")
    l2 = lockcheck.lock("metrics.Counter._lock")
    with l1:
        with l2:
            pass
    with l2:
        with l1:
            pass
    assert lockcheck.inversion_count() == 0


def test_lockdep_condition_shares_lock_class(lockcheck):
    lk = lockcheck.lock("t.H")
    cv = lockcheck.condition("t.H", lk)
    done = []

    def waiter():
        with cv:
            cv.wait_for(lambda: bool(done), timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    with cv:
        done.append(1)
        cv.notify_all()
    t.join(5.0)
    assert not t.is_alive()
    assert lockcheck.inversion_count() == 0


def test_lockdep_warn_mode_counts_without_raising(capsys):
    from horovod_tpu.common import lockdep
    lockdep.reset("warn")
    try:
        a = lockdep.lock("w.A")
        b = lockdep.lock("w.B")
        with a:
            with b:
                pass
        with b:
            with a:  # warn-mode: logged + counted, not raised
                pass
        assert lockdep.inversion_count() == 1
        assert "lock-order inversion" in capsys.readouterr().err
    finally:
        lockdep.reset()


def test_lockdep_disabled_returns_plain_locks():
    from horovod_tpu.common import lockdep
    lockdep.reset("")
    try:
        lk = lockdep.lock("x")
        assert isinstance(lk, type(threading.Lock()))
    finally:
        lockdep.reset()


def test_lockdep_counter_reaches_metrics_plane(monkeypatch):
    """Satellite: an armed world surfaces inversions on the metrics
    plane — hvd_lockcheck_inversions_total mirrors
    lockdep.inversion_count() through the runtime collector."""
    import horovod_tpu as hvd
    from horovod_tpu.common import lockdep

    hvd.shutdown()
    lockdep.reset("warn")
    monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
    try:
        a = lockdep.lock("m.A")
        b = lockdep.lock("m.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert lockdep.inversion_count() == 1
        hvd.init()
        try:
            view = hvd.metrics()
            rec = view["local"]["hvd_lockcheck_inversions_total"]
            assert rec["v"] == 1.0, rec
            world = view["world"]["hvd_lockcheck_inversions_total"]
            assert world["v"] == 1.0, world
        finally:
            hvd.shutdown()
    finally:
        lockdep.reset()


def test_logging_lock_level_env_still_works(monkeypatch, capsys):
    """The knob rerouting kept semantics: HOROVOD_LOG_HIDE_TIME is now
    a real boolean (hvdlint: knobs), and levels still gate."""
    from horovod_tpu.common import logging as hlog
    monkeypatch.setenv("HOROVOD_LOG_HIDE_TIME", "1")
    hlog.set_level("info")
    try:
        hlog.info("knob-reroute-probe", rank=3)
        err = capsys.readouterr().err
        assert "knob-reroute-probe" in err and "[3]" in err
        assert not any(ch.isdigit() for ch in err.split("[3]")[0])
    finally:
        hlog.reset_level()


# -- CLI --list completeness ------------------------------------------------

def test_list_names_every_analyzer():
    """--list is the discovery surface: a registered analyzer missing
    here (or an unregistered module) is a silent hole in the gate."""
    from tools.hvdlint.core import get_analyzers
    out = subprocess.run(
        [sys.executable, "-m", "tools.hvdlint", "--list"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    listed = out.stdout.split()
    assert listed == sorted(get_analyzers())
    assert listed == [
        "jax_compat", "knobs", "lock-order", "native-codec",
        "native-lifetime", "teardown", "thread-ownership",
        "wire-protocol", "world-coherence"]


# -- thread-ownership -------------------------------------------------------

# check 1: compound writes from two roles, nothing ordering them
BAD_MULTI_ROLE_WRITE = """
    import threading

    class Svc:
        def __init__(self):
            self._stats = {}
            t = threading.Thread(target=self._loop,
                                 name="hvd-background", daemon=True)
            t.start()

        def _loop(self):
            self._stats["cycles"] = 1

        def public(self):
            self._stats["calls"] = 2
"""

GOOD_MULTI_ROLE_WRITE = """
    import threading

    class Svc:
        def __init__(self):
            self._lk = threading.Lock()
            self._stats = {}
            t = threading.Thread(target=self._loop,
                                 name="hvd-background", daemon=True)
            t.start()

        def _loop(self):
            with self._lk:
                self._stats["cycles"] = 1

        def public(self):
            with self._lk:
                self._stats["calls"] = 2
"""


def test_thread_ownership_multi_role_write_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_MULTI_ROLE_WRITE,
                       "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "compound writes" in msgs and "hvd-background" in msgs, fs


def test_thread_ownership_locked_writes_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_MULTI_ROLE_WRITE,
                         "thread-ownership") == []


# check 2: single writer, foreign lock-free reader, no snapshot-swap
BAD_UNPUBLISHED_WRITE = """
    import threading

    class Svc:
        def __init__(self):
            self._table = {}
            t = threading.Thread(target=self._loop,
                                 name="hvd-background", daemon=True)
            t.start()

        def _loop(self):
            self._table["x"] = 1

        def read(self):
            return self._table.get("x")
"""

# the snapshot-swap idiom: the writer rebinds a freshly built dict in
# one assignment — a lock-free reader sees old or new, never a hybrid
GOOD_SNAPSHOT_SWAP = """
    import threading

    class Svc:
        def __init__(self):
            self._table = {}
            t = threading.Thread(target=self._loop,
                                 name="hvd-background", daemon=True)
            t.start()

        def _loop(self):
            self._table = {"x": 1}

        def read(self):
            return self._table.get("x")
"""


def test_thread_ownership_unpublished_write_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_UNPUBLISHED_WRITE,
                       "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "read from role(s)" in msgs and "['main']" in msgs, fs


def test_thread_ownership_snapshot_swap_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_SNAPSHOT_SWAP,
                         "thread-ownership") == []


# check 3: the _on_arrivals shape — a rebindable hook read twice with
# a rebind possible between the reads (if self.hook: self.hook())
BAD_CAPTURE_ONCE = """
    class Svc:
        _hook = None

        def attach(self, cb):
            self._hook = cb

        def fire(self):
            if self._hook is not None:
                self._hook(1)
"""

GOOD_CAPTURE_ONCE = """
    class Svc:
        _hook = None

        def attach(self, cb):
            self._hook = cb

        def fire(self):
            hook = self._hook
            if hook is not None:
                hook(1)
"""


def test_thread_ownership_capture_once_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_CAPTURE_ONCE, "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "capture it into a local once" in msgs, fs


def test_thread_ownership_captured_hook_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_CAPTURE_ONCE,
                         "thread-ownership") == []


def test_thread_ownership_sees_through_inheritance(tmp_path):
    """A base-declared hook read from a derived-class method is the
    SAME storage — the exact split that hid the original
    Controller._on_arrivals bug from a per-class field model."""
    code = """
        class Base:
            _hook = None

            def attach(self, cb):
                self._hook = cb

        class Derived(Base):
            def fire(self):
                if self._hook is not None:
                    self._hook(1)
    """
    fs = _lint_snippet(tmp_path, code, "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "mod.Base._hook" in msgs, fs


# check 4: the mark_done shape — gate published before the payload a
# lock-free reader keys on
BAD_PUBLISH_ORDER = """
    import threading

    class Table:
        def __init__(self):
            self._lk = threading.Lock()
            self._res = {}
            self._out = {}

        def done(self, h, status, output):
            with self._lk:
                self._res[h] = status
                self._out[h] = output

        def poll(self, h):
            return self._res.get(h) is not None

        def get(self, h):
            return self._out[h]
"""

GOOD_PUBLISH_ORDER = """
    import threading

    class Table:
        def __init__(self):
            self._lk = threading.Lock()
            self._res = {}
            self._out = {}

        def done(self, h, status, output):
            with self._lk:
                self._out[h] = output
                self._res[h] = status

        def poll(self, h):
            return self._res.get(h) is not None

        def get(self, h):
            return self._out[h]
"""


def test_thread_ownership_publish_order_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_PUBLISH_ORDER, "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "before storing payload" in msgs, fs


def test_thread_ownership_payload_first_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_PUBLISH_ORDER,
                         "thread-ownership") == []


def test_thread_ownership_pragma_suppresses_with_justification(tmp_path):
    code = BAD_MULTI_ROLE_WRITE.replace(
        'self._stats["calls"] = 2',
        'self._stats["calls"] = 2  '
        '# hvdlint: owned-by=main -- single-writer in this app')
    assert _lint_snippet(tmp_path, code, "thread-ownership") == []


def test_thread_ownership_pragma_requires_justification(tmp_path):
    code = BAD_MULTI_ROLE_WRITE.replace(
        'self._stats["calls"] = 2',
        'self._stats["calls"] = 2  # hvdlint: owned-by=main')
    fs = _lint_snippet(tmp_path, code, "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "justification" in msgs, fs


# -- native-lifetime --------------------------------------------------------

BAD_INLINE_TEMPORARY = """
    import ctypes
    import numpy as np

    def call(lib, x):
        lib.hvd_pack(np.ascontiguousarray(x).ctypes.data_as(
            ctypes.c_void_p))
"""

GOOD_NAMED_BUFFER = """
    import ctypes
    import numpy as np

    def call(lib, x):
        buf = np.ascontiguousarray(x)
        lib.hvd_pack(buf.ctypes.data_as(ctypes.c_void_p))
"""


def test_native_lifetime_inline_temporary_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_INLINE_TEMPORARY,
                       "native-lifetime")
    msgs = "\n".join(f.message for f in fs)
    assert "unnamed temporary" in msgs, fs


def test_native_lifetime_named_buffer_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_NAMED_BUFFER,
                         "native-lifetime") == []


BAD_TEMP_CALLBACK = """
    import ctypes

    ON_IDLE = ctypes.CFUNCTYPE(None)

    def install(lib, f):
        lib.hvd_set_idle(ON_IDLE(f))
"""

GOOD_OWNED_CALLBACK = """
    import ctypes

    ON_IDLE = ctypes.CFUNCTYPE(None)

    class Hooks:
        def install(self, lib, f):
            self._cb = ON_IDLE(f)
            lib.hvd_set_idle(self._cb)
"""


def test_native_lifetime_temp_callback_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_TEMP_CALLBACK, "native-lifetime")
    msgs = "\n".join(f.message for f in fs)
    assert "CFUNCTYPE" in msgs, fs


def test_native_lifetime_owned_callback_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_OWNED_CALLBACK,
                         "native-lifetime") == []


BAD_ARENA_CACHE = """
    import ctypes

    class Ring:
        def __init__(self):
            self._ptr_cache = {}

        def send(self, arena, n):
            buf = arena.ensure(n)
            key = ("send", n)
            c = self._ptr_cache.get(key)
            if c is None:
                c = buf.ctypes.data_as(ctypes.c_void_p)
                self._ptr_cache[key] = c
            return c
"""

GOOD_ARENA_CACHE = """
    import ctypes

    class Ring:
        def __init__(self):
            self._ptr_cache = {}

        def send(self, arena, n):
            buf = arena.ensure(n)
            key = ("send", n, arena.generation)
            c = self._ptr_cache.get(key)
            if c is None:
                c = buf.ctypes.data_as(ctypes.c_void_p)
                self._ptr_cache[key] = c
            return c
"""


def test_native_lifetime_arena_cache_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_ARENA_CACHE, "native-lifetime")
    msgs = "\n".join(f.message for f in fs)
    assert "generation" in msgs, fs


def test_native_lifetime_generation_keyed_cache_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_ARENA_CACHE,
                         "native-lifetime") == []


GOOD_REACTOR_IDLE_CACHE = """
    import ctypes

    ON_IDLE = ctypes.CFUNCTYPE(None)

    class Fanout:
        def __init__(self):
            self._on_idle_c = None

        def gather(self, lib, f):
            if self._on_idle_c is None:
                self._on_idle_c = ON_IDLE(f)
            lib.hvd_gather_frames_batched(self._on_idle_c)
"""


def test_native_lifetime_reactor_idle_cache_clean(tmp_path):
    """The batched reactor's lazily-built, self-owned ON_IDLE thunk
    (the _NativeFanout.gather_into shape): cached on the instance, so
    the callback object outlives the native call that fires it — the
    analyzer must accept it, only temporaries fire."""
    assert _lint_snippet(tmp_path, GOOD_REACTOR_IDLE_CACHE,
                         "native-lifetime") == []


# -- real-tree mutation gates ----------------------------------------------
# Each test reintroduces one shipped (or would-ship) bug into a scratch
# copy of the package and asserts the analyzer re-finds it — the proof
# that the gate bites on the real tree, not just on fixtures. The
# mutated shapes are the three historical bug classes from the module
# docstring of tools/hvdlint/thread_ownership.py plus the three true
# positives this analyzer found (and this PR fixed) in the tree.

@pytest.fixture(scope="module")
def mut_tree(tmp_path_factory):
    dst = str(tmp_path_factory.mktemp("mut") / "horovod_tpu")
    shutil.copytree(os.path.join(REPO, "horovod_tpu"), dst,
                    ignore=shutil.ignore_patterns("__pycache__"))
    return dst


def _mutate_and_lint(tree, rel, transform, analyzer):
    full = os.path.join(tree, rel)
    with open(full) as f:
        orig = f.read()
    mutated = transform(orig)
    assert mutated != orig, f"mutation anchor vanished in {rel}"
    with open(full, "w") as f:
        f.write(mutated)
    try:
        return lint_paths([tree], [analyzer])
    finally:
        with open(full, "w") as f:
            f.write(orig)


def test_mutation_on_arrivals_double_read_refound(mut_tree):
    """Historical bug #1: the _on_arrivals hook read twice while
    attach_trace can rebind it between the reads."""
    def revert(s):
        old = ("        on_arrivals = self._on_arrivals\n"
               "        track = (expect_tag == TAG_REQUESTS\n"
               "                 and on_arrivals is not None)")
        assert old in s
        s = s.replace(old,
                      "        track = (expect_tag == TAG_REQUESTS\n"
                      "                 and self._on_arrivals "
                      "is not None)", 1)
        return s.replace("on_arrivals(arrivals)",
                         "self._on_arrivals(arrivals)")
    fs = _mutate_and_lint(mut_tree, "common/controller.py", revert,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "controller.Controller._on_arrivals" in msgs \
        and "capture it into a local once" in msgs, fs


def test_mutation_mark_done_order_swap_refound(mut_tree):
    """Historical bug #2: mark_done publishing the status gate before
    the output payload that lock-free wait() keys on."""
    def swap(s):
        old = ("            self._outputs[handle] = output\n"
               "            self._results[handle] = status")
        assert old in s
        return s.replace(
            old,
            "            self._results[handle] = status\n"
            "            self._outputs[handle] = output", 1)
    fs = _mutate_and_lint(mut_tree, "common/tensor_table.py", swap,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "tensor_table.HandleManager._results" in msgs \
        and "before storing payload" in msgs, fs


def test_mutation_bucket_sets_in_place_refound(mut_tree):
    """Historical bug #3: note_bucket_names mutating the set in place
    instead of snapshot-swapping a fresh frozenset."""
    def aug(s):
        old = "        self._bucket_sets = cur | {s}"
        assert old in s
        return s.replace(old, "        self._bucket_sets |= {s}", 1)
    fs = _mutate_and_lint(mut_tree, "common/runtime.py", aug,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "runtime.Runtime._bucket_sets" in msgs, fs


def test_mutation_coordinator_pragma_strip_refound(mut_tree):
    """The ResponseCache audit is load-bearing: stripping the owned-by
    pragmas must re-flag the fields, proving the clean tree is clean
    because of reviewed justifications, not analyzer blindness."""
    def strip(s):
        return "".join(ln for ln in s.splitlines(True)
                       if "hvdlint: owned-by" not in ln)
    fs = _mutate_and_lint(mut_tree, "common/coordinator.py", strip,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "coordinator.ResponseCache" in msgs, fs


def test_mutation_native_inline_temp_refound(mut_tree):
    """native-lifetime real-tree gate: inlining pack()'s output buffer
    into the call expression must be re-found."""
    def inline(s):
        assert "out.ctypes.data_as" in s
        return s.replace("out.ctypes.data_as",
                         "np.empty(total, dtype).ctypes.data_as", 1)
    fs = _mutate_and_lint(mut_tree, "native.py", inline,
                          "native-lifetime")
    msgs = "\n".join(f.message for f in fs)
    assert "unnamed temporary" in msgs, fs


def test_mutation_steady_generation_strip_refound(mut_tree):
    """native-lifetime real-tree gate: dropping the arena generation
    from steady's iovec cache keys must be re-found (ensure()
    reallocates on growth; a stale pointer bundle writes freed
    memory)."""
    def strip(s):
        assert s.count("scratch.generation") >= 2
        return s.replace("scratch.generation", "0")
    fs = _mutate_and_lint(mut_tree, "common/steady.py", strip,
                          "native-lifetime")
    msgs = "\n".join(f.message for f in fs)
    assert "generation" in msgs, fs


def test_regression_stall_inspector_warned_lock(mut_tree):
    """True positive #1 fixed by this analyzer: StallInspector._warned
    was mutated from the caller thread with no lock while the
    background sweep also writes it. Reverting the lock re-fires."""
    def unlock(s):
        old = ("        with self._warned_lock:\n"
               "            self._warned.discard(name)")
        assert old in s
        return s.replace(old, "        self._warned.discard(name)", 1)
    fs = _mutate_and_lint(mut_tree, "common/coordinator.py", unlock,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "coordinator.StallInspector._warned" in msgs, fs


def test_regression_socket_ops_hook_capture(mut_tree):
    """True positive #2: the ring's metric hook was tested then used
    (two reads) while attach_metrics can rebind it between them."""
    def revert(s):
        old = ("            m_link = self._m_ring_link_bytes\n"
               "            if self._ring is not None and m_link "
               "is not None:\n"
               "                self._ring.m_link_bytes = m_link")
        assert old in s
        return s.replace(
            old,
            "            if self._ring is not None and \\\n"
            "                    self._m_ring_link_bytes "
            "is not None:\n"
            "                self._ring.m_link_bytes = "
            "self._m_ring_link_bytes", 1)
    fs = _mutate_and_lint(mut_tree, "ops/socket_ops.py", revert,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "socket_ops.SocketBackend._m_ring_link_bytes" in msgs, fs


def test_regression_tenant_lane_handoff_lock(mut_tree):
    """True positive #3: teardown handed _tenant_lane off with no lock
    while the scheduler's attach path rebinds it from its own
    thread. Reverting the lane lock re-fires."""
    def unlock(s):
        old = ("        with self._lane_lock:\n"
               "            lane, self._tenant_lane = "
               "self._tenant_lane, None\n"
               "            self._lane_closed = True")
        assert old in s
        return s.replace(
            old,
            "        lane, self._tenant_lane = "
            "self._tenant_lane, None\n"
            "        self._lane_closed = True", 1)
    fs = _mutate_and_lint(mut_tree, "common/runtime.py", unlock,
                          "thread-ownership")
    msgs = "\n".join(f.message for f in fs)
    assert "runtime.Runtime._tenant_lane" in msgs, fs


# -- jax_compat -------------------------------------------------------------
# Three checks, each with a known-bad fixture that must fire and a
# known-good twin that must stay silent, plus real-tree mutation gates
# reverting the shim-ported idiom (the exact rot that kept the 52-test
# shard_map family red from PR 3 to PR 20).

def test_jax_compat_floor_mirrors_shim():
    """The analyzer may not import the package under analysis, so it
    carries the supported-jax floor as a literal — this is the bolt
    keeping the two declarations (and the pyproject pin) one value."""
    from tools.hvdlint import jax_compat
    from horovod_tpu.compat import jaxshim
    assert jax_compat.SUPPORTED_FLOOR == jaxshim.SUPPORTED_JAX_FLOOR


# check 1: version-ranged API table — removed symbols...
BAD_JAX_REMOVED_API = """
    from jax.experimental.maps import Mesh

    def build(devs):
        return Mesh(devs, ("data",))
"""

# ...function-scoped imports (the tree's dominant jax idiom) count too
BAD_JAX_DEFERRED_TREE_MAP = """
    def halve(tree):
        import jax
        return jax.tree_map(lambda x: x / 2, tree)
"""

# ...and symbols introduced ABOVE the supported floor are rot as well
BAD_JAX_ABOVE_FLOOR = """
    import jax

    def size(axis):
        return jax.lax.axis_size(axis)
"""

GOOD_JAX_VIA_SHIM = """
    from horovod_tpu.compat import jaxshim

    def run(f, devs):
        mesh = jaxshim.make_mesh({"data": 4}, devices=devs)
        spec = jaxshim.partition_spec("data")
        return jaxshim.shard_map(f, mesh=mesh, in_specs=spec,
                                 out_specs=spec)
"""


def test_jax_compat_removed_api_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_JAX_REMOVED_API, "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "jax.experimental.maps" in msgs and "removed" in msgs, fs


def test_jax_compat_deferred_import_fires(tmp_path):
    """jax.tree_map reached through a function-body import: the
    analyzer's whole-file import overlay must still resolve it."""
    fs = _lint_snippet(tmp_path, BAD_JAX_DEFERRED_TREE_MAP,
                       "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "jax.tree_map" in msgs \
        and "jax.tree_util.tree_map" in msgs, fs


def test_jax_compat_above_floor_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_JAX_ABOVE_FLOOR, "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "jax.lax.axis_size" in msgs \
        and "above the supported floor" in msgs \
        and "jaxshim.axis_size" in msgs, fs


def test_jax_compat_shim_usage_is_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_JAX_VIA_SHIM,
                         "jax_compat") == []


# check 2: mesh/sharding construction must route through the shim
BAD_DIRECT_CONSTRUCTION = """
    def build(devs):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        mesh = Mesh(devs, ("data",))
        return NamedSharding(mesh, PartitionSpec("data"))
"""

GOOD_SHIM_CONSTRUCTION = """
    from horovod_tpu.compat import jaxshim

    def build(devs):
        mesh = jaxshim.make_mesh({"data": 2, "model": 2},
                                 devices=devs)
        spec = jaxshim.partition_spec("data", "model")
        return jaxshim.named_sharding(mesh, spec)
"""


def test_jax_compat_direct_construction_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_DIRECT_CONSTRUCTION, "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "direct jax.sharding.Mesh construction" in msgs \
        and "make_mesh" in msgs, fs
    assert "direct jax.sharding.NamedSharding construction" in msgs \
        and "named_sharding" in msgs, fs


def test_jax_compat_shim_construction_is_clean(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_SHIM_CONSTRUCTION,
                         "jax_compat") == []


def test_jax_compat_shim_module_itself_exempt(tmp_path):
    """The one sanctioned call site: a module named jaxshim.py may
    touch the version-ranged API directly — that's its whole job."""
    code = """
        import jax

        def make_raw_mesh(devs, names):
            return jax.sharding.Mesh(devs, names)
    """
    assert _lint_snippet(tmp_path, code, "jax_compat",
                         name="jaxshim.py") == []


# check 3: PartitionSpec axis names must be axes of a mesh in scope
BAD_STALE_AXIS = """
    from horovod_tpu.compat import jaxshim

    def build(devs):
        mesh = jaxshim.make_mesh({"data": 2, "model": 2},
                                 devices=devs)
        return jaxshim.named_sharding(
            mesh, jaxshim.partition_spec("data", "modle"))
"""

GOOD_UNPROVABLE_MESH_SKIPPED = """
    from horovod_tpu.compat import jaxshim

    def apply(mesh):
        # mesh arrives as a parameter: axes statically unknown, so
        # the check must skip rather than guess
        return jaxshim.partition_spec("whatever")
"""


def test_jax_compat_stale_axis_fires(tmp_path):
    fs = _lint_snippet(tmp_path, BAD_STALE_AXIS, "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "'modle'" in msgs and "silently replicates" in msgs, fs
    assert "'data'" not in msgs.split("known axes")[0], \
        "the coherent axis must not be flagged"


def test_jax_compat_unprovable_mesh_is_skipped(tmp_path):
    assert _lint_snippet(tmp_path, GOOD_UNPROVABLE_MESH_SKIPPED,
                         "jax_compat") == []


# real-tree gates: reverting a shim-ported file to the removed-API
# idiom must trip jax_compat on the actual package, proving the green
# tree is green because the port is complete, not because the
# analyzer is blind to the shipped code.

def test_mutation_axis_size_revert_refound(mut_tree):
    """spmd.axis_size reverted to the above-floor jax.lax.axis_size
    spelling (the exact AttributeError that killed the family on
    0.4.37)."""
    def revert(s):
        old = "    return jaxshim.axis_size(axis)"
        assert old in s
        return s.replace(old, "    return jax.lax.axis_size(axis)", 1)
    fs = _mutate_and_lint(mut_tree, "spmd/__init__.py", revert,
                          "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "jax.lax.axis_size" in msgs \
        and "above the supported floor" in msgs, fs


def test_mutation_shard_map_revert_refound(mut_tree):
    """ring_attention's shard_map reverted to the top-level jax
    spelling that only exists from 0.5.0."""
    def revert(s):
        old = "partial(jaxshim.shard_map, mesh=mesh"
        assert old in s
        return s.replace(old, "partial(jax.shard_map, mesh=mesh", 1)
    fs = _mutate_and_lint(mut_tree, "parallel/ring_attention.py",
                          revert, "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "jax.shard_map" in msgs \
        and "compat.jaxshim.shard_map" in msgs, fs


def test_mutation_direct_sharding_revert_refound(mut_tree):
    """spmd's named_sharding helper reverted to constructing
    jax.sharding.NamedSharding directly."""
    def revert(s):
        old = ("    return jaxshim.named_sharding("
               "mesh, jaxshim.partition_spec(axis))")
        assert old in s
        return s.replace(
            old,
            "    return jax.sharding.NamedSharding("
            "mesh, jaxshim.partition_spec(axis))", 1)
    fs = _mutate_and_lint(mut_tree, "spmd/__init__.py", revert,
                          "jax_compat")
    msgs = "\n".join(f.message for f in fs)
    assert "direct jax.sharding.NamedSharding construction" in msgs, fs


# -- the --changed cache ----------------------------------------------------

def _seed_pkg(tmp_path):
    pkg = tmp_path / "cpkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "import os\nX = os.environ.get('HOROVOD_CACHE_PROBE')\n")
    (pkg / "b.py").write_text("Y = 1\n")
    return pkg


def test_cache_replays_when_nothing_changed(tmp_path):
    from tools.hvdlint import cache as hcache
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    findings = lint_paths([str(pkg)], ["knobs"])
    assert findings, "seed must produce a finding"
    hcache.save([str(pkg)], ["knobs"], cf, findings)
    replay = hcache.load([str(pkg)], ["knobs"], cf)
    assert replay is not None
    assert [f.to_dict() for f in replay] == \
        [f.to_dict() for f in findings]


def test_cache_survives_mtime_touch(tmp_path):
    from tools.hvdlint import cache as hcache
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    hcache.save([str(pkg)], ["knobs"], cf,
                lint_paths([str(pkg)], ["knobs"]))
    # mtime bump, identical content: sha1 fallback must still replay
    a = pkg / "a.py"
    os.utime(a, (os.path.getmtime(a) + 10,) * 2)
    assert hcache.load([str(pkg)], ["knobs"], cf) is not None


def test_cache_invalidated_by_edit(tmp_path):
    from tools.hvdlint import cache as hcache
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    hcache.save([str(pkg)], ["knobs"], cf,
                lint_paths([str(pkg)], ["knobs"]))
    (pkg / "b.py").write_text("Y = 2\n")
    assert hcache.load([str(pkg)], ["knobs"], cf) is None


def test_cache_invalidated_by_rename(tmp_path):
    from tools.hvdlint import cache as hcache
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    hcache.save([str(pkg)], ["knobs"], cf,
                lint_paths([str(pkg)], ["knobs"]))
    os.rename(pkg / "b.py", pkg / "b2.py")
    assert hcache.load([str(pkg)], ["knobs"], cf) is None


def test_cache_invalidated_by_pragma_change(tmp_path):
    """A pragma edit changes no code object but DOES change findings —
    it must invalidate like any other content change."""
    from tools.hvdlint import cache as hcache
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    hcache.save([str(pkg)], ["knobs"], cf,
                lint_paths([str(pkg)], ["knobs"]))
    a = pkg / "a.py"
    a.write_text(a.read_text() + "# hvdlint: disable=knobs -- probe\n")
    assert hcache.load([str(pkg)], ["knobs"], cf) is None


def test_cache_invalidated_by_analyzer_selection(tmp_path):
    from tools.hvdlint import cache as hcache
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    hcache.save([str(pkg)], ["knobs"], cf,
                lint_paths([str(pkg)], ["knobs"]))
    assert hcache.load([str(pkg)], ["knobs", "teardown"], cf) is None


def test_cache_invalidated_by_api_table_edit(tmp_path):
    """jax_compat's API_TABLE is data, but it IS the analyzer: adding
    a row must change the tool stamp (so a --changed replay re-runs),
    and load() must key on that stamp."""
    from tools.hvdlint import cache as hcache
    scratch = str(tmp_path / "hvdlint")
    shutil.copytree(os.path.join(REPO, "tools", "hvdlint"), scratch,
                    ignore=shutil.ignore_patterns("__pycache__"))
    before = hcache._tool_stamp(scratch)
    assert before == hcache._tool_stamp(), \
        "scratch copy must stamp identically to the shipped suite"
    jc = os.path.join(scratch, "jax_compat.py")
    with open(jc) as f:
        src = f.read()
    anchor = "API_TABLE: Dict[str, Tuple[Optional[tuple], " \
             "Optional[tuple], str]] = {"
    assert anchor in src
    with open(jc, "w") as f:
        f.write(src.replace(
            anchor,
            anchor + '\n    "jax.experimental.probe": '
                     '(None, (0, 9, 0), "nothing"),', 1))
    after = hcache._tool_stamp(scratch)
    assert after != before, "API-table edit must change the tool stamp"

    # and the load path enforces it: a cache saved under another
    # suite build is a miss, never a replay
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "c.json")
    hcache.save([str(pkg)], ["knobs"], cf,
                lint_paths([str(pkg)], ["knobs"]))
    assert hcache.load([str(pkg)], ["knobs"], cf) is not None
    with open(cf) as f:
        payload = json.load(f)
    payload["tool"] = after
    with open(cf, "w") as f:
        json.dump(payload, f)
    assert hcache.load([str(pkg)], ["knobs"], cf) is None


def test_cache_cli_end_to_end(tmp_path):
    pkg = _seed_pkg(tmp_path)
    cf = str(tmp_path / "cli.json")
    cmd = [sys.executable, "-m", "tools.hvdlint", str(pkg),
           "--changed", "--cache-file", cf]
    first = subprocess.run(cmd, cwd=REPO, capture_output=True,
                           text=True, timeout=120)
    assert first.returncode == 1 and os.path.exists(cf)
    second = subprocess.run(cmd, cwd=REPO, capture_output=True,
                            text=True, timeout=120)
    assert second.returncode == 1
    assert second.stdout == first.stdout


# -- flight-recorder hygiene ------------------------------------------------

def test_no_stray_flight_dumps_at_repo_root():
    """In-process aborts used to dump hvd-flight-*.jsonl into the CWD
    (the checkout, under pytest). tests/conftest.py now defaults
    HOROVOD_TPU_FLIGHT_DIR to a throwaway dir; a stray file here means
    some path bypassed it."""
    strays = glob.glob(os.path.join(REPO, "hvd-flight-*.jsonl"))
    assert strays == [], strays
