"""NDArray-protocol double of the mxnet surface horovod_tpu.mxnet uses.

mxnet ships no TPU wheel and isn't in the image, but the adapter's
contract is pure protocol: ``.asnumpy()``, ``mx.nd.array``, slice
assignment, gluon ``Trainer``/``Parameter`` shapes. This module
implements exactly that surface so the adapter code actually EXECUTES
under a real multi-process world (scenario ``mxnet`` in
tests/mp_scenarios.py) instead of existing as never-run staging code.

Install with ``fake_mxnet.install()`` before importing
horovod_tpu.mxnet.
"""

from __future__ import annotations

import sys
import types

import numpy as np


class NDArray:
    def __init__(self, data, dtype=None):
        self._np = np.array(data, dtype=dtype)

    @property
    def dtype(self):
        return self._np.dtype

    @property
    def shape(self):
        return self._np.shape

    def asnumpy(self) -> np.ndarray:
        return self._np

    def __setitem__(self, key, value):
        v = value.asnumpy() if isinstance(value, NDArray) \
            else np.asarray(value)
        self._np[key] = v

    def __repr__(self):
        return f"FakeNDArray({self._np!r})"


def _nd_array(data, dtype=None, ctx=None):
    if isinstance(data, NDArray):
        data = data.asnumpy()
    return NDArray(np.asarray(data), dtype=dtype)


class DeferredInitializationError(RuntimeError):
    pass


class Parameter:
    """gluon Parameter double: deferred init until initialize()."""

    def __init__(self, name, data, grad=None, grad_req="write",
                 deferred=False):
        self.name = name
        self.grad_req = grad_req
        self._data = NDArray(data)
        self._grad = NDArray(grad if grad is not None
                             else np.zeros_like(np.asarray(data)))
        self._deferred = deferred

    def initialize(self):
        self._deferred = False

    def data(self) -> NDArray:
        if self._deferred:
            raise DeferredInitializationError(self.name)
        return self._data

    def list_grad(self):
        return [self._grad]


class Trainer:
    """gluon Trainer double: only what DistributedTrainer extends."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        assert kvstore is None, "horovod trainer must disable kvstore"
        if hasattr(params, "values"):
            params = list(params.values())
        self._params = list(params)
        self._optimizer = optimizer
        self._optimizer_params = optimizer_params
        self._scale = 1.0


def install() -> None:
    mx = types.ModuleType("mxnet")
    mx.__version__ = "0.0-fake"
    nd = types.ModuleType("mxnet.nd")
    nd.array = _nd_array
    nd.NDArray = NDArray
    gluon = types.ModuleType("mxnet.gluon")
    gluon.Trainer = Trainer
    gluon.Parameter = Parameter
    mx.nd = nd
    mx.gluon = gluon
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = nd
    sys.modules["mxnet.gluon"] = gluon
