"""compat/jaxshim — the one sanctioned JAX version boundary.

The wrappers re-read ``jax.__version__`` per call (never cached at
import) precisely so these tests can mock a FUTURE release and prove
the gate flips to the new spelling before that release exists: the
whole point of the shim is that the next jax migration is a
one-module diff, and that claim is only testable against versions we
don't have installed.
"""

import numpy as np
import pytest

import jax

from horovod_tpu.compat import jaxshim

pytestmark = pytest.mark.fast


# -- version parsing --------------------------------------------------------

@pytest.mark.parametrize("raw,want", [
    ("0.4.37", (0, 4, 37)),
    ("0.5.0", (0, 5, 0)),
    ("0.7.0.dev20260101+abc123", (0, 7, 0)),
    ("0.6", (0, 6)),
    ("1.0.0rc1", (1, 0, 0)),
    ("garbage", (0,)),
])
def test_parse_version(raw, want):
    assert jaxshim._parse_version(raw) == want


def test_jax_version_reads_live_not_cached(monkeypatch):
    monkeypatch.setattr(jax, "__version__", "0.9.9")
    assert jaxshim.jax_version() == (0, 9, 9)
    monkeypatch.setattr(jax, "__version__", "0.4.37")
    assert jaxshim.jax_version() == (0, 4, 37)


# -- the shard_map version gate --------------------------------------------

def test_shard_map_future_jax_takes_top_level_check_vma(monkeypatch):
    """On a mocked future release the gate must call the top-level
    ``jax.shard_map`` with the ``check_vma`` spelling — without that
    release being installed."""
    seen = {}

    def fake_shard_map(body, mesh=None, in_specs=None, out_specs=None,
                       **kw):
        seen.update(kw, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, body=body)
        return "future-mapped"

    monkeypatch.setattr(jax, "__version__", "0.9.0")
    monkeypatch.setattr(jax, "shard_map", fake_shard_map,
                        raising=False)
    out = jaxshim.shard_map(lambda x: x, mesh="M", in_specs="I",
                            out_specs="O")
    assert out == "future-mapped"
    assert seen["mesh"] == "M" and seen["in_specs"] == "I" \
        and seen["out_specs"] == "O"
    assert seen["check_vma"] is False and "check_rep" not in seen


def test_shard_map_floor_jax_takes_experimental_check_rep(monkeypatch):
    """At the supported floor the gate must stay on
    ``jax.experimental.shard_map`` with ``check_rep``."""
    from jax.experimental import shard_map as esm
    seen = {}

    def fake(body, mesh=None, in_specs=None, out_specs=None, **kw):
        seen.update(kw)
        return "floor-mapped"

    monkeypatch.setattr(jax, "__version__", "0.4.37")
    monkeypatch.setattr(esm, "shard_map", fake)
    assert jaxshim.shard_map(lambda x: x, mesh="M", in_specs="I",
                             out_specs="O") == "floor-mapped"
    assert seen["check_rep"] is False and "check_vma" not in seen


def test_shard_map_future_without_top_level_falls_back(monkeypatch):
    """The feature probe is the net under the version gate: a release
    that *claims* >= 0.5 but ships no top-level shard_map (the 0.4.35
    deprecation-alias incident) must still resolve the experimental
    spelling instead of raising."""
    from jax.experimental import shard_map as esm
    seen = {}

    def fake(body, mesh=None, in_specs=None, out_specs=None, **kw):
        seen.update(kw)
        return "probed-fallback"

    monkeypatch.setattr(jax, "__version__", "0.9.0")
    monkeypatch.delattr(jax, "shard_map", raising=False)
    monkeypatch.setattr(esm, "shard_map", fake)
    assert jaxshim.shard_map(lambda x: x, mesh="M", in_specs="I",
                             out_specs="O") == "probed-fallback"
    assert seen["check_rep"] is False


def test_shard_map_executes_on_running_jax():
    """Whatever spelling the gate picked for the INSTALLED jax must
    actually trace: one psum over a real mesh (conftest forces an
    8-device host platform)."""
    mesh = jaxshim.make_mesh()
    n = mesh.devices.size
    spec = jaxshim.partition_spec("data")

    def body(x):
        return jax.lax.psum(x, "data")

    y = jax.jit(jaxshim.shard_map(body, mesh=mesh, in_specs=spec,
                                  out_specs=spec))(
        np.arange(n, dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(y), np.full(n, np.arange(n).sum(), np.float32))


# -- axis_size gate ---------------------------------------------------------

def test_axis_size_prefers_native_spelling(monkeypatch):
    monkeypatch.setattr(jax.lax, "axis_size", lambda a: 7,
                        raising=False)
    assert jaxshim.axis_size("model") == 7


def test_axis_size_floor_falls_back_to_psum(monkeypatch):
    """Below 0.5 there is no jax.lax.axis_size: the shim must lower
    to the psum(1, axis) constant-fold instead of AttributeError."""
    seen = {}
    monkeypatch.delattr(jax.lax, "axis_size", raising=False)
    monkeypatch.setattr(
        jax.lax, "psum",
        lambda v, a: seen.setdefault("call", (v, a)) and 3 or 3)
    assert jaxshim.axis_size("model") == 3
    assert seen["call"] == (1, "model")


# -- mesh construction ------------------------------------------------------

def test_make_mesh_default_is_one_data_axis():
    mesh = jaxshim.make_mesh()
    assert mesh.axis_names == ("data",)
    assert mesh.devices.size == len(jax.devices())


def test_make_mesh_infers_minus_one_axis():
    mesh = jaxshim.make_mesh({"data": -1, "model": 1})
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape["data"] == len(jax.devices())


def test_make_mesh_rejects_bad_product():
    with pytest.raises(ValueError, match="devices"):
        jaxshim.make_mesh({"data": len(jax.devices()) + 1})
    with pytest.raises(ValueError, match="-1"):
        jaxshim.make_mesh({"a": -1, "b": -1})


def test_named_sharding_coerces_specs():
    mesh = jaxshim.make_mesh()
    for spec in ("data", ("data", None),
                 jaxshim.partition_spec("data")):
        s = jaxshim.named_sharding(mesh, spec)
        assert s.spec[0] == "data"
