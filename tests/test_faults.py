"""Unit tests for the fail-fast building blocks: heartbeat/abort wire
frames, the connect() backoff schedule, and the fault-injection spec
parser (TPU-native extensions; the reference has no liveness layer —
see docs/fault_tolerance.md)."""

import pytest

from horovod_tpu.common import faults, heartbeat
from horovod_tpu.common.network import backoff_delays
from horovod_tpu.common.status import (
    HorovodInternalError, Status, WorldAbortedError,
)


class TestHeartbeatFrames:
    def test_ping_roundtrip(self):
        payload = heartbeat.encode_ping(7, 123456789)
        assert heartbeat.decode_ping(payload) == (7, 123456789)

    def test_ping_large_sequence(self):
        # seq is a u64: a long-lived world must never wrap it
        payload = heartbeat.encode_ping(0, 2 ** 63)
        assert heartbeat.decode_ping(payload) == (0, 2 ** 63)

    def test_ping_rejects_wrong_length(self):
        with pytest.raises(ValueError):
            heartbeat.decode_ping(b"\x00" * 5)
        with pytest.raises(ValueError):
            heartbeat.decode_ping(heartbeat.encode_ping(1, 1) + b"x")

    def test_abort_roundtrip(self):
        payload = heartbeat.encode_abort(3, "rank 3 lost its host")
        assert heartbeat.decode_abort(payload) == (
            3, "rank 3 lost its host")

    def test_abort_unicode_cause(self):
        payload = heartbeat.encode_abort(1, "死 ✂ cause")
        assert heartbeat.decode_abort(payload) == (1, "死 ✂ cause")

    def test_abort_tolerates_truncated_cause(self):
        # a dying sender may not flush the whole frame; the origin
        # rank must still be recoverable from the fixed header
        payload = heartbeat.encode_abort(5, "some long cause text")
        origin, cause = heartbeat.decode_abort(payload[:12])
        assert origin == 5
        assert cause == "some"

    def test_abort_rejects_short_header(self):
        with pytest.raises(ValueError):
            heartbeat.decode_abort(b"\x01\x02")

    def test_unknown_origin_abort_roundtrip(self):
        # origin -1 = "unknown rank" (ambiguous mid-frame stall)
        payload = heartbeat.encode_abort(-1, "stalled mid-frame")
        assert heartbeat.decode_abort(payload) == (
            -1, "stalled mid-frame")


class TestBackoffSchedule:
    def test_deterministic_schedule_without_jitter(self):
        delays = backoff_delays(base=0.05, cap=1.0, factor=2.0,
                                jitter=0.0)
        got = [next(delays) for _ in range(8)]
        assert got == [0.05, 0.1, 0.2, 0.4, 0.8, 1.0, 1.0, 1.0]

    def test_cap_is_respected_with_full_jitter(self):
        delays = backoff_delays(base=0.1, cap=0.5, factor=3.0,
                                jitter=0.25, rng=lambda: 1.0)
        got = [next(delays) for _ in range(6)]
        assert max(got) <= 0.5 * 1.25 + 1e-9

    def test_jitter_bounds(self):
        lo = backoff_delays(base=0.2, cap=1.0, jitter=0.25,
                            rng=lambda: 0.0)
        hi = backoff_delays(base=0.2, cap=1.0, jitter=0.25,
                            rng=lambda: 1.0)
        assert next(lo) == pytest.approx(0.2 * 0.75)
        assert next(hi) == pytest.approx(0.2 * 1.25)

    def test_two_streams_with_distinct_rngs_diverge(self):
        # the anti-stampede property: two ranks retrying in lockstep
        # must not sleep identically
        import random
        a = backoff_delays(rng=random.Random(1).random)
        b = backoff_delays(rng=random.Random(2).random)
        assert [next(a) for _ in range(4)] != [
            next(b) for _ in range(4)]


class TestFaultSpec:
    def teardown_method(self):
        faults.clear()

    def test_parse_single_kill(self):
        (f,) = faults.parse_spec("rank=1:kill:cycle=40")
        assert (f.action, f.rank, f.at_cycle) == ("kill", 1, 40)
        assert f.at_op is None and not f.fired

    def test_parse_multi_directive(self):
        fs = faults.parse_spec(
            "rank=1:kill:cycle=40; rank=2:delay:op=3:ms=50")
        assert [f.action for f in fs] == ["kill", "delay"]
        assert fs[1].at_op == 3 and fs[1].ms == 50.0

    def test_parse_hang_and_sever_args(self):
        fs = faults.parse_spec(
            "hang:cycle=5:seconds=2.5;sever:op=1:target=3")
        assert fs[0].seconds == 2.5 and fs[0].rank is None
        assert fs[1].target == 3

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            faults.parse_spec("explode:cycle=1")

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError):
            faults.parse_spec("kill:cycle=1:when=later")

    def test_missing_trigger_rejected(self):
        with pytest.raises(ValueError):
            faults.parse_spec("kill:rank=1")

    def test_double_trigger_rejected(self):
        with pytest.raises(ValueError):
            faults.parse_spec("kill:cycle=1:op=2")

    def test_install_arms_plan_and_clear_disarms(self):
        faults.install("delay", at_op=2, ms=1.0)
        # module-level plan is live (runtime ticks consult it)
        assert faults._PLAN and faults._PLAN[0].action == "delay"
        faults.clear()
        assert faults._PLAN is None

    def test_parse_delay_count(self):
        (f,) = faults.parse_spec("rank=2:delay:cycle=10:ms=40:count=8")
        assert (f.action, f.count, f.ms) == ("delay", 8, 40.0)

    def test_count_only_for_delay(self):
        # a fired kill/exit never returns; repeating them is a spec bug
        with pytest.raises(ValueError):
            faults.parse_spec("kill:cycle=1:count=2")
        with pytest.raises(ValueError):
            faults.parse_spec("delay:cycle=1:count=0")

    def test_repeating_delay_fires_count_times_then_spends(self):
        """count=K turns the one-shot delay into a sustained straggler
        (K consecutive trigger hits) — the lever the world-trace mp
        test uses to pin last-arriver attribution on one rank."""

        class _Ctl:
            rank = 0

        class _Rt:
            controller = _Ctl()

        try:
            f = faults.install("delay", at_cycle=3, ms=0.0, count=2)
            faults.tick_cycle(_Rt(), 2)
            assert f.count == 2 and not f.fired  # below trigger
            faults.tick_cycle(_Rt(), 3)
            assert f.count == 1 and not f.fired  # first hit
            faults.tick_cycle(_Rt(), 4)
            assert f.fired                       # second hit: spent
            faults.tick_cycle(_Rt(), 5)          # no-op thereafter
        finally:
            faults.clear()


class TestHeartbeatConfig:
    def test_env_knobs_round_trip(self, monkeypatch):
        from horovod_tpu.common.config import Config

        monkeypatch.setenv("HOROVOD_HEARTBEAT_INTERVAL", "0.25")
        monkeypatch.setenv("HOROVOD_HEARTBEAT_TIMEOUT", "7.5")
        cfg = Config.from_env()
        assert cfg.heartbeat_interval_s == 0.25
        assert cfg.heartbeat_timeout_s == 7.5

    def test_defaults_enable_detection(self):
        from horovod_tpu.common.config import Config

        cfg = Config()
        assert cfg.heartbeat_timeout_s > cfg.heartbeat_interval_s > 0


class TestDrainAbortNotice:
    """_drain_abort: a rank whose local blame came from an anonymous
    transport error must defer to an authoritative ABORT notice
    already queued on (or about to reach) its control channels, so a
    cascading teardown converges on one origin world-wide."""

    def _pair(self):
        import socket as _socket
        from horovod_tpu.common.network import Channel

        a, b = _socket.socketpair()
        return Channel(a), Channel(b)

    def test_finds_queued_abort(self):
        from horovod_tpu.common.controller import TAG_ABORT, _drain_abort

        mine, peer = self._pair()
        peer.send(heartbeat.encode_abort(3, "rank 3 fell over"),
                  TAG_ABORT)
        assert _drain_abort({3: mine}, 0.0) == (3, "rank 3 fell over")

    def test_skips_pings_before_abort(self):
        from horovod_tpu.common.controller import (
            TAG_ABORT, TAG_PING, _drain_abort,
        )

        mine, peer = self._pair()
        peer.send(heartbeat.encode_ping(2, 1), TAG_PING)
        peer.send(heartbeat.encode_abort(2, "died"), TAG_ABORT)
        assert _drain_abort({2: mine}, 0.0) == (2, "died")

    def test_empty_and_dead_channels_return_none(self):
        from horovod_tpu.common.controller import _drain_abort

        mine, peer = self._pair()
        assert _drain_abort({1: mine}, 0.0) is None
        peer.close()  # EOF now queued: still no notice, no raise
        assert _drain_abort({1: mine}, 0.0) is None

    def test_grace_window_catches_late_notice(self):
        import threading
        import time as _time
        from horovod_tpu.common.controller import TAG_ABORT, _drain_abort

        mine, peer = self._pair()

        def late_send():
            _time.sleep(0.1)
            peer.send(heartbeat.encode_abort(1, "late"), TAG_ABORT)

        t = threading.Thread(target=late_send)
        t.start()
        try:
            assert _drain_abort({1: mine}, 1.0) == (1, "late")
        finally:
            t.join()


class TestWorldAbortedStatus:
    def test_status_carries_origin(self):
        st = Status.WorldAborted(4, "host fell over")
        assert not st.ok()
        assert st.aborted_by == 4
        assert "rank 4" in st.reason and "host fell over" in st.reason

    def test_error_is_internal_error_subclass(self):
        # existing `except HorovodInternalError` handlers keep working
        e = WorldAbortedError("msg", origin_rank=2)
        assert isinstance(e, HorovodInternalError)
        assert e.origin_rank == 2

    def test_plain_abort_has_no_origin(self):
        assert Status.Aborted("clean shutdown").aborted_by is None
