"""Checkpoint utilities: async (non-blocking) saves must snapshot the
state before returning, stay ordered, and be drained by restore/wait —
on top of the existing mp checkpoint_resume broadcast contract."""

import os

import numpy as np

from horovod_tpu.utils import (
    latest_checkpoint, restore_checkpoint, save_checkpoint,
    wait_pending_saves,
)


def test_async_save_snapshot_ordering_and_prune(tmp_path, hvd_world):
    d = str(tmp_path / "ck")
    state = {"w": np.full(4, 1.0, np.float32)}
    fut1 = save_checkpoint(d, state, step=1, block=False)
    state["w"][:] = 999.0          # mutate AFTER the async call
    fut2 = save_checkpoint(d, state, step=2, block=False)
    state["w"][:] = -5.0

    target = {"w": np.zeros(4, np.float32)}
    restored = restore_checkpoint(d, target=target, broadcast=False)
    # restore drained both saves; newest is step 2 with value 999
    np.testing.assert_allclose(np.asarray(restored["w"]), 999.0)
    assert fut1.done() and fut2.done()
    assert fut1.result().endswith("step_1")

    # the step-1 artifact holds the pre-mutation snapshot
    r1 = restore_checkpoint(fut1.result(), target=target,
                            broadcast=False)
    np.testing.assert_allclose(np.asarray(r1["w"]), 1.0)

    # a blocking save drains pending first; keep= prunes the oldest
    save_checkpoint(d, {"w": np.full(4, 3.0, np.float32)}, step=3,
                    keep=2)
    wait_pending_saves()
    assert sorted(n for n in os.listdir(d)
                  if not n.endswith(".digest")) == ["step_2", "step_3"]
    assert latest_checkpoint(d).endswith("step_3")


def test_async_save_jax_state(tmp_path, hvd_world):
    """Device arrays snapshot to host at submit time (donation-safe)."""
    import jax.numpy as jnp
    d = str(tmp_path / "ckj")
    state = {"p": jnp.arange(6.0)}
    fut = save_checkpoint(d, state, step=1, block=False)
    path = fut.result()
    r = restore_checkpoint(path, target={"p": np.zeros(6, np.float32)},
                           broadcast=False)
    np.testing.assert_allclose(np.asarray(r["p"]), np.arange(6.0))


def test_async_save_preserves_leaf_types(tmp_path, hvd_world):
    """Non-array leaves (python int) must not become 0-d arrays in an
    async checkpoint — block=False and block=True serialize alike."""
    from flax import serialization
    d = str(tmp_path / "ckt")
    state = {"w": np.ones(2, np.float32), "step": 3, "tag": "run-a"}
    fut = save_checkpoint(d, state, step=1, block=False)
    p_async = fut.result()
    p_block = save_checkpoint(d, state, step=2)
    raw_a = serialization.msgpack_restore(
        open(p_async, "rb").read()) if os.path.isfile(p_async) else None
    raw_b = serialization.msgpack_restore(
        open(p_block, "rb").read()) if os.path.isfile(p_block) else None
    if raw_a is not None and raw_b is not None:  # flax backend
        assert type(raw_a["step"]) is type(raw_b["step"])
        assert raw_a["tag"] == "run-a"


def test_failed_async_save_drains_all_without_poisoning(tmp_path,
                                                        hvd_world):
    """A failing save must not leave later saves racing, and must not
    poison unrelated later operations: the drain awaits everything and
    only LOGS the failure — the returned Future is the error channel."""
    import pytest
    from horovod_tpu.utils import checkpoint as ck

    d = str(tmp_path / "ckf")
    ok = save_checkpoint(d, {"w": np.ones(1, np.float32)}, step=1,
                         block=False)
    bad = ck._writer_pool().submit(
        (lambda: (_ for _ in ()).throw(OSError("disk full"))).__call__)
    ck._pending.append(bad)
    ok2 = save_checkpoint(d, {"w": np.ones(1, np.float32)}, step=2,
                          block=False)
    wait_pending_saves()  # no raise: the failure is logged
    # everything was awaited; nothing left in flight
    assert ok.done() and ok2.done() and bad.done()
    assert ck._pending == []
    # the Future still delivers the error to whoever holds it
    with pytest.raises(OSError, match="disk full"):
        bad.result()
    # a subsequent blocking save is NOT blocked by the stale failure
    p = save_checkpoint(d, {"w": np.full(1, 9.0, np.float32)}, step=3)
    assert p.endswith("step_3")
    assert latest_checkpoint(d).endswith("step_3")


def test_failed_save_leaves_no_partial_step(tmp_path, hvd_world,
                                            monkeypatch):
    """Atomic writes: a save that dies mid-serialization must leave no
    step_<n> entry, so restore falls back to the last COMPLETE one."""
    from horovod_tpu.utils import checkpoint as ck

    d = str(tmp_path / "cka")
    save_checkpoint(d, {"w": np.full(2, 1.0, np.float32)}, step=1)

    def boom(path, tree):
        tmp = path + ".tmpX"
        with open(tmp, "wb") as f:
            f.write(b"partial")       # bytes hit disk...
        raise OSError("disk full")    # ...then the save dies

    monkeypatch.setattr(ck, "_save_tree", boom)
    fut = save_checkpoint(d, {"w": np.full(2, 2.0, np.float32)},
                          step=2, block=False)
    wait_pending_saves()              # logged, not raised
    assert fut.done()
    monkeypatch.undo()

    assert latest_checkpoint(d).endswith("step_1")  # no phantom step_2
    r = restore_checkpoint(d, target={"w": np.zeros(2, np.float32)},
                           broadcast=False)
    np.testing.assert_allclose(np.asarray(r["w"]), 1.0)


def test_digest_sidecar_written_and_verifies(tmp_path, hvd_world):
    """Every visible step_<n> carries a digest sidecar; verification
    passes on intact checkpoints and on pre-digest ones (no sidecar)."""
    from horovod_tpu.utils import checkpoint as ck
    d = str(tmp_path / "ckd")
    p = save_checkpoint(d, {"w": np.ones(3, np.float32)}, step=1)
    assert os.path.exists(p + ".digest")
    assert ck.verify_checkpoint(p)
    os.remove(p + ".digest")          # a pre-digest checkpoint
    assert ck.verify_checkpoint(p)    # stays restorable


def test_kill_mid_write_torn_checkpoint_is_skipped(tmp_path, hvd_world):
    """A checkpoint whose bytes changed after its digest was recorded
    (torn write, bit rot, a kill mid-rename) is skipped by latest and
    refused by a direct restore."""
    import pytest
    from horovod_tpu.utils import checkpoint as ck
    d = str(tmp_path / "ckk")
    save_checkpoint(d, {"w": np.full(2, 1.0, np.float32)}, step=1)
    p2 = save_checkpoint(d, {"w": np.full(2, 2.0, np.float32)}, step=2)

    # corrupt step_2's content behind its digest — what a kill between
    # the backend write and a later torn overwrite leaves behind
    victim = p2 if os.path.isfile(p2) else \
        os.path.join(p2, sorted(os.listdir(p2))[0])
    if os.path.isdir(victim):
        victim = os.path.join(victim, sorted(os.listdir(victim))[0])
    with open(victim, "r+b") as f:
        f.write(b"\x00\xff\x00\xff")

    assert not ck.verify_checkpoint(p2)
    assert latest_checkpoint(d).endswith("step_1")  # falls back
    r = restore_checkpoint(d, target={"w": np.zeros(2, np.float32)},
                           broadcast=False)
    np.testing.assert_allclose(np.asarray(r["w"]), 1.0)
    with pytest.raises(ValueError, match="digest"):
        restore_checkpoint(p2, target={"w": np.zeros(2, np.float32)},
                           broadcast=False)


def test_prune_removes_digest_sidecars(tmp_path, hvd_world):
    d = str(tmp_path / "ckp")
    for step in (1, 2, 3):
        save_checkpoint(d, {"w": np.ones(1, np.float32)}, step=step,
                        keep=2)
    names = sorted(os.listdir(d))
    assert "step_1" not in names and "step_1.digest" not in names
    assert "step_2.digest" in names and "step_3.digest" in names


def test_flax_fallback_backend_roundtrip(tmp_path, hvd_world,
                                         monkeypatch):
    """The msgpack (flax) storage fallback must round-trip when orbax
    is unavailable — otherwise that branch never executes in CI."""
    import sys
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    monkeypatch.setitem(sys.modules, "orbax", None)

    d = str(tmp_path / "ckflax")
    state = {"w": np.arange(5, dtype=np.float32), "step": 11}
    p = save_checkpoint(d, state, step=11)
    assert os.path.isfile(p)  # flax writes a FILE (orbax writes a dir)
    fut = save_checkpoint(d, state, step=12, block=False)
    assert os.path.isfile(fut.result())

    r = restore_checkpoint(d, target={"w": np.zeros(5, np.float32),
                                      "step": 0}, broadcast=False)
    np.testing.assert_allclose(np.asarray(r["w"]), np.arange(5.0))
    assert int(r["step"]) == 11  # both saves stored the same state
