"""In-jit SPMD collectives over the virtual 8-device CPU mesh
(test model: reference test/test_tensorflow.py collective correctness
vs locally computed expectation, re-aimed at the mesh path)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu import spmd

from horovod_tpu.compat import jaxshim


@pytest.fixture(scope="module")
def mesh():
    return spmd.create_mesh({"data": 8})


def _shard_map(mesh, body, in_specs, out_specs):
    return jax.jit(jaxshim.shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))


def test_mesh_default_axes():
    m = spmd.create_mesh()
    assert m.axis_names == ("data",)
    assert m.devices.size == 8


def test_mesh_infer_axis():
    m = spmd.create_mesh({"data": -1, "model": 2})
    assert dict(zip(m.axis_names, m.devices.shape)) == {
        "data": 4, "model": 2}


def test_mesh_bad_sizes():
    with pytest.raises(ValueError):
        spmd.create_mesh({"data": 3})
    with pytest.raises(ValueError):
        spmd.create_mesh({"data": -1, "model": -1})


def test_allreduce_mean_sum(mesh):
    # Global (8, 2) sharded over 'data': each replica holds one (1, 2)
    # row; allreduce preserves the per-replica shape (hvd semantics).
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    f = _shard_map(mesh, lambda t: spmd.allreduce(t, op=spmd.Sum),
                   P("data"), P())
    np.testing.assert_allclose(np.asarray(f(x)), x.sum(0, keepdims=True))
    g = _shard_map(mesh, lambda t: spmd.allreduce(t, op=spmd.Average),
                   P("data"), P())
    np.testing.assert_allclose(np.asarray(g(x)), x.mean(0, keepdims=True))


def test_allreduce_min_max_scale(mesh):
    x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    fmin = _shard_map(mesh, lambda t: spmd.allreduce(t, op=spmd.Min),
                      P("data"), P())
    np.testing.assert_allclose(np.asarray(fmin(x)),
                               x.min(0, keepdims=True))
    fs = _shard_map(
        mesh, lambda t: spmd.allreduce(t, op=spmd.Sum,
                                       prescale_factor=2.0,
                                       postscale_factor=0.5),
        P("data"), P())
    np.testing.assert_allclose(np.asarray(fs(x)), x.sum(0, keepdims=True),
                               rtol=1e-6)


def test_allgather(mesh):
    x = np.arange(24, dtype=np.float32).reshape(8, 3)
    f = _shard_map(mesh, lambda t: spmd.allgather(t), P("data"), P())
    np.testing.assert_allclose(np.asarray(f(x)), x)


def test_broadcast(mesh):
    x = np.tile(np.arange(8, dtype=np.float32)[:, None], (1, 4))
    f = _shard_map(mesh, lambda t: spmd.broadcast(t, root_rank=3),
                   P("data"), P("data"))
    out = np.asarray(f(x))
    assert (out == 3.0).all()


def test_alltoall(mesh):
    # Each replica holds 8 rows = 8 one-row blocks; block d goes to
    # replica d. Globally that is a block transpose of the 8x8 grid.
    x = np.arange(128, dtype=np.float32).reshape(64, 2)
    f = _shard_map(mesh, lambda t: spmd.alltoall(t), P("data"), P("data"))
    expected = x.reshape(8, 8, 2).transpose(1, 0, 2).reshape(64, 2)
    np.testing.assert_allclose(np.asarray(f(x)), expected)


def test_reducescatter(mesh):
    # Each replica holds an (8, 3) tensor; the summed tensor is
    # scattered one row per replica → global output (8, 3) = blockwise
    # sum of the shards.
    x = np.random.RandomState(1).randn(64, 3).astype(np.float32)

    def body(t):
        return spmd.reducescatter(t, op=spmd.Sum)

    f = _shard_map(mesh, body, P("data"), P("data"))
    expected = x.reshape(8, 8, 3).sum(0)
    np.testing.assert_allclose(np.asarray(f(x)), expected, rtol=1e-5)


def test_allreduce_gradients_tree_with_compression(mesh):
    from horovod_tpu import Compression
    tree = {"a": np.full((8, 2), 2.0, np.float32),
            "b": np.ones((8, 4), np.float32)}

    def body(t):
        return spmd.allreduce_gradients(t, compression=Compression.bf16)

    f = _shard_map(mesh, body, P("data"), P())
    out = f(tree)
    np.testing.assert_allclose(np.asarray(out["a"]), [[2.0, 2.0]])
    assert out["a"].dtype == jnp.float32  # restored after wire cast


def test_broadcast_variables_tree(mesh):
    tree = {"w": np.tile(np.arange(8, dtype=np.float32)[:, None], (1, 2))}
    f = _shard_map(mesh, lambda t: spmd.broadcast_variables(t, 5),
                   P("data"), P("data"))
    assert (np.asarray(f(tree)["w"]) == 5.0).all()


def test_mesh_rank_size(mesh):
    f = _shard_map(
        mesh,
        lambda t: t * 0 + spmd.mesh_rank("data").astype(jnp.float32),
        P("data"), P("data"))
    out = np.asarray(f(np.zeros((8, 1), np.float32)))
    np.testing.assert_allclose(out[:, 0], np.arange(8))


def test_hierarchical_axes():
    # ('cross', 'local') two-level mesh: psum over both axes == global sum
    m = spmd.create_mesh({"cross": 2, "local": 4})
    x = np.arange(8, dtype=np.float32).reshape(2, 4)

    f = jax.jit(jaxshim.shard_map(
        lambda t: spmd.allreduce(t, op=spmd.Sum, axis=("cross", "local")),
        mesh=m, in_specs=P("cross", "local"), out_specs=P()))
    np.testing.assert_allclose(np.asarray(f(x)), x.sum())


def test_shard_batch_and_shardings(mesh):
    batch = {"x": np.zeros((16, 3), np.float32)}
    out = spmd.shard_batch(mesh, batch)
    assert out["x"].sharding.spec == P("data")


def test_create_hybrid_mesh_axis_order(monkeypatch):
    """DCN axes lead the mesh (outer/slower network outermost); ICI
    axes follow — the contract the hierarchical collectives assume.
    Real multi-slice construction needs multi-slice hardware, so the
    device grid is injected."""
    import numpy as np
    import jax
    from jax.experimental import mesh_utils
    from horovod_tpu import spmd

    captured = {}

    def fake_hybrid(ici_shape, dcn_mesh_shape):
        captured["ici"] = tuple(ici_shape)
        captured["dcn"] = tuple(dcn_mesh_shape)
        return np.array(jax.devices()[:8]).reshape(2, 2, 2)

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh",
                        fake_hybrid)
    mesh = spmd.create_hybrid_mesh({"seq": 2, "model": 2}, {"data": 2})
    assert captured == {"ici": (2, 2), "dcn": (2,)}
    assert mesh.axis_names == ("data", "seq", "model")
    assert dict(mesh.shape) == {"data": 2, "seq": 2, "model": 2}


def test_distributed_optimizer_predivide_and_compression(mesh):
    """hvd.jax.DistributedOptimizer: the default pmean path, the
    prescale/postscale pre-divide path, and the bf16-compressed path
    must all produce the mean-gradient SGD update (prescale by 1/f,
    postscale by f/n — net mean, smaller intermediates; reference:
    allreduce prescale/postscale contract)."""
    import optax
    import horovod_tpu.jax as hj

    rng = np.random.RandomState(3)
    params = {"w": rng.randn(4, 6).astype(np.float32)}
    g_stacked = rng.randn(8, 4, 6).astype(np.float32)
    want_g = g_stacked.mean(0)

    def run(tx):
        def step(p, g8):
            g = {"w": g8[0]}
            state = tx.init(p)
            updates, _ = tx.update(g, state, p)
            return optax.apply_updates(p, updates)
        f = _shard_map(mesh, step, (P(), P("data")), P())
        return np.asarray(f(params, g_stacked)["w"])

    want = params["w"] - 0.1 * want_g
    base = run(hj.DistributedOptimizer(optax.sgd(0.1)))
    np.testing.assert_allclose(base, want, rtol=1e-5)

    pre = run(hj.DistributedOptimizer(optax.sgd(0.1),
                                      gradient_predivide_factor=8.0))
    np.testing.assert_allclose(pre, want, rtol=1e-5)

    comp = run(hj.DistributedOptimizer(
        optax.sgd(0.1), compression=hj.Compression.bf16))
    np.testing.assert_allclose(comp, want, rtol=2e-2, atol=1e-2)
