"""Unit tests for the steady-state negotiation fast path: the
world-coherent ResponseCache (slot assignment, LRU eviction,
invalidation), the cache-coherence wire frames, and the runtime's
unfuse/replay helpers. Cross-rank coherence is modeled by feeding two
cache instances the SAME world-identical event stream with DIFFERENT
rank-local signatures (device ids, allgather dim-0) and asserting their
coherent state fingerprints stay bit-identical — the invariant the
bitmask protocol stands on. End-to-end multi-process coverage lives in
tests/test_multiprocess.py (response_cache_* and cache_byte_budget)."""

import pytest

from horovod_tpu.common import wire
from horovod_tpu.common.coordinator import ResponseCache, fuse_responses
from horovod_tpu.common.message import (
    CacheCycleRequest, CacheCycleResponse, DataType, Request, RequestList,
    RequestType, Response, ResponseList, ResponseType,
)


def _req(name, rank=0, shape=(4,), dtype=DataType.FLOAT32, device=-1,
         op=RequestType.ALLREDUCE, root=-1):
    return Request(request_rank=rank, request_type=op, tensor_type=dtype,
                   tensor_name=name, root_rank=root, device=device,
                   tensor_shape=shape)


def _resp(name, numel=4, devices=(-1, -1)):
    return Response(response_type=ResponseType.ALLREDUCE,
                    tensor_names=[name], devices=list(devices),
                    tensor_sizes=[numel])


def _put(cache, name, req=None, resp=None):
    req = req or _req(name)
    cache.put(name, ResponseCache.signature(req), resp or _resp(name),
              req.tensor_type, 1)


class TestResponseCache:
    def test_lookup_states(self):
        c = ResponseCache(4)
        assert c.lookup(_req("g"))[0] == ResponseCache.MISS
        _put(c, "g")
        state, slot = c.lookup(_req("g"))
        assert state == ResponseCache.HIT and slot == 0
        # shape change -> INVALID, same slot reported for eviction
        state, slot = c.lookup(_req("g", shape=(8,)))
        assert state == ResponseCache.INVALID and slot == 0
        # dtype change -> INVALID too
        state, _ = c.lookup(_req("g", dtype=DataType.FLOAT64))
        assert state == ResponseCache.INVALID
        assert c.hits == 1 and c.misses == 3

    def test_lru_capacity_eviction_and_slot_reuse(self):
        c = ResponseCache(2)
        _put(c, "a")
        _put(c, "b")
        _put(c, "c")  # evicts a (LRU), reuses its slot 0
        assert c.lookup(_req("a"))[0] == ResponseCache.MISS
        assert c.lookup(_req("c")) == (ResponseCache.HIT, 0)
        assert c.lookup(_req("b")) == (ResponseCache.HIT, 1)

    def test_touch_steers_eviction_order(self):
        c = ResponseCache(2)
        _put(c, "a")
        _put(c, "b")
        c.touch_mask(0b01)  # a is now most-recently-used
        _put(c, "c")        # so b gets evicted, not a
        assert c.lookup(_req("b"))[0] == ResponseCache.MISS
        assert c.lookup(_req("a"))[0] == ResponseCache.HIT

    def test_touch_does_not_bump_epoch(self):
        """Hit cycles must not invalidate steady-state replay plans:
        only structural mutations (puts/evictions) move the epoch."""
        c = ResponseCache(4)
        _put(c, "a")
        e = c.epoch
        c.touch_mask(0b1)
        assert c.epoch == e

    def test_evict_slots_mask_ascending(self):
        c = ResponseCache(4)
        for n in "abcd":
            _put(c, n)
        c.evict_slots(0b0101)  # slots 0 and 2 -> a and c
        assert c.lookup(_req("a"))[0] == ResponseCache.MISS
        assert c.lookup(_req("c"))[0] == ResponseCache.MISS
        assert c.lookup(_req("b"))[0] == ResponseCache.HIT
        # freed slots are reused lowest-first — deterministically
        _put(c, "e")
        assert c.lookup(_req("e")) == (ResponseCache.HIT, 0)

    def test_two_ranks_march_in_lockstep(self):
        """The coherence contract: identical event streams with
        DIFFERENT rank-local signatures (device ids, allgather dim-0)
        must leave the coherent state — slot map, LRU order, epoch —
        bit-identical. This is what lets a slot bit stand in for a
        serialized Request."""
        r0, r1 = ResponseCache(3), ResponseCache(3)
        names = ["g0", "g1", "g2", "g3", "g0", "g4"]
        for i, n in enumerate(names):
            resp = _resp(n)
            # rank 0 submits on device 0, rank 1 on device 1, and their
            # allgather-ish shapes differ — signatures are local-only
            r0.put(n, ResponseCache.signature(
                _req(n, rank=0, device=0, shape=(i + 1, 4))),
                resp, DataType.FLOAT32, 4)
            r1.put(n, ResponseCache.signature(
                _req(n, rank=1, device=1, shape=(2 * i + 1, 4))),
                resp, DataType.FLOAT32, 4)
            assert r0.state_fingerprint() == r1.state_fingerprint()
        # mask-driven events stay coherent too
        r0.touch_mask(0b011)
        r1.touch_mask(0b011)
        r0.evict_slots(0b010)
        r1.evict_slots(0b010)
        assert r0.state_fingerprint() == r1.state_fingerprint()


class TestCycleFrames:
    def test_full_request_round_trip(self):
        rl = RequestList([_req("a"), _req("b", rank=3)], shutdown=True)
        out = wire.parse_cycle_request(wire.serialize_cycle_request(rl))
        assert isinstance(out, RequestList) and out == rl

    def test_cached_request_round_trip(self):
        cf = CacheCycleRequest(epoch=42, nslots=19, hit_mask=0b1011,
                               invalid_mask=1 << 17,
                               requests=[_req("u", rank=2)],
                               shutdown=True)
        out = wire.parse_cycle_request(wire.serialize_cycle_request(cf))
        assert isinstance(out, CacheCycleRequest) and out == cf

    def test_cached_request_frame_is_capacity_bounded(self):
        """The steady-state frame is O(nslots/8) bytes — the whole
        point of the fast path (the byte-budget mp test asserts the
        live world's traffic; this pins the encoding itself)."""
        cf = CacheCycleRequest(epoch=1, nslots=1024,
                               hit_mask=(1 << 1024) - 1,
                               invalid_mask=0, requests=[])
        frame = wire.serialize_cycle_request(cf)
        assert len(frame) <= 2 * (1024 // 8) + 32, len(frame)

    def test_full_response_round_trip(self):
        rl = ResponseList([_resp("a")], shutdown=False,
                          tuned_cycle_time_ms=2.0,
                          tuned_fusion_threshold_bytes=4096)
        out = wire.parse_cycle_response(
            wire.serialize_cycle_response(rl))
        assert isinstance(out, ResponseList) and out == rl

    def test_cached_response_round_trip(self):
        cr = CacheCycleResponse(
            epoch=7, nslots=9, grant_mask=0b101, invalid_mask=0b10,
            response_list=ResponseList([_resp("n")], shutdown=True,
                                       tuned_cycle_time_ms=1.5,
                                       tuned_fusion_threshold_bytes=64))
        out = wire.parse_cycle_response(
            wire.serialize_cycle_response(cr))
        assert isinstance(out, CacheCycleResponse) and out == cr

    def test_combine_folds_masks_and_concats_requests(self):
        a = wire.serialize_cycle_request(CacheCycleRequest(
            epoch=5, nslots=8, hit_mask=0b0111, invalid_mask=0b1000,
            requests=[_req("x", rank=1)]))
        b = wire.serialize_cycle_request(CacheCycleRequest(
            epoch=5, nslots=8, hit_mask=0b1101, invalid_mask=0b0010,
            requests=[_req("y", rank=2)], shutdown=True))
        combined = wire.combine_cycle_requests([a, b])
        assert combined is not None
        assert combined[0] == wire.FRAME_CACHED_AGG
        out = wire.parse_cycle_request(combined)
        assert out.hit_mask == 0b0101       # AND
        assert out.invalid_mask == 0b1010   # OR
        assert out.shutdown is True         # OR
        assert [r.tensor_name for r in out.requests] == ["x", "y"]
        assert [r.request_rank for r in out.requests] == [1, 2]

    def test_combine_is_associative_through_agg_frames(self):
        """A root's CACHED_AGG output can itself be folded again
        upstream (deeper trees)."""
        frames = [wire.serialize_cycle_request(CacheCycleRequest(
            epoch=1, nslots=4, hit_mask=m, invalid_mask=0,
            requests=[])) for m in (0b1111, 0b1110, 0b1011)]
        once = wire.combine_cycle_requests(frames[:2])
        twice = wire.combine_cycle_requests([once, frames[2]])
        assert wire.parse_cycle_request(twice).hit_mask == 0b1010

    def test_spec_request_round_trip(self):
        import numpy as np
        seg = [(DataType.FLOAT64, np.arange(8, dtype=np.float64)),
               (DataType.FLOAT32, np.ones(3, dtype=np.float32))]
        cf = CacheCycleRequest(epoch=3, nslots=9, hit_mask=0b101,
                               spec_payload=seg)
        frame = wire.serialize_cycle_request(cf)
        assert frame[0] == wire.FRAME_CACHED_SPEC
        out = wire.parse_cycle_request(frame)
        assert isinstance(out, CacheCycleRequest)
        assert out.hit_mask == 0b101 and out.epoch == 3
        assert out.requests == [] and not out.shutdown
        (d0, b0), (d1, b1) = out.spec_payload
        assert d0 == DataType.FLOAT64 and d1 == DataType.FLOAT32
        np.testing.assert_array_equal(
            np.frombuffer(b0, np.float64), np.arange(8.0))
        np.testing.assert_array_equal(
            np.frombuffer(b1, np.float32), np.ones(3, np.float32))

    def test_spec_response_round_trip(self):
        import numpy as np
        seg = [(DataType.FLOAT64, np.full(4, 36.0))]
        cr = CacheCycleResponse(epoch=7, nslots=5, grant_mask=0b11,
                                spec_payload=seg)
        out = wire.parse_cycle_response(
            wire.serialize_cycle_response(cr))
        assert isinstance(out, CacheCycleResponse)
        assert out.grant_mask == 0b11 and out.epoch == 7
        assert out.response_list.responses == []
        np.testing.assert_array_equal(
            np.frombuffer(out.spec_payload[0][1], np.float64),
            np.full(4, 36.0))

    def test_combine_refuses_spec_frames(self):
        """A local root must never mask-fold frames carrying fused
        payloads — the coordinator reduces them (the relay forwards
        them per-rank instead)."""
        import numpy as np
        spec = wire.serialize_cycle_request(CacheCycleRequest(
            epoch=1, nslots=4, hit_mask=0b1,
            spec_payload=[(DataType.FLOAT64,
                           np.ones(2, np.float64))]))
        plain = wire.serialize_cycle_request(CacheCycleRequest(
            epoch=1, nslots=4, hit_mask=0b1, invalid_mask=0,
            requests=[]))
        assert wire.combine_cycle_requests([spec, plain]) is None
        assert wire.combine_cycle_requests([spec, spec]) is None

    def test_reduce_spec_sums_ranks(self):
        import numpy as np

        from horovod_tpu.common.runtime import Runtime
        frames = [CacheCycleRequest(
            epoch=0, nslots=2, hit_mask=0b11,
            spec_payload=[(DataType.FLOAT64,
                           memoryview(np.full(4, float(r + 1))))])
            for r in range(3)]
        out = Runtime._reduce_spec(frames)
        assert out[0][0] == DataType.FLOAT64
        np.testing.assert_array_equal(out[0][1], np.full(4, 6.0))

    def test_reduce_spec_rejects_layout_divergence(self):
        import numpy as np

        from horovod_tpu.common.runtime import Runtime
        a = CacheCycleRequest(epoch=0, nslots=1, hit_mask=1,
                              spec_payload=[(DataType.FLOAT64,
                                             memoryview(np.ones(4)))])
        b = CacheCycleRequest(epoch=0, nslots=1, hit_mask=1,
                              spec_payload=[(DataType.FLOAT64,
                                             memoryview(np.ones(5)))])
        with pytest.raises(ConnectionError):
            Runtime._reduce_spec([a, b])

    def test_combine_refuses_mixed_or_diverged_frames(self):
        cached = wire.serialize_cycle_request(CacheCycleRequest(
            epoch=1, nslots=4, hit_mask=0b1, invalid_mask=0,
            requests=[]))
        full = wire.serialize_cycle_request(RequestList([]))
        assert wire.combine_cycle_requests([cached, full]) is None
        other_epoch = wire.serialize_cycle_request(CacheCycleRequest(
            epoch=2, nslots=4, hit_mask=0b1, invalid_mask=0,
            requests=[]))
        assert wire.combine_cycle_requests(
            [cached, other_epoch]) is None


class TestReplay:
    def _runtime_shell(self):
        """A bare object exposing just what _unfuse/_replay_grants
        need — keeps these tests transport-free."""
        from horovod_tpu.common.runtime import Runtime
        return Runtime.__new__(Runtime)

    def test_unfuse_fused_allreduce(self):
        from horovod_tpu.common.runtime import Runtime
        fused = Response(response_type=ResponseType.ALLREDUCE,
                         tensor_names=["a", "b"], devices=[-1, -1],
                         tensor_sizes=[10, 20], prescale_factor=0.5)
        one = Runtime._unfuse(fused, 1, world_size=2)
        assert one.tensor_names == ["b"]
        assert one.tensor_sizes == [20]
        assert one.prescale_factor == 0.5
        assert one.devices == [-1, -1]

    def test_unfuse_fused_allgather_entry_major(self):
        from horovod_tpu.common.runtime import Runtime
        # 2 entries x 3 ranks, entry-major sizes
        fused = Response(response_type=ResponseType.ALLGATHER,
                         tensor_names=["g1", "g2"],
                         devices=[-1, -1, -1],
                         tensor_sizes=[3, 4, 5, 1, 1, 1])
        assert Runtime._unfuse(fused, 0, 3).tensor_sizes == [3, 4, 5]
        assert Runtime._unfuse(fused, 1, 3).tensor_sizes == [1, 1, 1]

    def test_unfuse_sizeless_response(self):
        from horovod_tpu.common.runtime import Runtime
        bc = Response(response_type=ResponseType.BROADCAST,
                      tensor_names=["w"], devices=[-1, -1])
        one = Runtime._unfuse(bc, 0, 2)
        assert one.tensor_names == ["w"] and one.tensor_sizes == []

    def test_replayed_fusion_never_mutates_cached_entries(self):
        """fuse_responses mutates the batch head's lists; the replay
        must clone before fusing or the cache would corrupt after one
        hit cycle."""
        c = ResponseCache(4)
        _put(c, "a")
        _put(c, "b")
        clones = [c.entry(s).clone_response() for s in (0, 1)]
        fused = fuse_responses(
            clones, {"a": DataType.FLOAT32, "b": DataType.FLOAT32},
            1 << 20, {"a": 1, "b": 1})
        assert fused[0].tensor_names == ["a", "b"]
        assert c.entry(0).response.tensor_names == ["a"]
        assert c.entry(1).response.tensor_names == ["b"]

    def test_iter_slots_ascending(self):
        from horovod_tpu.common.runtime import Runtime
        mask = (1 << 63) | (1 << 5) | 1
        assert list(Runtime._iter_slots(mask)) == [0, 5, 63]


class TestConfigKnobs:
    def test_env_knobs(self, monkeypatch):
        from horovod_tpu.common.config import Config
        monkeypatch.setenv("HOROVOD_CACHE_ENABLED", "0")
        monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", "77")
        monkeypatch.setenv("HOROVOD_CACHE_SPECULATIVE", "0")
        c = Config.from_env()
        assert c.cache_enabled is False
        assert c.cache_capacity == 77
        assert c.cache_speculative is False

    def test_zero_capacity_disables(self):
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.controller import LocalController
        from horovod_tpu.common.runtime import Runtime
        from horovod_tpu.ops.local_ops import LocalBackend
        from horovod_tpu.ops.operation_manager import OperationManager
        cfg = Config(cache_capacity=0, async_completion=False)
        rt = Runtime(cfg, LocalController(),
                     OperationManager([LocalBackend(lambda: 1)]))
        assert rt._cache is None
        assert rt.negotiation_cache_stats() == {"enabled": False}

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError):
            ResponseCache(0)
