"""PR 16 kernel-side wire speed: the batched-submission reactor, the
native int8 codec, and the chunked cut-through relay — multi-process
acceptance legs.

The native unit tests (test_native.py) pin the C entry points against
their numpy/Python-Channel references in-process; this module proves
the RUNTIME contracts on real worlds:

* fail-fast survives the reactor: SIGKILL and link-sever while the
  coordinator sits in a batched gather still raise WorldAbortedError
  naming the dead peer within the heartbeat deadline;
* `HOROVOD_TPU_REACTOR` is recv discipline only: all-on, all-off and
  heterogeneous (one rank opted out) worlds are BIT-EXACT with each
  other across every collective family, including a multi-host
  hierarchy where the cut-through relay carries the root legs;
* the native int8 codec is BIT-IDENTICAL to the numpy reference:
  an int8+error-feedback training-shaped world re-run under
  HOROVOD_NATIVE=0 reproduces the same output bytes.
"""

import signal

import numpy as np

from tests.test_multiprocess import run_scenario

_HB_ENV = {
    "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
    "HOROVOD_HEARTBEAT_TIMEOUT": "3",
}
_SIGKILL_RC = -signal.SIGKILL
# Socket star with the ring disabled: every gather rides the
# coordinator's reactor path, the surface under test.
_SOCKET_ENV = {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1"}


def test_abort_sigkill_mid_batched_gather():
    """SIGKILL rank 1 of 3 mid-collective with the batched reactor
    carrying the coordinator's gathers: both survivors raise
    WorldAbortedError naming rank 1 within the detection deadline —
    the batched submission honors the same deadlines as the
    sequential loop it replaced."""
    run_scenario(
        "abort_sigkill_batched_gather", 3, timeout=60.0,
        extra_env={**_HB_ENV, **_SOCKET_ENV,
                   "HOROVOD_TPU_REACTOR": "1",
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=3"},
        expect_rc={1: _SIGKILL_RC})


def test_abort_sever_mid_batched_gather():
    """Abrupt link severance (process alive) mid-batched-gather: the
    EOF surfaces among the batch completions and the coordinator
    blames the severed peer, never itself."""
    run_scenario(
        "abort_sever_batched_gather", 3, timeout=60.0,
        extra_env={**_HB_ENV, **_SOCKET_ENV,
                   "HOROVOD_TPU_REACTOR": "1",
                   "HOROVOD_FAULT_SPEC": "rank=1:sever:cycle=20"})


def _reactor_world(tmp_path, tag, per_rank_env=None, extra=None,
                   np_ranks=3):
    out = str(tmp_path / f"reactor_{tag}.npy")
    env = {**_SOCKET_ENV, "HVD_REACTOR_OUT": out}
    if extra:
        env.update(extra)
    run_scenario("reactor_exact", np_ranks, timeout=90.0,
                 extra_env=env, per_rank_env=per_rank_env)
    return np.load(out)


def test_reactor_off_world_bit_exact(tmp_path):
    """HOROVOD_TPU_REACTOR=0 everywhere completes the full collective
    sweep with bytes identical to the reactor world — the runtime
    fallback is not a degraded mode, it is the same protocol."""
    on = _reactor_world(tmp_path, "on",
                        extra={"HOROVOD_TPU_REACTOR": "1",
                               "HOROVOD_TPU_METRICS": "1",
                               "HVD_EXPECT_REACTOR": "1"})
    off = _reactor_world(tmp_path, "off",
                         extra={"HOROVOD_TPU_REACTOR": "0"})
    np.testing.assert_array_equal(on, off)


def test_reactor_hetero_world_bit_exact(tmp_path):
    """ONE rank opted out (HOROVOD_TPU_REACTOR=0 on rank 1) in an
    otherwise-reactor world: the knob is rank-local recv discipline,
    so the mixed world must interoperate frame-for-frame and produce
    the same bytes as the uniform world."""
    uniform = _reactor_world(tmp_path, "uniform")
    mixed = _reactor_world(
        tmp_path, "mixed",
        per_rank_env=lambda rank: (
            {"HOROVOD_TPU_REACTOR": "0"} if rank == 1 else {}))
    np.testing.assert_array_equal(uniform, mixed)


def test_reactor_hier_multihost_bit_exact(tmp_path):
    """Two fake hosts x two ranks so the hierarchical control plane
    (and with it the chunked cut-through relay on the root legs)
    carries the sweep: reactor-on and reactor-off (store-and-forward
    relay fallback) worlds must be bit-exact."""
    hosts = lambda rank: {"HOROVOD_HOSTNAME": f"fakehost{rank // 2}"}
    on = _reactor_world(tmp_path, "hier_on", per_rank_env=hosts,
                        extra={"HOROVOD_TPU_REACTOR": "1"},
                        np_ranks=4)
    off = _reactor_world(tmp_path, "hier_off", per_rank_env=hosts,
                         extra={"HOROVOD_TPU_REACTOR": "0"},
                         np_ranks=4)
    np.testing.assert_array_equal(on, off)


def test_int8_codec_native_vs_numpy_bitexact(tmp_path):
    """The convergence-parity contract, bit-for-bit: an int8+EF
    steady world re-run with HOROVOD_NATIVE=0 (numpy codec, same wire
    format) must reproduce the same output bytes — hvd_quant8 /
    hvd_dequant8 change the cost of the codec, never its values."""
    base = {**_SOCKET_ENV, "HOROVOD_COMPRESSION": "int8"}
    nat = str(tmp_path / "i8_native.npy")
    run_scenario("int8_codec_parity", 3, timeout=90.0,
                 extra_env={**base, "HVD_REACTOR_OUT": nat})
    ref = str(tmp_path / "i8_numpy.npy")
    run_scenario("int8_codec_parity", 3, timeout=90.0,
                 extra_env={**base, "HVD_REACTOR_OUT": ref,
                            "HOROVOD_NATIVE": "0"})
    np.testing.assert_array_equal(np.load(nat), np.load(ref))
