"""Spark integration tests (reference: test/test_spark.py:51-107 —
local-mode run asserting per-rank results and env, plus graceful
failure without the launcher dependency). Real pyspark is absent from
the image, so partitions run in forked worker processes via
tests/fake_pyspark — the same process shape Spark local mode gives the
integration (see that module's docstring)."""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RUN_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from tests import fake_pyspark
fake_pyspark.install()

import numpy as np
import horovod_tpu.spark


def train():
    import os
    import numpy as np
    import horovod_tpu as hvd
    rank, size = hvd.rank(), hvd.size()
    out = hvd.allreduce(np.full(8, float(rank + 1), np.float32),
                        average=False, name="spark.ar")
    assert np.allclose(out, sum(range(1, size + 1))), out[0]
    return {{"rank": rank, "size": size,
             "env_rank": os.environ["HOROVOD_RANK"],
             "sum0": float(out[0])}}


results = horovod_tpu.spark.run(train, num_proc=3)
assert [r["rank"] for r in results] == [0, 1, 2], results
assert all(r["size"] == 3 for r in results)
assert all(r["env_rank"] == str(r["rank"]) for r in results)
assert all(r["sum0"] == 6.0 for r in results)
print("SPARK_OK")
"""


def test_spark_run_local_mode():
    """3 ranks through horovod_tpu.spark.run: rendezvous, coordinator
    socket handoff, per-rank env, allreduce, rank-ordered results."""
    script = _RUN_SCRIPT.format(repo=REPO)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, timeout=120)
    assert out.returncode == 0, out.stdout.decode() + out.stderr.decode()
    assert b"SPARK_OK" in out.stdout


def test_spark_requires_pyspark():
    """Graceful failure without pyspark (reference analog: mpirun
    missing from PATH, test/test_spark.py:91-107)."""
    import horovod_tpu.spark as hspark
    with pytest.raises(ImportError, match="requires pyspark"):
        hspark.run(lambda: None, num_proc=1)


def test_parent_death_watchdog_kills_orphan():
    """An intermediary process starts a grandchild running the
    watchdog; killing the intermediary must make the grandchild exit
    (reference: spark/task/mpirun_exec_fn.py:26-38)."""
    script = (
        "import os, sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "pid = os.fork()\n"
        "if pid == 0:\n"
        "    from horovod_tpu.spark import _start_parent_watchdog\n"
        "    _start_parent_watchdog(poll_s=0.2)\n"
        "    print('CHILD', os.getpid(), flush=True)\n"
        "    time.sleep(60)\n"
        "    os._exit(0)\n"
        "print('PARENT', pid, flush=True)\n"
        "time.sleep(60)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.Popen([sys.executable, "-c", script], env=env,
                         stdout=subprocess.PIPE)
    child_pid = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and child_pid is None:
        line = p.stdout.readline().decode().strip()
        if line.startswith("CHILD"):
            child_pid = int(line.split()[1])
    assert child_pid is not None
    p.kill()  # kill the intermediary -> grandchild is orphaned
    p.wait()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(child_pid, 0)  # still alive?
        except ProcessLookupError:
            return  # watchdog fired
        time.sleep(0.2)
    os.kill(child_pid, signal.SIGKILL)
    raise AssertionError("orphaned grandchild outlived its parent")
