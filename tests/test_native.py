"""Native core (native/libhvdtpu.so) correctness: HMAC vs hashlib,
reductions vs numpy, pack/unpack round-trip, and frame transport vs the
Python Channel implementation. Skipped wholesale when no compiler/lib
is available — every native path has a Python fallback."""

import ctypes
import hashlib
import hmac
import os
import socket
import threading

import numpy as np
import pytest

import horovod_tpu.native as native
from horovod_tpu.common.network import Channel


lib = native.get()
pytestmark = pytest.mark.skipif(lib is None,
                                reason="native core unavailable")


def _hmac_native(key: bytes, tag: int, payload: bytes) -> bytes:
    out = (ctypes.c_uint8 * 32)()
    kb = (ctypes.c_uint8 * max(1, len(key)))(*key)
    pb = (ctypes.c_uint8 * max(1, len(payload)))(*payload)
    lib.hvd_hmac_sha256(kb, len(key), tag, pb, len(payload), out)
    return bytes(out)


@pytest.mark.parametrize("key,payload", [
    (b"k", b""),
    (b"secretkey123", b"hello"),
    (b"x" * 64, b"y" * 4096),
    (b"z" * 100, os.urandom(100001)),  # key > block size, multi-block
])
def test_hmac_matches_hashlib(key, payload):
    expected = hmac.new(key, bytes([5]) + payload, hashlib.sha256).digest()
    assert _hmac_native(key, 5, payload) == expected


@pytest.mark.parametrize("dtype,tol", [
    (np.float32, 1e-6), (np.float64, 1e-12),
    (np.int32, 0), (np.int64, 0), (np.uint8, 0),
])
def test_sum_into_matches_numpy(dtype, tol):
    rng = np.random.RandomState(0)
    if np.issubdtype(dtype, np.floating):
        a = rng.randn(1337).astype(dtype)
        b = rng.randn(1337).astype(dtype)
    else:
        a = rng.randint(0, 100, 1337).astype(dtype)
        b = rng.randint(0, 100, 1337).astype(dtype)
    expected = a + b
    assert native.sum_into(a, b)
    if tol:
        np.testing.assert_allclose(a, expected, rtol=tol)
    else:
        np.testing.assert_array_equal(a, expected)


def test_sum_into_float16():
    rng = np.random.RandomState(1)
    a = rng.randn(257).astype(np.float16)
    b = rng.randn(257).astype(np.float16)
    expected = (a.astype(np.float32) + b.astype(np.float32))
    assert native.sum_into(a, b)
    np.testing.assert_allclose(a.astype(np.float32), expected, atol=1e-2)


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(2)
    arrays = [rng.randn(n).astype(np.float32) for n in (3, 17, 256)]
    total = sum(a.nbytes for a in arrays)
    dst = np.empty(total, np.uint8)
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * len(arrays))(*[a.nbytes for a in arrays])
    lib.hvd_pack(srcs, sizes, len(arrays),
                 dst.ctypes.data_as(ctypes.c_void_p))
    expected = np.concatenate([a.view(np.uint8) for a in arrays])
    np.testing.assert_array_equal(dst, expected)

    outs = [np.empty_like(a) for a in arrays]
    dsts = (ctypes.c_void_p * len(outs))(*[o.ctypes.data for o in outs])
    lib.hvd_unpack(dst.ctypes.data_as(ctypes.c_void_p), sizes,
                   len(outs), dsts)
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_pack_wrapper_matches_concatenate():
    """native.pack — the fusion-buffer hot path the host planes call —
    must equal numpy concatenation and refuse mixed dtypes."""
    rng = np.random.RandomState(3)
    import ml_dtypes
    for dtype in (np.float32, np.int64, ml_dtypes.bfloat16):
        arrays = [rng.randn(n).astype(dtype) for n in (1, 5, 64, 1000)]
        out = native.pack(arrays)
        assert out is not None and out.dtype == arrays[0].dtype
        np.testing.assert_array_equal(
            out.view(np.uint8), np.concatenate(
                [a.view(np.uint8).reshape(-1) for a in arrays]))
    mixed = [np.ones(3, np.float32), np.ones(3, np.float64)]
    assert native.pack(mixed) is None  # caller falls back


@pytest.mark.parametrize("secret", [b"", b"sharedsecret"])
def test_frame_transport_interop(secret):
    """Native gather/broadcast must interoperate with the Python
    Channel framing byte-for-byte."""
    a, b = socket.socketpair()
    c, d = socket.socketpair()
    # python side sends on b and d; native gathers from a and c
    chan_b, chan_d = Channel(b, secret), Channel(d, secret)
    payload0, payload1 = b"from-rank-1", os.urandom(5000)

    t0 = threading.Thread(target=chan_b.send, args=(payload0, 2))
    t1 = threading.Thread(target=chan_d.send, args=(payload1, 2))
    t0.start(); t1.start()

    n = 2
    u8p = ctypes.POINTER(ctypes.c_uint8)
    fds = (ctypes.c_int * n)(a.fileno(), c.fileno())
    bufs = (u8p * n)()
    lens = (ctypes.c_int64 * n)()
    tags = (ctypes.c_uint8 * n)()
    sec = (ctypes.c_uint8 * max(1, len(secret)))(*secret)
    arrive = (ctypes.c_double * n)()
    rc = lib.hvd_gather_frames(fds, n, sec, len(secret), bufs, lens,
                               tags, 5000, arrive)
    assert rc == 0
    assert ctypes.string_at(bufs[0], lens[0]) == payload0
    assert ctypes.string_at(bufs[1], lens[1]) == payload1
    assert tags[0] == 2 and tags[1] == 2
    # arrival stamps: CLOCK_MONOTONIC, comparable to time.monotonic()
    import time as _time
    now = _time.monotonic()
    for i in range(n):
        assert 0 < arrive[i] <= now + 1.0, (i, arrive[i], now)
    for i in range(n):
        lib.hvd_free(bufs[i])
    t0.join(); t1.join()

    # native broadcast → python recv
    msg = b"response-list-bytes"
    mb = (ctypes.c_uint8 * len(msg))(*msg)
    rc = lib.hvd_broadcast_frame(fds, n, 3, mb, len(msg), sec,
                                 len(secret))
    assert rc == 0
    assert chan_b.recv() == (3, msg)
    assert chan_d.recv() == (3, msg)
    for s in (a, b, c, d):
        s.close()


def test_frame_transport_rejects_bad_hmac():
    a, b = socket.socketpair()
    chan_bad = Channel(b, b"WRONG-secret")
    t = threading.Thread(target=chan_bad.send, args=(b"payload", 2))
    t.start()
    n = 1
    u8p = ctypes.POINTER(ctypes.c_uint8)
    fds = (ctypes.c_int * n)(a.fileno())
    bufs = (u8p * n)()
    lens = (ctypes.c_int64 * n)()
    tags = (ctypes.c_uint8 * n)()
    secret = b"right-secret"
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    rc = lib.hvd_gather_frames(fds, n, sec, len(secret), bufs, lens,
                               tags, 5000, None)
    assert rc != 0  # EBADMSG
    t.join()
    a.close(); b.close()


@pytest.mark.parametrize("secret", [b"", b"sharedsecret"])
def test_sendv_interop_with_python_channel(secret):
    """Channel.sendv (native scatter-gather sendmsg) must produce
    byte-identical frames to the Python path: header, HMAC over
    tag|payload, payload = concatenation of the parts."""
    a, b = socket.socketpair()
    ca, cb = Channel(a, secret), Channel(b, secret)
    parts = [b"prefix", np.arange(5000, dtype=np.float64),
             memoryview(b"tail")]
    t = threading.Thread(target=ca.sendv, args=(parts, 9))
    t.start()
    tag, data = cb.recv()
    t.join()
    assert tag == 9
    assert data == b"prefix" + parts[1].tobytes() + b"tail"
    a.close(); b.close()


def test_recv_into_native_skips_and_spills():
    """hvd_recv_into: skip-tags are drained+authenticated+discarded,
    a fitting frame lands in the caller buffer, and an oversized frame
    comes back whole via the spill pointer."""
    secret = b"s3cret"
    a, b = socket.socketpair()
    sender = Channel(b, secret)
    for payload, tag in ((b"ping!", 5), (os.urandom(3000), 4)):
        threading.Thread(target=sender.send,
                         args=(payload, tag)).start()
    buf = np.zeros(4096, np.uint8)
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    skip = (ctypes.c_uint8 * 1)(5)
    out_len = ctypes.c_int64()
    out_tag = ctypes.c_uint8()
    spill = ctypes.POINTER(ctypes.c_uint8)()
    rc = lib.hvd_recv_into(
        a.fileno(), sec, len(secret),
        buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
        skip, 1, ctypes.byref(out_len), ctypes.byref(out_tag),
        5000, 100, ctypes.byref(spill))
    assert rc == 0 and out_tag.value == 4 and out_len.value == 3000
    # the PING was skipped; the data frame landed in the buffer
    big = os.urandom(8192)
    threading.Thread(target=sender.send, args=(big, 4)).start()
    rc = lib.hvd_recv_into(
        a.fileno(), sec, len(secret),
        buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
        skip, 1, ctypes.byref(out_len), ctypes.byref(out_tag),
        5000, 100, ctypes.byref(spill))
    assert rc == 1 and out_len.value == len(big)
    assert ctypes.string_at(spill, out_len.value) == big
    lib.hvd_free(spill)
    a.close(); b.close()


def _steady_c_parts(epoch, nslots, mask, seg):
    """ctypes bundle for one-segment steady calls, with the prefix and
    header coming from wire.spec_frame_parts — the SAME single source
    the runtime uses, so these tests pin C/Python byte identity."""
    from horovod_tpu.common import wire
    from horovod_tpu.common.message import DataType
    prefix, hdrs = wire.spec_frame_parts(
        epoch, nslots, mask, [(DataType.FLOAT64, seg.nbytes)])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    mk = lambda b: (ctypes.c_uint8 * len(b)).from_buffer_copy(b)
    pre = mk(prefix)
    hdr = mk(hdrs[0])
    return {
        "prefix": pre, "prefix_len": len(prefix),
        "hdr_keep": hdr,
        "hdrs": (u8p * 1)(ctypes.cast(hdr, u8p)),
        "hdr_lens": (ctypes.c_int64 * 1)(len(hdrs[0])),
        "seg_lens": (ctypes.c_int64 * 1)(seg.nbytes),
        "seg_codes": (ctypes.c_int * 1)(1),  # f64 native code
        "u8p": u8p,
    }


@pytest.mark.parametrize("secret", [b"", b"steady-secret"])
def test_native_steady_cycle_roundtrip(secret):
    """Full steady cycle: two hvd_steady_worker clients against one
    hvd_steady_coord — the coordinator reduces every rank's segment
    into its own accumulator and every rank ends with the world sum,
    with zero Python-side frame assembly."""
    n = 2
    epoch, nslots, mask = 11, 64, 0b101
    seg = np.arange(2048, dtype=np.float64)
    c = _steady_c_parts(epoch, nslots, mask, seg)
    sec = (ctypes.c_uint8 * max(1, len(secret))).from_buffer_copy(
        secret or b"\x00")
    skip = (ctypes.c_uint8 * 2)(5, 7)
    pairs = [socket.socketpair() for _ in range(n)]
    results = {}

    def worker(sock, rank):
        data = seg * (rank + 1)
        recv = np.empty_like(data)
        send_ptrs = (ctypes.c_void_p * 1)(data.ctypes.data)
        recv_ptrs = (ctypes.c_void_p * 1)(recv.ctypes.data)
        dev = ctypes.POINTER(ctypes.c_uint8)()
        dl = ctypes.c_int64()
        dt = ctypes.c_uint8()
        rc = lib.hvd_steady_worker(
            sock.fileno(), 2, 3, c["prefix"], c["prefix_len"],
            c["hdrs"], c["hdr_lens"], send_ptrs, recv_ptrs,
            c["seg_lens"], 1, sec, len(secret), skip, 2, 5000, 100,
            ctypes.byref(dev), ctypes.byref(dl), ctypes.byref(dt))
        results[rank] = (rc, recv)

    threads = [threading.Thread(target=worker,
                                args=(pairs[i][1], i + 1))
               for i in range(n)]
    for t in threads:
        t.start()
    acc = seg * 1.0  # coordinator's own contribution
    scratch = np.empty((n, seg.size), np.float64)
    fds = (ctypes.c_int * n)(*[pairs[i][0].fileno() for i in range(n)])
    peer_ptrs = (c["u8p"] * n)(*[
        ctypes.cast(ctypes.c_void_p(scratch[i].ctypes.data), c["u8p"])
        for i in range(n)])
    acc_ptrs = (ctypes.c_void_p * 1)(acc.ctypes.data)
    done = (ctypes.c_uint8 * n)()
    dev_idx = ctypes.c_int(-1)
    dev = ctypes.POINTER(ctypes.c_uint8)()
    dl = ctypes.c_int64()
    dt = ctypes.c_uint8()
    import horovod_tpu.native as _nat
    arrive = (ctypes.c_double * n)()
    rc = lib.hvd_steady_coord(
        fds, n, 2, 3, c["prefix"], c["prefix_len"], c["hdrs"],
        c["hdr_lens"], c["seg_lens"], c["seg_codes"], 1, peer_ptrs,
        acc_ptrs, sec, len(secret), skip, 2, 5000, 100,
        _nat.ON_IDLE_FUNC(0), done, arrive, ctypes.byref(dev_idx),
        ctypes.byref(dev), ctypes.byref(dl), ctypes.byref(dt))
    for t in threads:
        t.join()
    assert rc == 0, rc
    import time as _time
    now = _time.monotonic()
    for i in range(n):  # per-peer arrival stamps on the steady gather
        assert 0 < arrive[i] <= now + 1.0, (i, arrive[i], now)
    expect = seg * (1.0 + 2.0 + 3.0)
    np.testing.assert_allclose(acc, expect)
    for r in (1, 2):
        rcw, recv = results[r]
        assert rcw == 0, rcw
        np.testing.assert_allclose(recv, expect)


def test_native_steady_coord_deviation_returns_classic_frame():
    """A peer that sends a CLASSIC frame instead of the expected
    steady layout must come back to Python whole (deviation), exactly
    as sent — the fallback path feeds it to the normal parser."""
    from horovod_tpu.common import wire
    from horovod_tpu.common.message import CacheCycleRequest
    secret = b"devsecret"
    epoch, nslots, mask = 11, 64, 0b101
    seg = np.arange(128, dtype=np.float64)
    c = _steady_c_parts(epoch, nslots, mask, seg)
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    skip = (ctypes.c_uint8 * 1)(5)
    a, b = socket.socketpair()
    classic = wire.serialize_cycle_request(CacheCycleRequest(
        epoch=epoch, nslots=nslots, hit_mask=mask))
    t = threading.Thread(target=Channel(b, secret).send,
                         args=(classic, 2))
    t.start()
    scratch = np.empty(seg.size, np.float64)
    acc = seg.copy()
    fds = (ctypes.c_int * 1)(a.fileno())
    peer_ptrs = (c["u8p"] * 1)(
        ctypes.cast(ctypes.c_void_p(scratch.ctypes.data), c["u8p"]))
    acc_ptrs = (ctypes.c_void_p * 1)(acc.ctypes.data)
    done = (ctypes.c_uint8 * 1)()
    dev_idx = ctypes.c_int(-1)
    dev = ctypes.POINTER(ctypes.c_uint8)()
    dl = ctypes.c_int64()
    dt = ctypes.c_uint8()
    import horovod_tpu.native as _nat
    rc = lib.hvd_steady_coord(
        fds, 1, 2, 3, c["prefix"], c["prefix_len"], c["hdrs"],
        c["hdr_lens"], c["seg_lens"], c["seg_codes"], 1, peer_ptrs,
        acc_ptrs, sec, len(secret), skip, 1, 5000, 100,
        _nat.ON_IDLE_FUNC(0), done, None, ctypes.byref(dev_idx),
        ctypes.byref(dev), ctypes.byref(dl), ctypes.byref(dt))
    t.join()
    assert rc == 1 and dev_idx.value == 0 and dt.value == 2
    got = ctypes.string_at(dev, dl.value)
    lib.hvd_free(dev)
    assert got == classic
    parsed = wire.parse_cycle_request(got)
    assert parsed.hit_mask == mask and parsed.epoch == epoch
    a.close(); b.close()


def test_sum_into_bfloat16_matches_numpy_rne():
    """Native bf16 sum (f32 accumulate + round-to-nearest-even) must
    agree bitwise with ml_dtypes' own bf16 addition."""
    ml_dtypes = pytest.importorskip("ml_dtypes")
    rng = np.random.RandomState(11)
    a = (rng.randn(4096) * 3).astype(ml_dtypes.bfloat16)
    b = (rng.randn(4096) * 3).astype(ml_dtypes.bfloat16)
    ref = a.copy()
    ref += b  # ml_dtypes: f32 math + RNE cast
    acc = a.copy()
    assert native.sum_into(acc, b), "native bf16 sum unavailable"
    assert acc.tobytes() == ref.tobytes(), "bitwise mismatch vs RNE"
    # specials survive
    sp = np.array([np.inf, -np.inf, np.nan, 0.0],
                  ml_dtypes.bfloat16)
    add = np.array([1.0, 1.0, 1.0, -0.0], ml_dtypes.bfloat16)
    acc = sp.copy()
    assert native.sum_into(acc, add)
    out = np.asarray(acc, np.float32)
    assert np.isposinf(out[0]) and np.isneginf(out[1])
    assert np.isnan(out[2]) and out[3] == 0.0


# ---- PR 16: batched reactor, zero-copy sends, int8 codec, relay ------

def _wd():
    from horovod_tpu.common import wire_dtype as wd
    return wd


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_quant8_bit_identical_to_numpy(dtype):
    """hvd_quant8 (plain mode) must produce the exact bytes of the
    numpy reference leg — same scale narrowing, round-half-even,
    saturation — so mixed native/numpy worlds stay convergent."""
    wd = _wd()
    rng = np.random.RandomState(21)
    for arr in (rng.randn(1337).astype(dtype) * 40,
                np.zeros(64, dtype),                    # scale-0 path
                np.array([1e-30, -1e-30, 127.0, -127.0, 0.5], dtype),
                rng.randn(1).astype(dtype)):
        ref = np.empty(4 + arr.size, np.uint8)
        wd._quantize_numpy(arr.copy(), ref)
        nat = np.empty(4 + arr.size, np.uint8)
        assert native.quant8(arr, nat), "native quant8 unavailable"
        assert nat.tobytes() == ref.tobytes()


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_dequant8_bit_identical_to_numpy(dtype):
    wd = _wd()
    rng = np.random.RandomState(22)
    arr = rng.randn(999).astype(dtype) * 7
    buf = wd.quantize(arr)
    # numpy reference expansion
    scale = float(buf[:4].view(np.float32)[0])
    q = buf[4:].view(np.int8)
    ref = q.astype(dtype) * np.asarray(scale, dtype)
    out = np.empty(arr.size, dtype)
    assert native.dequant8(buf, out), "native dequant8 unavailable"
    assert out.tobytes() == ref.tobytes()


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_quant8_fused_ef_matches_classic_triple(dtype):
    """The fused residual mode must equal the classic
    apply -> quantize -> update triple bit-for-bit: same wire bytes
    AND same next-step residual, including when residual_out aliases
    residual."""
    wd = _wd()
    rng = np.random.RandomState(23)
    arr = rng.randn(513).astype(dtype)
    res = (rng.randn(513) * 0.01).astype(dtype)

    # classic triple (pure numpy)
    comp = arr + res
    ref_buf = np.empty(4 + arr.size, np.uint8)
    wd._quantize_numpy(comp, ref_buf)
    scale = float(ref_buf[:4].view(np.float32)[0])
    sent = ref_buf[4:].view(np.int8).astype(dtype) \
        * np.asarray(scale, dtype)
    ref_res = comp - sent

    # fused, separate residual_out
    nat_buf = np.empty(4 + arr.size, np.uint8)
    res_out = np.empty(arr.size, dtype)
    assert native.quant8(arr, nat_buf, residual=res,
                         residual_out=res_out)
    assert nat_buf.tobytes() == ref_buf.tobytes()
    assert res_out.tobytes() == ref_res.tobytes()

    # fused, residual_out ALIASES residual (the store's hot shape)
    alias = res.copy()
    nat_buf2 = np.empty(4 + arr.size, np.uint8)
    assert native.quant8(arr, nat_buf2, residual=alias,
                         residual_out=alias)
    assert nat_buf2.tobytes() == ref_buf.tobytes()
    assert alias.tobytes() == ref_res.tobytes()


def test_quant8_residual_without_out_rejected():
    """residual without residual_out would silently drop the error
    chain — the wrapper must refuse and route to the fallback."""
    arr = np.ones(8, np.float32)
    buf = np.empty(12, np.uint8)
    assert not native.quant8(arr, buf, residual=np.zeros(8, np.float32))


def test_quantize_ef_roundtrip_chain_native_vs_numpy():
    """Two steady steps through wire_dtype.quantize_ef must yield the
    same bytes whether the native codec serves them or not (the
    convergence-parity contract, in-process edition)."""
    wd = _wd()
    rng = np.random.RandomState(24)
    steps = [rng.randn(257).astype(np.float32) for _ in range(3)]
    key = ("t",)
    ef_nat, ef_np = wd.ErrorFeedback(), wd.ErrorFeedback()
    for arr in steps:
        nat = wd.quantize_ef(arr, ef_nat, key)
        # classic triple, forced
        comp = ef_np.apply(key, arr)
        ref = np.empty(4 + arr.size, np.uint8)
        wd._quantize_numpy(comp, ref)
        ef_np.update(key, comp, ref)
        assert nat.tobytes() == ref.tobytes()


def test_build_flags_shape():
    """bit1 (runtime io_uring) implies bit0 (compiled); the trace
    build_info string renders the same bits."""
    f = native.build_flags()
    assert f >= 0
    if f & 2:
        assert f & 1, "runtime probe set without compiled support"
    from horovod_tpu.common.trace import build_info
    names = build_info()["flags"]
    assert (("io_uring" in names.split("+")) == bool(f & 1))
    assert (("io_uring_rt" in names) == bool(f & 2))
    assert (("zerocopy" in names) == bool(f & 4))


def _batched_call(fds, secret, want_tag, caps, timeout_ms=5000,
                  done=None, skip=(1,)):
    """One hvd_gather_frames_batched invocation with fresh out-params;
    returns (rc, bufs, lens, done, arrive, batches, dev)."""
    n = len(fds)
    sec = (ctypes.c_uint8 * max(1, len(secret)))(*secret)
    bufs = [np.zeros(c, np.uint8) for c in caps]
    bufp = (ctypes.c_void_p * n)(*[b.ctypes.data for b in bufs])
    capv = (ctypes.c_int64 * n)(*caps)
    lens = (ctypes.c_int64 * n)()
    skipv = (ctypes.c_uint8 * max(1, len(skip)))(*skip)
    if done is None:
        done = (ctypes.c_uint8 * n)()
    arrive = (ctypes.c_double * n)()
    batch = (ctypes.c_int32 * n)()
    nbatch = ctypes.c_int(0)
    dev_idx = ctypes.c_int(-2)
    dev_buf = ctypes.POINTER(ctypes.c_uint8)()
    dev_len = ctypes.c_int64(0)
    dev_tag = ctypes.c_uint8(0)
    fdv = (ctypes.c_int * n)(*fds)
    rc = lib.hvd_gather_frames_batched(
        fdv, n, sec, len(secret), want_tag, bufp, capv, lens,
        skipv, len(skip), timeout_ms, 100, native.NULL_ON_IDLE,
        done, arrive, batch, ctypes.byref(nbatch),
        ctypes.byref(dev_idx), ctypes.byref(dev_buf),
        ctypes.byref(dev_len), ctypes.byref(dev_tag))
    return (rc, bufs, list(lens), done, list(arrive),
            list(batch[:nbatch.value]),
            (dev_idx.value, dev_buf, dev_len.value, dev_tag.value))


@pytest.mark.parametrize("secret", [b"", b"reactor-secret"])
def test_gather_batched_interop_and_stamps(secret):
    """The batched reactor must absorb frames from plain Python
    Channels (wire identical), skip PINGs in C, stamp arrivals on
    CLOCK_MONOTONIC, and report its batching histogram."""
    import time as _time
    pairs = [socket.socketpair() for _ in range(3)]
    chans = [Channel(b, secret) for _, b in pairs]
    payloads = [os.urandom(100 + 1000 * i) for i in range(3)]
    threads = [threading.Thread(target=c.send, args=(p, 7))
               for c, p in zip(chans, payloads)]
    # rank 1 also fires a PING first — must be drained in C
    ping = threading.Thread(target=chans[1].send, args=(b"", 1))
    ping.start(); ping.join()
    for t in threads:
        t.start()
    rc, bufs, lens, done, arrive, batches, _ = _batched_call(
        [a.fileno() for a, _ in pairs], secret, 7,
        [len(p) + 64 for p in payloads])
    for t in threads:
        t.join()
    assert rc == 0
    assert list(done) == [1, 1, 1]
    now = _time.monotonic()
    for i, p in enumerate(payloads):
        assert lens[i] == len(p)
        assert bufs[i][:lens[i]].tobytes() == p
        assert 0 < arrive[i] <= now + 1.0
    assert batches and sum(batches) == 3  # histogram covers every frame
    for a, b in pairs:
        a.close(); b.close()


def test_gather_batched_deviation_and_reentry(secret=b"s"):
    """A non-skip foreign tag must surface as a deviation (rc 1, frame
    spilled, peer named) and a re-entry with the done map must finish
    the remaining peers without re-reading absorbed ones."""
    pairs = [socket.socketpair() for _ in range(2)]
    chans = [Channel(b, secret) for _, b in pairs]
    t0 = threading.Thread(target=chans[0].send, args=(b"data-0", 7))
    tdev = threading.Thread(target=chans[1].send,
                            args=(b"metrics-blob", 9))
    t0.start(); tdev.start()
    fds = [a.fileno() for a, _ in pairs]
    rc, bufs, lens, done, _, _, dev = _batched_call(
        fds, secret, 7, [4096, 4096])
    t0.join(); tdev.join()
    assert rc == 1
    dev_idx, dev_buf, dev_len, dev_tag = dev
    assert dev_idx == 1 and dev_tag == 9
    assert ctypes.string_at(dev_buf, dev_len) == b"metrics-blob"
    lib.hvd_free(dev_buf)
    # the deviating peer now sends its real frame; re-enter with done
    t1 = threading.Thread(target=chans[1].send, args=(b"data-1", 7))
    t1.start()
    rc2, bufs2, lens2, done2, _, _, _ = _batched_call(
        fds, secret, 7, [4096, 4096], done=done)
    t1.join()
    assert rc2 == 0 and list(done2) == [1, 1]
    assert bufs2[1][:lens2[1]].tobytes() == b"data-1"
    # peer 0 was NOT re-read: its buffer stayed untouched on re-entry
    assert lens2[0] == 0 or bufs2[0][:lens2[0]].tobytes() == b"data-0"
    for a, b in pairs:
        a.close(); b.close()


def test_gather_batched_timeout_names_world():
    a, b = socket.socketpair()
    rc, _, _, _, _, _, dev = _batched_call(
        [a.fileno()], b"", 7, [64], timeout_ms=150)
    assert rc < 0 and dev[0] == -1  # world-wide silence
    a.close(); b.close()


@pytest.mark.parametrize("secret", [b"", b"zc-secret"])
def test_sendv_zc_interop_with_python_channel(secret):
    """hvd_sendv_zc must put byte-identical frames on the wire (the
    Python Channel parses them) whether or not the kernel honors
    SO_ZEROCOPY on this socket family."""
    a, b = socket.socketpair()
    chan = Channel(b, secret)
    parts = [b"head", os.urandom(200_000), b"tail"]
    arrs = [np.frombuffer(p, np.uint8) for p in parts]
    bufp = (ctypes.c_void_p * 3)(*[x.ctypes.data for x in arrs])
    lens = (ctypes.c_int64 * 3)(*[x.nbytes for x in arrs])
    sec = (ctypes.c_uint8 * max(1, len(secret)))(*secret)
    zc_sends = ctypes.c_int(0)
    zc_copied = ctypes.c_int(0)
    got = {}

    def _recv():
        got["frame"] = chan.recv()
    t = threading.Thread(target=_recv)
    t.start()
    rc = lib.hvd_sendv_zc(a.fileno(), 7, bufp, lens, 3, sec,
                          len(secret), 5000, ctypes.byref(zc_sends),
                          ctypes.byref(zc_copied))
    t.join(timeout=10)
    assert rc == 0
    assert got["frame"] == (7, b"".join(parts))
    # AF_UNIX rejects SO_ZEROCOPY → plain-send fallback: counters may
    # be zero; they must never go negative or report copies > sends.
    assert zc_sends.value >= 0
    assert 0 <= zc_copied.value <= max(zc_sends.value, zc_copied.value)
    a.close(); b.close()


def _relay_call(up_fd, child_fds, secret, want_tag, cap=1 << 16,
                chunk=4096, timeout_ms=5000, skip=()):
    fdv = (ctypes.c_int * max(1, len(child_fds)))(
        *(child_fds or [-1]))
    buf = np.zeros(cap, np.uint8)
    sec = (ctypes.c_uint8 * max(1, len(secret)))(*secret)
    skipv = (ctypes.c_uint8 * max(1, len(skip)))(*(skip or [0]))
    out_len = ctypes.c_int64(0)
    out_tag = ctypes.c_uint8(0)
    spill = ctypes.POINTER(ctypes.c_uint8)()
    rc = lib.hvd_relay_frame(
        up_fd, fdv, len(child_fds), want_tag,
        ctypes.c_void_p(buf.ctypes.data), cap, sec, len(secret),
        skipv if skip else None, len(skip), chunk, timeout_ms, 100,
        ctypes.byref(out_len), ctypes.byref(out_tag),
        ctypes.byref(spill))
    return rc, buf, out_len.value, out_tag.value, spill


@pytest.mark.parametrize("secret", [b"", b"relay-secret"])
def test_relay_frame_cut_through_interop(secret):
    """One frame in at the top must come out byte-identical at every
    child (chunked through a 4 KiB window, so multiple chunks), AND
    land in the relay's own buffer."""
    up_a, up_b = socket.socketpair()
    kids = [socket.socketpair() for _ in range(2)]
    sender = Channel(up_b, secret)
    payload = os.urandom(50_000)  # ~13 chunks at 4 KiB
    t = threading.Thread(target=sender.send, args=(payload, 11))
    t.start()
    got = {}

    def _kid(i, sock):
        got[i] = Channel(sock, secret).recv()
    kts = [threading.Thread(target=_kid, args=(i, b))
           for i, (_, b) in enumerate(kids)]
    for kt in kts:
        kt.start()
    rc, buf, out_len, out_tag, _ = _relay_call(
        up_a.fileno(), [a.fileno() for a, _ in kids], secret, 11)
    t.join()
    for kt in kts:
        kt.join(timeout=10)
    assert rc == 0 and out_tag == 11 and out_len == len(payload)
    assert buf[:out_len].tobytes() == payload
    assert got[0] == (11, payload) and got[1] == (11, payload)
    for a, b in kids:
        a.close(); b.close()
    up_a.close(); up_b.close()


def test_relay_frame_spill_and_deviation():
    """cap overflow: rc 1, children still got the whole frame, payload
    complete in *spill. Foreign tag: rc 2, NOT relayed."""
    secret = b"x"
    up_a, up_b = socket.socketpair()
    kid_a, kid_b = socket.socketpair()
    sender = Channel(up_b, secret)
    big = os.urandom(9000)
    t = threading.Thread(target=sender.send, args=(big, 11))
    t.start()
    got = {}
    kt = threading.Thread(
        target=lambda: got.update(f=Channel(kid_b, secret).recv()))
    kt.start()
    rc, _, out_len, _, spill = _relay_call(
        up_a.fileno(), [kid_a.fileno()], secret, 11, cap=1024,
        chunk=512)
    t.join(); kt.join(timeout=10)
    assert rc == 1 and out_len == len(big)
    assert ctypes.string_at(spill, out_len) == big
    lib.hvd_free(spill)
    assert got["f"] == (11, big)

    # deviation: an ABORT-class tag must NOT be forwarded downstream
    t = threading.Thread(target=sender.send, args=(b"abort!", 4))
    t.start()
    rc, _, out_len, out_tag, spill = _relay_call(
        up_a.fileno(), [kid_a.fileno()], secret, 11)
    t.join()
    assert rc == 2 and out_tag == 4
    assert ctypes.string_at(spill, out_len) == b"abort!"
    lib.hvd_free(spill)
    kid_b.setblocking(False)
    with pytest.raises(BlockingIOError):
        kid_b.recv(1)  # nothing went downstream
    for s in (up_a, up_b, kid_a, kid_b):
        s.close()


def test_relay_frame_skip_tags_drained():
    """PING-class tags in skip_tags are absorbed (not relayed, not
    returned) and the relay keeps waiting for the wanted frame."""
    secret = b"y"
    up_a, up_b = socket.socketpair()
    kid_a, kid_b = socket.socketpair()
    sender = Channel(up_b, secret)
    threading.Thread(target=sender.send, args=(b"", 1)).start()
    t = threading.Thread(target=sender.send, args=(b"real", 11))
    t.start()
    got = {}
    kt = threading.Thread(
        target=lambda: got.update(f=Channel(kid_b, secret).recv()))
    kt.start()
    rc, buf, out_len, out_tag, _ = _relay_call(
        up_a.fileno(), [kid_a.fileno()], secret, 11, skip=(1,))
    t.join(); kt.join(timeout=10)
    assert rc == 0 and out_tag == 11
    assert buf[:out_len].tobytes() == b"real"
    assert got["f"] == (11, b"real")  # only the real frame relayed
    for s in (up_a, up_b, kid_a, kid_b):
        s.close()
