"""Native core (native/libhvdtpu.so) correctness: HMAC vs hashlib,
reductions vs numpy, pack/unpack round-trip, and frame transport vs the
Python Channel implementation. Skipped wholesale when no compiler/lib
is available — every native path has a Python fallback."""

import ctypes
import hashlib
import hmac
import os
import socket
import threading

import numpy as np
import pytest

import horovod_tpu.native as native
from horovod_tpu.common.network import Channel


lib = native.get()
pytestmark = pytest.mark.skipif(lib is None,
                                reason="native core unavailable")


def _hmac_native(key: bytes, tag: int, payload: bytes) -> bytes:
    out = (ctypes.c_uint8 * 32)()
    kb = (ctypes.c_uint8 * max(1, len(key)))(*key)
    pb = (ctypes.c_uint8 * max(1, len(payload)))(*payload)
    lib.hvd_hmac_sha256(kb, len(key), tag, pb, len(payload), out)
    return bytes(out)


@pytest.mark.parametrize("key,payload", [
    (b"k", b""),
    (b"secretkey123", b"hello"),
    (b"x" * 64, b"y" * 4096),
    (b"z" * 100, os.urandom(100001)),  # key > block size, multi-block
])
def test_hmac_matches_hashlib(key, payload):
    expected = hmac.new(key, bytes([5]) + payload, hashlib.sha256).digest()
    assert _hmac_native(key, 5, payload) == expected


@pytest.mark.parametrize("dtype,tol", [
    (np.float32, 1e-6), (np.float64, 1e-12),
    (np.int32, 0), (np.int64, 0), (np.uint8, 0),
])
def test_sum_into_matches_numpy(dtype, tol):
    rng = np.random.RandomState(0)
    if np.issubdtype(dtype, np.floating):
        a = rng.randn(1337).astype(dtype)
        b = rng.randn(1337).astype(dtype)
    else:
        a = rng.randint(0, 100, 1337).astype(dtype)
        b = rng.randint(0, 100, 1337).astype(dtype)
    expected = a + b
    assert native.sum_into(a, b)
    if tol:
        np.testing.assert_allclose(a, expected, rtol=tol)
    else:
        np.testing.assert_array_equal(a, expected)


def test_sum_into_float16():
    rng = np.random.RandomState(1)
    a = rng.randn(257).astype(np.float16)
    b = rng.randn(257).astype(np.float16)
    expected = (a.astype(np.float32) + b.astype(np.float32))
    assert native.sum_into(a, b)
    np.testing.assert_allclose(a.astype(np.float32), expected, atol=1e-2)


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(2)
    arrays = [rng.randn(n).astype(np.float32) for n in (3, 17, 256)]
    total = sum(a.nbytes for a in arrays)
    dst = np.empty(total, np.uint8)
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * len(arrays))(*[a.nbytes for a in arrays])
    lib.hvd_pack(srcs, sizes, len(arrays),
                 dst.ctypes.data_as(ctypes.c_void_p))
    expected = np.concatenate([a.view(np.uint8) for a in arrays])
    np.testing.assert_array_equal(dst, expected)

    outs = [np.empty_like(a) for a in arrays]
    dsts = (ctypes.c_void_p * len(outs))(*[o.ctypes.data for o in outs])
    lib.hvd_unpack(dst.ctypes.data_as(ctypes.c_void_p), sizes,
                   len(outs), dsts)
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_pack_wrapper_matches_concatenate():
    """native.pack — the fusion-buffer hot path the host planes call —
    must equal numpy concatenation and refuse mixed dtypes."""
    rng = np.random.RandomState(3)
    import ml_dtypes
    for dtype in (np.float32, np.int64, ml_dtypes.bfloat16):
        arrays = [rng.randn(n).astype(dtype) for n in (1, 5, 64, 1000)]
        out = native.pack(arrays)
        assert out is not None and out.dtype == arrays[0].dtype
        np.testing.assert_array_equal(
            out.view(np.uint8), np.concatenate(
                [a.view(np.uint8).reshape(-1) for a in arrays]))
    mixed = [np.ones(3, np.float32), np.ones(3, np.float64)]
    assert native.pack(mixed) is None  # caller falls back


@pytest.mark.parametrize("secret", [b"", b"sharedsecret"])
def test_frame_transport_interop(secret):
    """Native gather/broadcast must interoperate with the Python
    Channel framing byte-for-byte."""
    a, b = socket.socketpair()
    c, d = socket.socketpair()
    # python side sends on b and d; native gathers from a and c
    chan_b, chan_d = Channel(b, secret), Channel(d, secret)
    payload0, payload1 = b"from-rank-1", os.urandom(5000)

    t0 = threading.Thread(target=chan_b.send, args=(payload0, 2))
    t1 = threading.Thread(target=chan_d.send, args=(payload1, 2))
    t0.start(); t1.start()

    n = 2
    u8p = ctypes.POINTER(ctypes.c_uint8)
    fds = (ctypes.c_int * n)(a.fileno(), c.fileno())
    bufs = (u8p * n)()
    lens = (ctypes.c_int64 * n)()
    tags = (ctypes.c_uint8 * n)()
    sec = (ctypes.c_uint8 * max(1, len(secret)))(*secret)
    rc = lib.hvd_gather_frames(fds, n, sec, len(secret), bufs, lens,
                               tags, 5000)
    assert rc == 0
    assert ctypes.string_at(bufs[0], lens[0]) == payload0
    assert ctypes.string_at(bufs[1], lens[1]) == payload1
    assert tags[0] == 2 and tags[1] == 2
    for i in range(n):
        lib.hvd_free(bufs[i])
    t0.join(); t1.join()

    # native broadcast → python recv
    msg = b"response-list-bytes"
    mb = (ctypes.c_uint8 * len(msg))(*msg)
    rc = lib.hvd_broadcast_frame(fds, n, 3, mb, len(msg), sec,
                                 len(secret))
    assert rc == 0
    assert chan_b.recv() == (3, msg)
    assert chan_d.recv() == (3, msg)
    for s in (a, b, c, d):
        s.close()


def test_frame_transport_rejects_bad_hmac():
    a, b = socket.socketpair()
    chan_bad = Channel(b, b"WRONG-secret")
    t = threading.Thread(target=chan_bad.send, args=(b"payload", 2))
    t.start()
    n = 1
    u8p = ctypes.POINTER(ctypes.c_uint8)
    fds = (ctypes.c_int * n)(a.fileno())
    bufs = (u8p * n)()
    lens = (ctypes.c_int64 * n)()
    tags = (ctypes.c_uint8 * n)()
    secret = b"right-secret"
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    rc = lib.hvd_gather_frames(fds, n, sec, len(secret), bufs, lens,
                               tags, 5000)
    assert rc != 0  # EBADMSG
    t.join()
    a.close(); b.close()


def test_sum_into_bfloat16_matches_numpy_rne():
    """Native bf16 sum (f32 accumulate + round-to-nearest-even) must
    agree bitwise with ml_dtypes' own bf16 addition."""
    ml_dtypes = pytest.importorskip("ml_dtypes")
    rng = np.random.RandomState(11)
    a = (rng.randn(4096) * 3).astype(ml_dtypes.bfloat16)
    b = (rng.randn(4096) * 3).astype(ml_dtypes.bfloat16)
    ref = a.copy()
    ref += b  # ml_dtypes: f32 math + RNE cast
    acc = a.copy()
    assert native.sum_into(acc, b), "native bf16 sum unavailable"
    assert acc.tobytes() == ref.tobytes(), "bitwise mismatch vs RNE"
    # specials survive
    sp = np.array([np.inf, -np.inf, np.nan, 0.0],
                  ml_dtypes.bfloat16)
    add = np.array([1.0, 1.0, 1.0, -0.0], ml_dtypes.bfloat16)
    acc = sp.copy()
    assert native.sum_into(acc, add)
    out = np.asarray(acc, np.float32)
    assert np.isposinf(out[0]) and np.isneginf(out[1])
    assert np.isnan(out[2]) and out[3] == 0.0
