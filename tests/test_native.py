"""Native core (native/libhvdtpu.so) correctness: HMAC vs hashlib,
reductions vs numpy, pack/unpack round-trip, and frame transport vs the
Python Channel implementation. Skipped wholesale when no compiler/lib
is available — every native path has a Python fallback."""

import ctypes
import hashlib
import hmac
import os
import socket
import threading

import numpy as np
import pytest

import horovod_tpu.native as native
from horovod_tpu.common.network import Channel


lib = native.get()
pytestmark = pytest.mark.skipif(lib is None,
                                reason="native core unavailable")


def _hmac_native(key: bytes, tag: int, payload: bytes) -> bytes:
    out = (ctypes.c_uint8 * 32)()
    kb = (ctypes.c_uint8 * max(1, len(key)))(*key)
    pb = (ctypes.c_uint8 * max(1, len(payload)))(*payload)
    lib.hvd_hmac_sha256(kb, len(key), tag, pb, len(payload), out)
    return bytes(out)


@pytest.mark.parametrize("key,payload", [
    (b"k", b""),
    (b"secretkey123", b"hello"),
    (b"x" * 64, b"y" * 4096),
    (b"z" * 100, os.urandom(100001)),  # key > block size, multi-block
])
def test_hmac_matches_hashlib(key, payload):
    expected = hmac.new(key, bytes([5]) + payload, hashlib.sha256).digest()
    assert _hmac_native(key, 5, payload) == expected


@pytest.mark.parametrize("dtype,tol", [
    (np.float32, 1e-6), (np.float64, 1e-12),
    (np.int32, 0), (np.int64, 0), (np.uint8, 0),
])
def test_sum_into_matches_numpy(dtype, tol):
    rng = np.random.RandomState(0)
    if np.issubdtype(dtype, np.floating):
        a = rng.randn(1337).astype(dtype)
        b = rng.randn(1337).astype(dtype)
    else:
        a = rng.randint(0, 100, 1337).astype(dtype)
        b = rng.randint(0, 100, 1337).astype(dtype)
    expected = a + b
    assert native.sum_into(a, b)
    if tol:
        np.testing.assert_allclose(a, expected, rtol=tol)
    else:
        np.testing.assert_array_equal(a, expected)


def test_sum_into_float16():
    rng = np.random.RandomState(1)
    a = rng.randn(257).astype(np.float16)
    b = rng.randn(257).astype(np.float16)
    expected = (a.astype(np.float32) + b.astype(np.float32))
    assert native.sum_into(a, b)
    np.testing.assert_allclose(a.astype(np.float32), expected, atol=1e-2)


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(2)
    arrays = [rng.randn(n).astype(np.float32) for n in (3, 17, 256)]
    total = sum(a.nbytes for a in arrays)
    dst = np.empty(total, np.uint8)
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * len(arrays))(*[a.nbytes for a in arrays])
    lib.hvd_pack(srcs, sizes, len(arrays),
                 dst.ctypes.data_as(ctypes.c_void_p))
    expected = np.concatenate([a.view(np.uint8) for a in arrays])
    np.testing.assert_array_equal(dst, expected)

    outs = [np.empty_like(a) for a in arrays]
    dsts = (ctypes.c_void_p * len(outs))(*[o.ctypes.data for o in outs])
    lib.hvd_unpack(dst.ctypes.data_as(ctypes.c_void_p), sizes,
                   len(outs), dsts)
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)


def test_pack_wrapper_matches_concatenate():
    """native.pack — the fusion-buffer hot path the host planes call —
    must equal numpy concatenation and refuse mixed dtypes."""
    rng = np.random.RandomState(3)
    import ml_dtypes
    for dtype in (np.float32, np.int64, ml_dtypes.bfloat16):
        arrays = [rng.randn(n).astype(dtype) for n in (1, 5, 64, 1000)]
        out = native.pack(arrays)
        assert out is not None and out.dtype == arrays[0].dtype
        np.testing.assert_array_equal(
            out.view(np.uint8), np.concatenate(
                [a.view(np.uint8).reshape(-1) for a in arrays]))
    mixed = [np.ones(3, np.float32), np.ones(3, np.float64)]
    assert native.pack(mixed) is None  # caller falls back


@pytest.mark.parametrize("secret", [b"", b"sharedsecret"])
def test_frame_transport_interop(secret):
    """Native gather/broadcast must interoperate with the Python
    Channel framing byte-for-byte."""
    a, b = socket.socketpair()
    c, d = socket.socketpair()
    # python side sends on b and d; native gathers from a and c
    chan_b, chan_d = Channel(b, secret), Channel(d, secret)
    payload0, payload1 = b"from-rank-1", os.urandom(5000)

    t0 = threading.Thread(target=chan_b.send, args=(payload0, 2))
    t1 = threading.Thread(target=chan_d.send, args=(payload1, 2))
    t0.start(); t1.start()

    n = 2
    u8p = ctypes.POINTER(ctypes.c_uint8)
    fds = (ctypes.c_int * n)(a.fileno(), c.fileno())
    bufs = (u8p * n)()
    lens = (ctypes.c_int64 * n)()
    tags = (ctypes.c_uint8 * n)()
    sec = (ctypes.c_uint8 * max(1, len(secret)))(*secret)
    arrive = (ctypes.c_double * n)()
    rc = lib.hvd_gather_frames(fds, n, sec, len(secret), bufs, lens,
                               tags, 5000, arrive)
    assert rc == 0
    assert ctypes.string_at(bufs[0], lens[0]) == payload0
    assert ctypes.string_at(bufs[1], lens[1]) == payload1
    assert tags[0] == 2 and tags[1] == 2
    # arrival stamps: CLOCK_MONOTONIC, comparable to time.monotonic()
    import time as _time
    now = _time.monotonic()
    for i in range(n):
        assert 0 < arrive[i] <= now + 1.0, (i, arrive[i], now)
    for i in range(n):
        lib.hvd_free(bufs[i])
    t0.join(); t1.join()

    # native broadcast → python recv
    msg = b"response-list-bytes"
    mb = (ctypes.c_uint8 * len(msg))(*msg)
    rc = lib.hvd_broadcast_frame(fds, n, 3, mb, len(msg), sec,
                                 len(secret))
    assert rc == 0
    assert chan_b.recv() == (3, msg)
    assert chan_d.recv() == (3, msg)
    for s in (a, b, c, d):
        s.close()


def test_frame_transport_rejects_bad_hmac():
    a, b = socket.socketpair()
    chan_bad = Channel(b, b"WRONG-secret")
    t = threading.Thread(target=chan_bad.send, args=(b"payload", 2))
    t.start()
    n = 1
    u8p = ctypes.POINTER(ctypes.c_uint8)
    fds = (ctypes.c_int * n)(a.fileno())
    bufs = (u8p * n)()
    lens = (ctypes.c_int64 * n)()
    tags = (ctypes.c_uint8 * n)()
    secret = b"right-secret"
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    rc = lib.hvd_gather_frames(fds, n, sec, len(secret), bufs, lens,
                               tags, 5000, None)
    assert rc != 0  # EBADMSG
    t.join()
    a.close(); b.close()


@pytest.mark.parametrize("secret", [b"", b"sharedsecret"])
def test_sendv_interop_with_python_channel(secret):
    """Channel.sendv (native scatter-gather sendmsg) must produce
    byte-identical frames to the Python path: header, HMAC over
    tag|payload, payload = concatenation of the parts."""
    a, b = socket.socketpair()
    ca, cb = Channel(a, secret), Channel(b, secret)
    parts = [b"prefix", np.arange(5000, dtype=np.float64),
             memoryview(b"tail")]
    t = threading.Thread(target=ca.sendv, args=(parts, 9))
    t.start()
    tag, data = cb.recv()
    t.join()
    assert tag == 9
    assert data == b"prefix" + parts[1].tobytes() + b"tail"
    a.close(); b.close()


def test_recv_into_native_skips_and_spills():
    """hvd_recv_into: skip-tags are drained+authenticated+discarded,
    a fitting frame lands in the caller buffer, and an oversized frame
    comes back whole via the spill pointer."""
    secret = b"s3cret"
    a, b = socket.socketpair()
    sender = Channel(b, secret)
    for payload, tag in ((b"ping!", 5), (os.urandom(3000), 4)):
        threading.Thread(target=sender.send,
                         args=(payload, tag)).start()
    buf = np.zeros(4096, np.uint8)
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    skip = (ctypes.c_uint8 * 1)(5)
    out_len = ctypes.c_int64()
    out_tag = ctypes.c_uint8()
    spill = ctypes.POINTER(ctypes.c_uint8)()
    rc = lib.hvd_recv_into(
        a.fileno(), sec, len(secret),
        buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
        skip, 1, ctypes.byref(out_len), ctypes.byref(out_tag),
        5000, 100, ctypes.byref(spill))
    assert rc == 0 and out_tag.value == 4 and out_len.value == 3000
    # the PING was skipped; the data frame landed in the buffer
    big = os.urandom(8192)
    threading.Thread(target=sender.send, args=(big, 4)).start()
    rc = lib.hvd_recv_into(
        a.fileno(), sec, len(secret),
        buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
        skip, 1, ctypes.byref(out_len), ctypes.byref(out_tag),
        5000, 100, ctypes.byref(spill))
    assert rc == 1 and out_len.value == len(big)
    assert ctypes.string_at(spill, out_len.value) == big
    lib.hvd_free(spill)
    a.close(); b.close()


def _steady_c_parts(epoch, nslots, mask, seg):
    """ctypes bundle for one-segment steady calls, with the prefix and
    header coming from wire.spec_frame_parts — the SAME single source
    the runtime uses, so these tests pin C/Python byte identity."""
    from horovod_tpu.common import wire
    from horovod_tpu.common.message import DataType
    prefix, hdrs = wire.spec_frame_parts(
        epoch, nslots, mask, [(DataType.FLOAT64, seg.nbytes)])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    mk = lambda b: (ctypes.c_uint8 * len(b)).from_buffer_copy(b)
    pre = mk(prefix)
    hdr = mk(hdrs[0])
    return {
        "prefix": pre, "prefix_len": len(prefix),
        "hdr_keep": hdr,
        "hdrs": (u8p * 1)(ctypes.cast(hdr, u8p)),
        "hdr_lens": (ctypes.c_int64 * 1)(len(hdrs[0])),
        "seg_lens": (ctypes.c_int64 * 1)(seg.nbytes),
        "seg_codes": (ctypes.c_int * 1)(1),  # f64 native code
        "u8p": u8p,
    }


@pytest.mark.parametrize("secret", [b"", b"steady-secret"])
def test_native_steady_cycle_roundtrip(secret):
    """Full steady cycle: two hvd_steady_worker clients against one
    hvd_steady_coord — the coordinator reduces every rank's segment
    into its own accumulator and every rank ends with the world sum,
    with zero Python-side frame assembly."""
    n = 2
    epoch, nslots, mask = 11, 64, 0b101
    seg = np.arange(2048, dtype=np.float64)
    c = _steady_c_parts(epoch, nslots, mask, seg)
    sec = (ctypes.c_uint8 * max(1, len(secret))).from_buffer_copy(
        secret or b"\x00")
    skip = (ctypes.c_uint8 * 2)(5, 7)
    pairs = [socket.socketpair() for _ in range(n)]
    results = {}

    def worker(sock, rank):
        data = seg * (rank + 1)
        recv = np.empty_like(data)
        send_ptrs = (ctypes.c_void_p * 1)(data.ctypes.data)
        recv_ptrs = (ctypes.c_void_p * 1)(recv.ctypes.data)
        dev = ctypes.POINTER(ctypes.c_uint8)()
        dl = ctypes.c_int64()
        dt = ctypes.c_uint8()
        rc = lib.hvd_steady_worker(
            sock.fileno(), 2, 3, c["prefix"], c["prefix_len"],
            c["hdrs"], c["hdr_lens"], send_ptrs, recv_ptrs,
            c["seg_lens"], 1, sec, len(secret), skip, 2, 5000, 100,
            ctypes.byref(dev), ctypes.byref(dl), ctypes.byref(dt))
        results[rank] = (rc, recv)

    threads = [threading.Thread(target=worker,
                                args=(pairs[i][1], i + 1))
               for i in range(n)]
    for t in threads:
        t.start()
    acc = seg * 1.0  # coordinator's own contribution
    scratch = np.empty((n, seg.size), np.float64)
    fds = (ctypes.c_int * n)(*[pairs[i][0].fileno() for i in range(n)])
    peer_ptrs = (c["u8p"] * n)(*[
        ctypes.cast(ctypes.c_void_p(scratch[i].ctypes.data), c["u8p"])
        for i in range(n)])
    acc_ptrs = (ctypes.c_void_p * 1)(acc.ctypes.data)
    done = (ctypes.c_uint8 * n)()
    dev_idx = ctypes.c_int(-1)
    dev = ctypes.POINTER(ctypes.c_uint8)()
    dl = ctypes.c_int64()
    dt = ctypes.c_uint8()
    import horovod_tpu.native as _nat
    arrive = (ctypes.c_double * n)()
    rc = lib.hvd_steady_coord(
        fds, n, 2, 3, c["prefix"], c["prefix_len"], c["hdrs"],
        c["hdr_lens"], c["seg_lens"], c["seg_codes"], 1, peer_ptrs,
        acc_ptrs, sec, len(secret), skip, 2, 5000, 100,
        _nat.ON_IDLE_FUNC(0), done, arrive, ctypes.byref(dev_idx),
        ctypes.byref(dev), ctypes.byref(dl), ctypes.byref(dt))
    for t in threads:
        t.join()
    assert rc == 0, rc
    import time as _time
    now = _time.monotonic()
    for i in range(n):  # per-peer arrival stamps on the steady gather
        assert 0 < arrive[i] <= now + 1.0, (i, arrive[i], now)
    expect = seg * (1.0 + 2.0 + 3.0)
    np.testing.assert_allclose(acc, expect)
    for r in (1, 2):
        rcw, recv = results[r]
        assert rcw == 0, rcw
        np.testing.assert_allclose(recv, expect)


def test_native_steady_coord_deviation_returns_classic_frame():
    """A peer that sends a CLASSIC frame instead of the expected
    steady layout must come back to Python whole (deviation), exactly
    as sent — the fallback path feeds it to the normal parser."""
    from horovod_tpu.common import wire
    from horovod_tpu.common.message import CacheCycleRequest
    secret = b"devsecret"
    epoch, nslots, mask = 11, 64, 0b101
    seg = np.arange(128, dtype=np.float64)
    c = _steady_c_parts(epoch, nslots, mask, seg)
    sec = (ctypes.c_uint8 * len(secret))(*secret)
    skip = (ctypes.c_uint8 * 1)(5)
    a, b = socket.socketpair()
    classic = wire.serialize_cycle_request(CacheCycleRequest(
        epoch=epoch, nslots=nslots, hit_mask=mask))
    t = threading.Thread(target=Channel(b, secret).send,
                         args=(classic, 2))
    t.start()
    scratch = np.empty(seg.size, np.float64)
    acc = seg.copy()
    fds = (ctypes.c_int * 1)(a.fileno())
    peer_ptrs = (c["u8p"] * 1)(
        ctypes.cast(ctypes.c_void_p(scratch.ctypes.data), c["u8p"]))
    acc_ptrs = (ctypes.c_void_p * 1)(acc.ctypes.data)
    done = (ctypes.c_uint8 * 1)()
    dev_idx = ctypes.c_int(-1)
    dev = ctypes.POINTER(ctypes.c_uint8)()
    dl = ctypes.c_int64()
    dt = ctypes.c_uint8()
    import horovod_tpu.native as _nat
    rc = lib.hvd_steady_coord(
        fds, 1, 2, 3, c["prefix"], c["prefix_len"], c["hdrs"],
        c["hdr_lens"], c["seg_lens"], c["seg_codes"], 1, peer_ptrs,
        acc_ptrs, sec, len(secret), skip, 1, 5000, 100,
        _nat.ON_IDLE_FUNC(0), done, None, ctypes.byref(dev_idx),
        ctypes.byref(dev), ctypes.byref(dl), ctypes.byref(dt))
    t.join()
    assert rc == 1 and dev_idx.value == 0 and dt.value == 2
    got = ctypes.string_at(dev, dl.value)
    lib.hvd_free(dev)
    assert got == classic
    parsed = wire.parse_cycle_request(got)
    assert parsed.hit_mask == mask and parsed.epoch == epoch
    a.close(); b.close()


def test_sum_into_bfloat16_matches_numpy_rne():
    """Native bf16 sum (f32 accumulate + round-to-nearest-even) must
    agree bitwise with ml_dtypes' own bf16 addition."""
    ml_dtypes = pytest.importorskip("ml_dtypes")
    rng = np.random.RandomState(11)
    a = (rng.randn(4096) * 3).astype(ml_dtypes.bfloat16)
    b = (rng.randn(4096) * 3).astype(ml_dtypes.bfloat16)
    ref = a.copy()
    ref += b  # ml_dtypes: f32 math + RNE cast
    acc = a.copy()
    assert native.sum_into(acc, b), "native bf16 sum unavailable"
    assert acc.tobytes() == ref.tobytes(), "bitwise mismatch vs RNE"
    # specials survive
    sp = np.array([np.inf, -np.inf, np.nan, 0.0],
                  ml_dtypes.bfloat16)
    add = np.array([1.0, 1.0, 1.0, -0.0], ml_dtypes.bfloat16)
    acc = sp.copy()
    assert native.sum_into(acc, add)
    out = np.asarray(acc, np.float32)
    assert np.isposinf(out[0]) and np.isneginf(out[1])
    assert np.isnan(out[2]) and out[3] == 0.0
