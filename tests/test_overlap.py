"""Unit tests for the overlap tier (common/overlap.py, the chunked
native transfer in common/steady.py, the autotuned bucket count) plus
the satellite regressions that ride this PR (aggregate-frame
truncation, IPv6 loopback leaf filtering, int32-offset guard in the
skewed-allgather psum path)."""

import socket
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import overlap as hoverlap
from horovod_tpu.common.controller import (
    _dialable_leaf_ip, pack_frames, unpack_frames,
)


# -- bucket planner ------------------------------------------------------
def test_plan_buckets_balanced_and_contiguous():
    sizes = [100] * 8
    ends = hoverlap.plan_buckets(sizes, 4, 0)
    assert ends == [2, 4, 6, 8]


def test_plan_buckets_derives_count_from_bytes():
    sizes = [1000] * 10
    ends = hoverlap.plan_buckets(sizes, 0, 2500)  # 10000/2500 = 4
    assert ends is not None and ends[-1] == 10 and len(ends) == 4


def test_plan_buckets_off_and_degenerate():
    assert hoverlap.plan_buckets([100] * 8, 0, 0) is None
    assert hoverlap.plan_buckets([100], 4, 0) is None
    assert hoverlap.plan_buckets([], 4, 0) is None
    assert hoverlap.plan_buckets([0, 0], 4, 0) is None


def test_plan_buckets_clamps_to_tensor_count_and_cap():
    ends = hoverlap.plan_buckets([10, 10, 10], 8, 0)
    assert ends is not None and len(ends) <= 3 and ends[-1] == 3
    ends = hoverlap.plan_buckets([10] * 64, 64, 0)
    assert len(ends) == hoverlap.MAX_BUCKETS


def test_plan_buckets_skewed_sizes_stay_nonempty():
    sizes = [10_000_000, 1, 1, 1]
    ends = hoverlap.plan_buckets(sizes, 4, 0)
    assert ends[-1] == 4
    last = 0
    for e in ends:
        assert e > last  # every bucket non-empty, boundaries ascend
        last = e


def test_plan_buckets_pure_function():
    sizes = [3, 1, 4, 1, 5, 9, 2, 6]
    assert hoverlap.plan_buckets(sizes, 3, 0) \
        == hoverlap.plan_buckets(list(sizes), 3, 0)


# -- overlap runner ------------------------------------------------------
def _mk_cycle(seq, plan=None):
    return hoverlap.InflightCycle(plan or object(), [], [], [], seq)


def test_runner_fifo_order_and_done_flow():
    order = []

    def run_fn(plan, bufs):
        order.append(plan)
        return ("done", plan)

    r = hoverlap.OverlapRunner(run_fn, max_inflight=2)
    try:
        plans = [object() for _ in range(4)]
        for i, p in enumerate(plans):
            r.submit(_mk_cycle(i, p))
        got = []
        deadline = time.monotonic() + 5
        while len(got) < 4 and time.monotonic() < deadline:
            c = r.wait_completed(0.5)
            if c is not None:
                got.append(c)
        assert [c.plan for c in got] == plans  # strict FIFO
        assert order == plans
        assert all(c.outcome[0] == "done" for c in got)
        assert r.cycles_total == 4
    finally:
        r.stop()


def test_runner_deviation_stalls_and_cancel_resumes():
    def run_fn(plan, bufs):
        if plan == "bad":
            return ("frame", b"classic")
        return ("done", plan)

    r = hoverlap.OverlapRunner(run_fn, max_inflight=4)
    try:
        r.submit(_mk_cycle(0, "bad"))
        c = r.wait_completed(5.0)
        assert c is not None and c.outcome == ("frame", b"classic")
        assert r.stalled
        # stalled runner refuses new work until the bg loop resolves
        with pytest.raises(RuntimeError):
            r.submit(_mk_cycle(1, "later"))
        assert r.cancel_pending() == []
        assert not r.stalled
        r.submit(_mk_cycle(2, "ok"))
        c = r.wait_completed(5.0)
        assert c is not None and c.outcome == ("done", "ok")
    finally:
        r.stop()


def test_runner_parks_exception_for_drain():
    def run_fn(plan, bufs):
        raise ConnectionError("wire died")

    r = hoverlap.OverlapRunner(run_fn, max_inflight=2)
    try:
        r.submit(_mk_cycle(0))
        c = r.wait_completed(5.0)
        assert c is not None
        kind, err = c.outcome
        assert kind == "error" and isinstance(err, ConnectionError)
        assert r.stalled
    finally:
        r.stop()


def test_runner_same_plan_exclusion():
    """A plan whose arena views are on the wire must not be repacked:
    submit blocks until the first cycle of the same plan is DRAINED."""
    release = threading.Event()

    def run_fn(plan, bufs):
        release.wait(5.0)
        return ("done", None)

    r = hoverlap.OverlapRunner(run_fn, max_inflight=4)
    try:
        plan = object()
        r.submit(_mk_cycle(0, plan))
        blocked = threading.Event()
        submitted = threading.Event()

        def second():
            blocked.set()
            r.submit(_mk_cycle(1, plan))
            submitted.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        blocked.wait(5.0)
        assert not submitted.wait(0.3)  # still excluded
        release.set()
        c = r.wait_completed(5.0)   # drain the first cycle
        assert c is not None
        assert submitted.wait(5.0)  # now the second went through
        c = r.wait_completed(5.0)
        assert c is not None
        t.join(5.0)
    finally:
        r.stop()


def test_runner_stop_returns_leftovers():
    hold = threading.Event()

    def run_fn(plan, bufs):
        hold.wait(0.5)
        return ("done", None)

    r = hoverlap.OverlapRunner(run_fn, max_inflight=4)
    r.submit(_mk_cycle(0, "a"))
    r.submit(_mk_cycle(1, "b"))
    r.submit(_mk_cycle(2, "c"))
    hold.set()
    leftovers = r.stop()
    # everything undrained comes back (pending and/or completed)
    assert len(leftovers) == 3


# -- tuned trailer + overlap tuner ---------------------------------------
def test_response_list_trailer_roundtrip():
    from horovod_tpu.common import wire
    from horovod_tpu.common.message import ResponseList

    rl = ResponseList([], shutdown=False, tuned_cycle_time_ms=3.5,
                      tuned_fusion_threshold_bytes=1 << 20,
                      tuned_overlap_buckets=4)
    out = wire.parse_response_list(wire.serialize_response_list(rl))
    assert out.tuned_overlap_buckets == 4
    assert out == rl
    rl2 = ResponseList([])
    out2 = wire.parse_response_list(wire.serialize_response_list(rl2))
    assert out2.tuned_overlap_buckets == -1  # no-verdict sentinel


def test_overlap_tuner_settles_argmax():
    from horovod_tpu.common.parameter_manager import _OverlapTuner

    t = _OverlapTuner([0, 2, 4])
    score = {0: 1.0, 2: 5.0, 4: 3.0}
    while not t.done:
        t.feed(score[t.current()], traffic=100)
    assert t.choice == 2


def test_overlap_tuner_ignores_lulls():
    from horovod_tpu.common.parameter_manager import _OverlapTuner

    t = _OverlapTuner([0, 2])
    cur = t.current()
    t.feed(9.0, traffic=0)  # global lull: not a measurement
    assert t.current() == cur and not t.done


def test_parameter_manager_overlap_gating():
    """The overlap grid only measures after the wire sweep settles,
    workers adopt the trailer value, and spec stays safe while the
    overlap grid runs."""
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.parameter_manager import ParameterManager

    class _Ctl:
        rank = 0

    cfg = Config()
    cfg.autotune = True
    pm = ParameterManager(cfg, _Ctl())
    pm.configure_overlap(True)
    assert pm.overlap_buckets() in (0, 2, 4, 8)
    assert pm.spec_safe  # overlap grid needs live speculation
    assert pm.tuned_overlap_buckets >= 0

    class _Ctl1:
        rank = 1

    worker = ParameterManager(cfg, _Ctl1())
    assert worker.overlap_buckets() is None
    worker.apply_synced(1 << 20, 2.0, overlap_buckets=4)
    assert worker.overlap_buckets() == 4
    worker.apply_synced(1 << 20, 2.0, overlap_buckets=-1)
    assert worker.overlap_buckets() == 4  # sentinel never clears


# -- chunked pipelined transfer ------------------------------------------
def _native_lib():
    from horovod_tpu import native as _nat
    lib = _nat.get()
    if lib is None or not hasattr(lib, "hvd_steady_worker_chunked"):
        pytest.skip("native core unavailable")
    return lib


def test_steady_plan_defers_cast_when_chunked():
    from horovod_tpu.common import wire_dtype as _wd
    from horovod_tpu.common.arena import FusionArena
    from horovod_tpu.common.message import DataType
    from horovod_tpu.common.steady import SteadyPlan

    _native_lib()
    n = 64
    segments = [(_wd.wire_datatype(_wd.WIRE_BF16),
                 _wd.wire_np_dtype(_wd.WIRE_BF16), n * 2, np.float32)]
    plan = SteadyPlan(1, 64, 0b1, segments, FusionArena(),
                      chunk_bytes=32)
    assert plan.chunked
    arrays = [np.linspace(-3, 3, n, dtype=np.float32)]
    plan.send_views[0].view(np.uint8)[:] = 0xEE  # sentinel
    bufs = plan.pack([arrays], [1.0])
    # the cast was DEFERRED: staging filled, wire view untouched
    np.testing.assert_array_equal(plan.stage_views[0], arrays[0])
    assert (plan.send_views[0].view(np.uint8) == 0xEE).all()
    # materialize_wire produces exactly the direct-cast bytes
    plan.materialize_wire()
    expect = np.empty(n, _wd.wire_np_dtype(_wd.WIRE_BF16))
    _wd.cast_into(arrays[0], expect)
    np.testing.assert_array_equal(
        plan.send_views[0].view(np.uint8), expect.view(np.uint8))
    assert bufs[0] is plan.send_views[0]


def test_steady_plan_chunk_gate_rejects_unsupported_cast_pairs():
    """hvd_cast only speaks f32<->bf16/f16: a float64-source
    compressed segment must NOT arm the chunked worker (the chunk
    loop would -EINVAL mid-frame and abort a healthy world) — it
    keeps the Python cast + classic one-shot send instead."""
    from horovod_tpu.common import wire_dtype as _wd
    from horovod_tpu.common.arena import FusionArena
    from horovod_tpu.common.steady import SteadyPlan

    _native_lib()
    n = 32
    f64_seg = [(_wd.wire_datatype(_wd.WIRE_BF16),
                _wd.wire_np_dtype(_wd.WIRE_BF16), n * 2, np.float64)]
    plan = SteadyPlan(1, 64, 0b1, f64_seg, FusionArena(),
                      chunk_bytes=64)
    assert not plan.chunked
    # ...and pack still produces correct wire bytes via the fallback
    arrays = [np.linspace(-1, 1, n, dtype=np.float64)]
    bufs = plan.pack([arrays], [1.0])
    expect = np.empty(n, _wd.wire_np_dtype(_wd.WIRE_BF16))
    _wd.cast_into(arrays[0], expect)
    np.testing.assert_array_equal(
        bufs[0].view(np.uint8), expect.view(np.uint8))
    # the supported pair still arms
    f32_seg = [(_wd.wire_datatype(_wd.WIRE_BF16),
                _wd.wire_np_dtype(_wd.WIRE_BF16), n * 2, np.float32)]
    assert SteadyPlan(1, 64, 0b1, f32_seg, FusionArena(),
                      chunk_bytes=64).chunked


@pytest.mark.parametrize("secret", [b"", b"shared-key"])
def test_chunked_worker_wire_parity(secret):
    """hvd_steady_worker_chunked must put byte-identical frames on
    the wire (chunking only reschedules the cast): capture its
    request frame over a socketpair and compare against the classic
    serialized frame; reply with a valid response so the cycle
    completes DONE."""
    import ctypes

    from horovod_tpu.common import steady as hsteady
    from horovod_tpu.common import wire_dtype as _wd
    from horovod_tpu.common.arena import FusionArena
    from horovod_tpu.common.message import DataType
    from horovod_tpu.common.steady import SteadyPlan

    lib = _native_lib()
    n = 256
    segments = [
        (_wd.wire_datatype(_wd.WIRE_BF16),
         _wd.wire_np_dtype(_wd.WIRE_BF16), n * 2, np.float32),
        (DataType.FLOAT32, np.float32, n * 4, None),
    ]
    plan = SteadyPlan(7, 64, 0b11, segments, FusionArena(),
                      chunk_bytes=100)  # forces several chunks
    assert plan.chunked
    comp = np.linspace(-2, 2, n, dtype=np.float32)
    raw = np.linspace(5, 6, n, dtype=np.float32)
    bufs = plan.pack([[comp], [raw]], [1.0, 1.0])

    # classic bytes: clone plan without chunking, same data
    ref = SteadyPlan(7, 64, 0b11, segments, FusionArena())
    ref_bufs = ref.pack([[comp], [raw]], [1.0, 1.0])
    classic = ref.frame_bytes(ref_bufs)

    a, b = socket.socketpair()
    captured = {}

    def peer():
        want = 5 + (32 if secret else 0) + plan.payload_nbytes
        buf = b""
        while len(buf) < want:
            chunk = b.recv(want - len(buf))
            if not chunk:
                break
            buf += chunk
        captured["frame"] = buf
        payload = buf[5 + (32 if secret else 0):]
        # echo a valid response frame (tag 3) with the same payload
        hdr = len(payload).to_bytes(4, "little") + bytes([3])
        out = hdr
        if secret:
            import hashlib
            import hmac as _hmac
            out += _hmac.new(secret, bytes([3]) + payload,
                             hashlib.sha256).digest()
        b.sendall(out + payload)

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    kind, val = hsteady.run_worker_cycle(
        lib, plan, a.fileno(), secret, bufs, b"", 2, 3, (5.0, 0.1))
    t.join(5.0)
    a.close()
    b.close()
    assert kind == hsteady.DONE, (kind, val)
    payload = captured["frame"][5 + (32 if secret else 0):]
    assert payload == classic  # byte-identical wire format
    # the echoed "world result" round-trips into typed segments
    (dt0, seg0), (dt1, seg1) = val
    np.testing.assert_array_equal(
        seg0.view(np.uint8), ref_bufs[0].view(np.uint8))
    np.testing.assert_array_equal(seg1, ref_bufs[1])


# -- satellite regressions ----------------------------------------------
def test_unpack_frames_truncation_raises_connection_error():
    """Every prefix cut of a packed aggregate must raise
    ConnectionError — never a raw struct.error escaping the relay
    error handling (ADVICE r05)."""
    blob = pack_frames([b"alpha", b"", b"gamma" * 7])
    assert unpack_frames(blob) == [b"alpha", b"", b"gamma" * 7]
    for cut in range(len(blob)):
        with pytest.raises(ConnectionError):
            unpack_frames(blob[:cut])
    with pytest.raises(ConnectionError):
        unpack_frames(blob + b"x")  # trailing garbage too


def test_dialable_leaf_ip_loopback_families():
    assert not _dialable_leaf_ip("127.0.0.1")
    assert not _dialable_leaf_ip("127.8.9.10")
    assert not _dialable_leaf_ip("::1")  # IPv6 loopback (ADVICE r05)
    assert _dialable_leaf_ip("10.0.0.5")
    assert _dialable_leaf_ip("fe80::1")
    assert not _dialable_leaf_ip("not-an-ip")


def test_ragged_psum_guard_int32_boundary():
    """ >= 2^31 assembled psum elements must route to the padded
    path: a 32-bit offset would silently wrap (ADVICE r05). At the
    boundary the skew is extreme, so without the guard psum wins."""
    from horovod_tpu.ops.xla_ops import ragged_psum_wins

    ws = 8
    # Small case with the same skew shape: psum wins (sanity).
    small = [1000] + [1] * (ws - 1)
    assert ragged_psum_wins(small, [1], ws)
    # Scale rows so psum_elems = sum(rows) + max crosses 2^31.
    big = 2**30
    rows = [big] + [1] * (ws - 1)
    assert ragged_psum_wins(rows, [1], ws) is False
    # Just under the boundary with identical skew: still allowed.
    under = [2**29] + [1] * (ws - 1)
    assert ragged_psum_wins(under, [1], ws) is True
