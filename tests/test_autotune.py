"""Autotuner tests (reference analog: the reference has no dedicated
autotune tests; we cover the GP/EI machinery and the ParameterManager
sampling loop directly — reference: horovod/common/parameter_manager.cc,
optim/bayesian_optimization.cc)."""

import numpy as np

from horovod_tpu.common.config import Config
from horovod_tpu.common.controller import LocalController
from horovod_tpu.common.parameter_manager import ParameterManager
from horovod_tpu.optim.bayesian_optimization import BayesianOptimization
from horovod_tpu.optim.gaussian_process import GaussianProcessRegressor


class TestGaussianProcess:
    def test_fit_predict_interpolates(self):
        gp = GaussianProcessRegressor(alpha=1e-10)
        x = np.array([[0.0], [0.5], [1.0]])
        y = np.array([0.0, 1.0, 0.0])
        gp.fit(x, y)
        mean, std = gp.predict(x)
        np.testing.assert_allclose(mean, y, atol=1e-4)
        assert np.all(std < 1e-2)

    def test_predict_without_fit(self):
        gp = GaussianProcessRegressor()
        mean, std = gp.predict(np.array([[0.3]]))
        assert mean[0] == 0.0
        assert std[0] > 0


class TestBayesianOptimization:
    def test_finds_peak_of_smooth_function(self):
        # maximize -(x-0.7)^2 on [0, 1]
        bo = BayesianOptimization(bounds=[(0.0, 1.0)], alpha=1e-6, seed=1)
        x = bo.next_sample()
        for _ in range(20):
            y = -(float(x[0]) - 0.7) ** 2
            bo.add_sample(x, y)
            x = bo.next_sample()
        best, score = bo.best()
        assert abs(best[0] - 0.7) < 0.15

    def test_respects_bounds(self):
        bo = BayesianOptimization(bounds=[(2.0, 4.0), (10.0, 20.0)], seed=0)
        for _ in range(5):
            x = bo.next_sample()
            assert 2.0 <= x[0] <= 4.0
            assert 10.0 <= x[1] <= 20.0
            bo.add_sample(x, float(np.sum(x)))

    def test_lbfgs_refinement_beats_candidate_sweep(self):
        """The L-BFGS acquisition maximization (reference:
        bayesian_optimization.cc + third_party/lbfgs) must return a
        point whose EI is at least the best of the random sweep, and
        refine it when the optimum falls between candidates."""
        bo = BayesianOptimization(bounds=[(0.0, 64.0), (1.0, 100.0)],
                                  alpha=1e-6, seed=3)
        rng = np.random.RandomState(0)
        for _ in range(12):
            x = np.array([rng.uniform(0, 64), rng.uniform(1, 100)])
            y = -((x[0] - 20.0) / 32.0) ** 2 - ((x[1] - 60.0) / 50.0) ** 2
            bo.add_sample(x, y)
        bo._gp.fit(np.stack(bo._xs), np.asarray(bo._ys))
        cand = bo._rng.uniform(size=(2048, bo.dim))
        ei = bo._expected_improvement(cand)
        refined, refined_ei = bo._maximize_ei(cand, ei)
        assert refined is not None, "scipy present -> refinement runs"
        assert refined_ei >= float(ei.max()) - 1e-12
        assert np.all(refined >= 0.0) and np.all(refined <= 1.0)
        # refinement power: from a deliberately coarse sweep whose
        # candidates all miss the acquisition peak, L-BFGS must find a
        # strictly better point than any candidate
        coarse = bo._rng.uniform(size=(4, bo.dim))
        coarse_ei = bo._expected_improvement(coarse)
        ref2, ref2_ei = bo._maximize_ei(coarse, coarse_ei, n_starts=4)
        assert ref2 is not None
        assert ref2_ei > float(coarse_ei.max()), \
            (ref2_ei, float(coarse_ei.max()))
        # next_sample returns in-bounds denormalized coords
        nxt = bo.next_sample()
        assert 0.0 <= nxt[0] <= 64.0 and 1.0 <= nxt[1] <= 100.0


class TestParameterManager:
    def _make(self, tmp_path=None):
        cfg = Config()
        cfg.autotune = True
        cfg.autotune_warmup_samples = 1
        cfg.autotune_steps_per_sample = 2
        cfg.autotune_bayes_opt_max_samples = 4
        if tmp_path is not None:
            cfg.autotune_log = str(tmp_path / "autotune.csv")
        return ParameterManager(cfg, LocalController())

    def test_tunes_then_converges(self, tmp_path):
        pm = self._make(tmp_path)
        initial = (pm.fusion_threshold_bytes(), pm.cycle_time_ms())
        assert pm.tuning
        # drive enough cycles: warmup 1 sample + 4 samples × 3 medians,
        # 2 cycles each
        for _ in range(2 * (1 + 4 * 3) + 4):
            pm.on_cycle(1 << 20)
        assert not pm.tuning
        assert 0 <= pm.fusion_threshold_bytes() <= 64 << 20
        assert 1.0 <= pm.cycle_time_ms() <= 100.0
        log = (tmp_path / "autotune.csv").read_text().strip().splitlines()
        assert log[0].startswith("sample,")
        assert len(log) == 5  # header + 4 samples

    def test_worker_applies_synced_params(self):
        cfg = Config()
        cfg.autotune = True

        class _W:
            rank = 1
        pm = ParameterManager(cfg, _W())
        pm.apply_synced(32 << 20, 7.5)
        assert pm.fusion_threshold_bytes() == 32 << 20
        assert pm.cycle_time_ms() == 7.5
        # a tuned fusion threshold of 0 MB (fusion off) is legitimate
        # and must be adopted — only cycle_time 0 marks an untuned
        # trailer (regression: the 0-threshold sentinel collision)
        pm.apply_synced(0, 100.0)
        assert pm.fusion_threshold_bytes() == 0
        assert pm.cycle_time_ms() == 100.0
        before = pm.fusion_threshold_bytes(), pm.cycle_time_ms()
        pm.apply_synced(0, 0.0)  # untuned trailer: ignored
        assert (pm.fusion_threshold_bytes(), pm.cycle_time_ms()) == before
