"""Framework adapter tests (flax in-jit; keras/TF size-1 host path).

Multi-rank adapter behavior is covered by mp_scenarios
(torch_optimizer, jax_adapter, keras_optimizer, tf_tape)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu.compat import jaxshim


# ---------------------------------------------------------------------------
# flax
# ---------------------------------------------------------------------------

def test_flax_distributed_train_state_syncs_grads(hvd_world):
    """Two different per-device batches, replicated params: the wrapped
    tx must produce identical (averaged) updates on every device."""
    import optax
    from horovod_tpu import spmd
    import horovod_tpu.flax as hvd_flax
    from horovod_tpu.models import MnistConvNet

    mesh = spmd.create_mesh({"data": 8})
    model = MnistConvNet()
    x0 = jnp.zeros((8, 28, 28, 1))
    params = model.init(jax.random.key(0), x0)["params"]

    state = hvd_flax.create_distributed_train_state(
        model.apply, params, optax.sgd(0.1))

    def step(s, batch, labels):
        def loss_fn(p):
            logits = s.apply_fn({"params": p}, batch)
            oh = jax.nn.one_hot(labels, 10)
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * oh, axis=-1))
        grads = jax.grad(loss_fn)(s.params)
        return s.apply_gradients(grads=grads)

    smap = jax.jit(jaxshim.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P("data"), P("data")), out_specs=P()))

    rng = np.random.RandomState(0)
    batch = jnp.asarray(rng.randn(16, 28, 28, 1), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, 16), jnp.int32)
    new_state = smap(state, batch, labels)
    # out_specs=P() asserts the updated params are identical across
    # devices — that only holds if the tx averaged the per-device grads.
    leaves = jax.tree_util.tree_leaves(new_state.params)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    # and the params actually moved
    moved = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        state.params, new_state.params)
    assert max(jax.tree_util.tree_leaves(moved)) > 0


def test_flax_average_metrics_size1(hvd_world):
    import horovod_tpu.flax as hvd_flax
    out = hvd_flax.average_metrics({"loss": 2.0, "acc": 0.5})
    assert out == {"loss": 2.0, "acc": 0.5}


def test_flax_scaled_lr_schedule():
    import horovod_tpu.flax as hvd_flax
    sched = hvd_flax.scaled_lr_schedule(0.1, warmup_steps=10,
                                        world_size=4)
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(10)) == pytest.approx(0.4)
    flat = hvd_flax.scaled_lr_schedule(0.1, warmup_steps=0, world_size=8)
    assert float(flat(123)) == pytest.approx(0.8)


# ---------------------------------------------------------------------------
# keras
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def keras_mod():
    keras = pytest.importorskip("keras")
    return keras


def _tiny_keras_model(keras):
    model = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(3, activation="relu"),
        keras.layers.Dense(2),
    ])
    return model


def test_keras_distributed_optimizer_trains(hvd_world, keras_mod):
    import horovod_tpu.keras as hvd_keras
    keras = keras_mod
    model = _tiny_keras_model(keras)
    opt = hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.05))
    assert opt.__class__.__name__ == "SGD"
    model.compile(optimizer=opt, loss="mse")
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(16, 2).astype(np.float32)
    h = model.fit(x, y, epochs=2, batch_size=8, verbose=0)
    assert h.history["loss"][1] < h.history["loss"][0] * 1.5


def test_keras_broadcast_and_callbacks(hvd_world, keras_mod):
    import horovod_tpu.keras as hvd_keras
    keras = keras_mod
    model = _tiny_keras_model(keras)
    model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
    w0 = model.get_weights()
    hvd_keras.broadcast_global_variables(model, root_rank=0)
    for a, b in zip(w0, model.get_weights()):
        np.testing.assert_allclose(a, b)

    cb = hvd_keras.callbacks.MetricAverageCallback()
    cb.set_model(model)
    logs = {"loss": 3.0}
    cb.on_epoch_end(0, logs)
    assert logs["loss"] == pytest.approx(3.0)  # size-1 world

    bcast = hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0)
    bcast.set_model(model)
    bcast.on_batch_begin(0)
    assert bcast.broadcast_done


def test_keras_warmup_callback_ramps(hvd_world, keras_mod):
    import horovod_tpu.keras as hvd_keras
    keras = keras_mod
    model = _tiny_keras_model(keras)
    model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
    cb = hvd_keras.callbacks.LearningRateWarmupCallback(warmup_epochs=5)
    cb.set_model(model)
    cb.set_params({"steps": 2})
    # size-1 world: multiplier is identically 1.0 → lr unchanged
    cb.on_epoch_begin(0)
    cb.on_batch_begin(0)
    assert float(np.asarray(model.optimizer.learning_rate)) == \
        pytest.approx(0.1)
    # the multiplier math itself ramps 1 → size
    assert cb.multiplier(0) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# tensorflow
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tf_mod():
    tf = pytest.importorskip("tensorflow")
    return tf


def test_tf_ops_size1(hvd_world, tf_mod):
    import horovod_tpu.tensorflow as hvd_tf
    tf = tf_mod
    x = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    out = hvd_tf.allreduce(x, op=hvd_tf.Sum)
    np.testing.assert_allclose(out.numpy(), x.numpy())
    out = hvd_tf.broadcast(x, root_rank=0)
    np.testing.assert_allclose(out.numpy(), x.numpy())
    out = hvd_tf.allgather(x)
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_tf_indexed_slices_sparse_path(hvd_world, tf_mod):
    import horovod_tpu.tensorflow as hvd_tf
    tf = tf_mod
    slices = tf.IndexedSlices(
        values=tf.constant([[1.0, 2.0]]), indices=tf.constant([3]),
        dense_shape=tf.constant([8, 2]))
    out = hvd_tf.allreduce(slices, op=hvd_tf.Average)
    assert isinstance(out, tf.IndexedSlices)
    np.testing.assert_allclose(out.values.numpy(), [[1.0, 2.0]])
    np.testing.assert_allclose(out.indices.numpy(), [3])


def test_tf_distributed_gradient_tape(hvd_world, tf_mod):
    import horovod_tpu.tensorflow as hvd_tf
    tf = tf_mod
    v = tf.Variable([1.0, 2.0])
    with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * v)
    grads = tape.gradient(loss, [v])
    np.testing.assert_allclose(grads[0].numpy(), [2.0, 4.0])


def test_tf_broadcast_variables(hvd_world, tf_mod):
    import horovod_tpu.tensorflow as hvd_tf
    tf = tf_mod
    v = tf.Variable([5.0, 6.0])
    hvd_tf.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [5.0, 6.0])


# ---------------------------------------------------------------------------
# mxnet (real wheel, optional)
# ---------------------------------------------------------------------------

def test_mxnet_real_wheel(hvd_world):
    """Exercise the MXNet adapter against a REAL mxnet wheel when one
    is importable. No wheel exists for TPU images, so this leg skips
    VISIBLY there — the skip message is the honest record that
    real-NDArray semantics (dtype promotion, views, engine-deferred
    init) are otherwise validated only by the protocol double
    (tests/fake_mxnet.py; see docs/parity.md). With a wheel present it
    validates the round-trip the double cannot: adapter outputs must be
    genuine mx.nd.NDArrays that the engine accepts downstream."""
    mx = pytest.importorskip(
        "mxnet",
        reason="no real mxnet wheel on this image - MXNet adapter "
               "semantics validated only against the NDArray-protocol "
               "double (tests/fake_mxnet.py); see docs/parity.md")
    import horovod_tpu.mxnet as hmx

    x = mx.nd.array(np.arange(6, dtype=np.float32))
    out = hmx.allreduce(x, average=False, name="mxreal.ar")
    assert isinstance(out, mx.nd.NDArray)
    np.testing.assert_allclose(out.asnumpy(), np.arange(6))
    # engine accepts the result downstream (not just a protocol look-alike)
    np.testing.assert_allclose((out * 2).asnumpy(), np.arange(6) * 2)

    hmx.allreduce_(x, average=True, name="mxreal.ar_")
    np.testing.assert_allclose(x.asnumpy(), np.arange(6))

    g = hmx.allgather(mx.nd.array(np.ones((2, 3), np.float32)),
                      name="mxreal.ag")
    assert isinstance(g, mx.nd.NDArray) and g.shape == (2, 3)

    b = hmx.broadcast(mx.nd.array(np.full(4, 7.0, np.float64)),
                      root_rank=0, name="mxreal.bc")
    assert b.dtype == np.float64
    np.testing.assert_allclose(b.asnumpy(), 7.0)

    params = {"w": mx.nd.zeros((3,)), "b": mx.nd.ones((2,))}
    hmx.broadcast_parameters(params, root_rank=0)
    opt = hmx.DistributedOptimizer(mx.optimizer.SGD(learning_rate=0.1))
    w = mx.nd.ones((3,))
    grad = mx.nd.full((3,), 2.0)
    state = opt.create_state(0, w)
    opt.update(0, w, grad, state)
    np.testing.assert_allclose(w.asnumpy(), 1.0 - 0.1 * 2.0, rtol=1e-5)
