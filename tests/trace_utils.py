"""Shared Chrome-trace parsing for timeline assertions (used by
tests/test_async_completion.py and tests/mp_scenarios.py — one copy so
span-format changes cannot silently diverge the two)."""

import json


def load_trace(path):
    """Returns (events, by_tensor_name) with metadata events dropped
    from the per-tensor groups."""
    with open(path) as f:
        events = json.load(f)
    pid_names = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    by_name = {}
    for e in events:
        if e.get("ph") == "M":
            continue
        by_name.setdefault(pid_names.get(e.get("pid")), []).append(e)
    return events, by_name


def collective_span(evts):
    """(start_ts, end_ts) of a tensor's async-nestable COLLECTIVE span
    (ph b/e paired by id)."""
    b = next(e for e in evts
             if e["ph"] == "b" and e.get("name") == "COLLECTIVE")
    e_ = next(e for e in evts
              if e["ph"] == "e" and e.get("name") == "COLLECTIVE"
              and e.get("id") == b["id"])
    return b["ts"], e_["ts"]


def negotiate_start_ts(evts, op: str = "ALLREDUCE"):
    """ts of the tensor's NEGOTIATE_<op> begin event."""
    return next(e["ts"] for e in evts
                if e["ph"] == "B"
                and e.get("name") == f"NEGOTIATE_{op}")
