"""The shared steady-state timing helper used by bench.py and the
examples: warmup runs first and is excluded; chunks are timed with a
sync per chunk; the median chunk is reported."""

import time

from horovod_tpu.utils.timing import steady_state_sec_per_step


def test_warmup_excluded_and_median_reported():
    calls = []

    def step():
        calls.append(time.perf_counter())
        # first 3 calls (warmup) artificially slow
        if len(calls) <= 3:
            time.sleep(0.05)
        else:
            time.sleep(0.002)
        return len(calls)

    synced = []
    sec = steady_state_sec_per_step(
        step, synced.append, warmup_steps=3, chunks=3, chunk_steps=4)
    assert len(calls) == 3 + 3 * 4
    # one sync per chunk plus the warmup sync
    assert len(synced) == 1 + 3
    # the slow warmup never pollutes the measurement
    assert 0.0015 < sec < 0.02, sec


def test_degenerate_counts_clamped():
    n = {"v": 0}

    def step():
        n["v"] += 1
        return n["v"]

    sec = steady_state_sec_per_step(step, lambda r: None,
                                    warmup_steps=0, chunks=0,
                                    chunk_steps=0)
    assert n["v"] == 1  # warmup 0 honored (cold start); 1 chunk of 1
    assert sec >= 0.0
